"""Light client package: follow the chain through sync-committee updates.

Reference: packages/light-client/src/index.ts:110 (Lightclient class) and
its spec core (processLightClientUpdate / validateLightClientUpdate).
"""

from .client import LightClient, LightClientError  # noqa: F401
