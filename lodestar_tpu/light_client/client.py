"""LightClient: header tracking via validated sync-committee updates.

Reference: packages/light-client/src/index.ts:110 with the altair sync
protocol semantics: an update is valid when (1) its sync aggregate has
enough participation, (2) the aggregate signature by the KNOWN sync
committee verifies over the attested header, (3) the merkle branches tie
the next sync committee and finalized header into the attested state
root.  Applying a finalized update advances the store's finalized header
and rotates committees across periods.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from ..config.chain_config import ChainConfig
from ..params import DOMAIN_SYNC_COMMITTEE, Preset
from ..ssz import Fields
from ..state_transition import compute_domain, compute_epoch_at_slot
from ..types import get_types
from ..utils.logger import get_logger

logger = get_logger("light-client")


class LightClientError(Exception):
    pass


def _verify_branch(leaf: bytes, branch, index_in_container: int, root: bytes) -> bool:
    """is_valid_merkle_branch over a bottom-up sibling list for a field at
    position `index_in_container` of the (padded) container tree; for the
    finality branch the caller pre-composes the deeper path."""
    h = leaf
    idx = index_in_container
    for sib in branch:
        if idx & 1:
            h = hashlib.sha256(bytes(sib) + h).digest()
        else:
            h = hashlib.sha256(h + bytes(sib)).digest()
        idx //= 2
    return h == root


class LightClient:
    def __init__(self, preset: Preset, cfg: ChainConfig, bootstrap,
                 genesis_validators_root: bytes):
        self.p = preset
        self.cfg = cfg
        self.t = get_types(preset)
        self.gvr = genesis_validators_root
        from ..config.fork_config import ForkConfig

        self.fork_config = ForkConfig(cfg)
        self.finalized_header = bootstrap.header
        self.optimistic_header = bootstrap.header
        self.current_sync_committee = bootstrap.current_sync_committee
        self.next_sync_committee = None
        # verify the bootstrap proof against the trusted header state root
        st_alt = self.t.altair
        leaf = st_alt.SyncCommittee.hash_tree_root(bootstrap.current_sync_committee)
        idx = self._field_index("current_sync_committee")
        if not _verify_branch(
            leaf, bootstrap.current_sync_committee_branch, idx,
            bytes(bootstrap.header.state_root),
        ):
            raise LightClientError("invalid bootstrap sync committee proof")

    def _sync_period(self, slot: int) -> int:
        return (
            compute_epoch_at_slot(self.p, slot)
            // self.p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )

    def _field_index(self, name: str) -> int:
        fields = [f for f, _ in self.t.altair.BeaconState.fields]
        return fields.index(name)

    # -- update processing (processLightClientUpdate) --------------------------

    def process_update(self, update) -> None:
        agg = update.sync_aggregate
        participation = sum(agg.sync_committee_bits)
        if participation * 3 < len(agg.sync_committee_bits) * 2:
            raise LightClientError("insufficient sync committee participation")
        attested = update.attested_header
        state_root = bytes(attested.state_root)

        # next sync committee proof
        st_alt = self.t.altair
        nsc_leaf = st_alt.SyncCommittee.hash_tree_root(update.next_sync_committee)
        if not _verify_branch(
            nsc_leaf, update.next_sync_committee_branch,
            self._field_index("next_sync_committee"), state_root,
        ):
            raise LightClientError("invalid next_sync_committee branch")

        # finality proof (when a finalized header is claimed)
        finalized = update.finalized_header
        if finalized.slot != 0 or bytes(finalized.state_root) != b"\x00" * 32:
            fin_root = self.t.phase0.BeaconBlockHeader.hash_tree_root(finalized)
            # path: root within Checkpoint (index 1), checkpoint in state
            idx = 1 + 2 * self._field_index("finalized_checkpoint")
            if not _verify_branch(fin_root, update.finality_branch, idx, state_root):
                raise LightClientError("invalid finality branch")

        # sync aggregate signature over the attested header under
        # DOMAIN_SYNC_COMMITTEE.  The signing committee is the one of the
        # SIGNATURE slot's period: the store's current committee for a
        # same-period update, the proven next committee for the update
        # that crosses into the following period (spec
        # validate_light_client_update committee selection).  The fork
        # version is derived from OUR fork schedule at the signature slot —
        # trusting update.fork_version would let a malicious server pick
        # whichever domain it likes (ADVICE r3)
        from ..crypto.bls.api import PublicKey
        from ..state_transition.altair import eth_fast_aggregate_verify

        store_period = self._sync_period(self.finalized_header.slot)
        sig_slot = attested.slot + 1
        sig_period = self._sync_period(sig_slot)
        if sig_period == store_period:
            committee = self.current_sync_committee
        elif sig_period == store_period + 1 and self.next_sync_committee is not None:
            committee = self.next_sync_committee
        else:
            raise LightClientError(
                f"update signature period {sig_period} outside known committees"
                f" (store period {store_period})"
            )
        # spec validate_light_client_update: fork version at
        # epoch(max(signature_slot, 1) - 1) — the aggregate is signed with
        # the domain of the slot BEFORE the signature slot, so an update
        # straddling a fork activation must use the pre-fork version
        sig_epoch = compute_epoch_at_slot(self.p, max(sig_slot, 1) - 1)
        fork_version = self.fork_config.get_fork_version(sig_epoch)
        domain = compute_domain(
            self.p, DOMAIN_SYNC_COMMITTEE, fork_version, self.gvr
        )
        signing_root = self.t.phase0.SigningData.hash_tree_root(
            Fields(
                object_root=self.t.phase0.BeaconBlockHeader.hash_tree_root(attested),
                domain=domain,
            )
        )
        pks = [
            PublicKey.from_bytes(bytes(pk))
            for pk, bit in zip(committee.pubkeys, agg.sync_committee_bits)
            if bit
        ]
        if not eth_fast_aggregate_verify(
            pks, signing_root, bytes(agg.sync_committee_signature)
        ):
            raise LightClientError("invalid sync aggregate signature")

        # apply (spec apply_light_client_update): a finalized header
        # crossing into the next period rotates next->current and installs
        # the update's own proven next committee; advancing more than one
        # period at a time, or crossing without a known next committee,
        # would leave the store without the committee needed to verify
        # anything afterwards — reject instead of desyncing silently.
        attested_period = self._sync_period(attested.slot)
        if attested.slot > self.optimistic_header.slot:
            self.optimistic_header = attested
        if finalized.slot > self.finalized_header.slot:
            new_period = self._sync_period(finalized.slot)
            if new_period == store_period + 1:
                if self.next_sync_committee is None:
                    raise LightClientError("period rotation without known next committee")
                self.current_sync_committee = self.next_sync_committee
                # the update's next committee is proven against the attested
                # state; it names new_period's successor only when the
                # attested header itself sits in new_period
                self.next_sync_committee = (
                    update.next_sync_committee if attested_period == new_period else None
                )
            elif new_period > store_period + 1:
                raise LightClientError("update skips a sync-committee period")
            self.finalized_header = finalized
        if attested_period == store_period and self.next_sync_committee is None:
            self.next_sync_committee = update.next_sync_committee
        logger.info(
            "light client advanced: optimistic slot %d, finalized slot %d",
            self.optimistic_header.slot, self.finalized_header.slot,
        )
