"""LightClient: spec LightClientStore over validated sync-committee updates.

Reference: packages/light-client/src/index.ts:110 with the altair sync
protocol semantics: an update is valid when (1) its sync aggregate has
enough participation, (2) the aggregate signature by the KNOWN sync
committee verifies over the attested header, (3) the merkle branches tie
the next sync committee and finalized header into the attested state
root.  The store additionally keeps the best-seen valid update per spec
(`best_valid_update`) so `force_update` can advance past a period whose
updates never reached finality (forced committee advance —
light-client/src/index.ts:110 subscribes and forces on timeout), and an
optimistic header gated by the safety threshold
(max active participants across the last two periods / 2).

Finality and optimistic updates (the head-following routes,
api/src/beacon/routes/lightclient.ts:60) are processed with the same
validator — they are updates without a next-sync-committee proof.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from ..config.chain_config import ChainConfig
from ..params import DOMAIN_SYNC_COMMITTEE, Preset
from ..ssz import Fields
from ..state_transition import compute_domain, compute_epoch_at_slot
from ..types import get_types
from ..utils.logger import get_logger

logger = get_logger("light-client")


class LightClientError(Exception):
    pass


def _verify_branch(leaf: bytes, branch, index_in_container: int, root: bytes) -> bool:
    """is_valid_merkle_branch over a bottom-up sibling list for a field at
    position `index_in_container` of the (padded) container tree; for the
    finality branch the caller pre-composes the deeper path."""
    h = leaf
    idx = index_in_container
    for sib in branch:
        if idx & 1:
            h = hashlib.sha256(bytes(sib) + h).digest()
        else:
            h = hashlib.sha256(h + bytes(sib)).digest()
        idx //= 2
    return h == root


def _has_sync_committee(update) -> bool:
    try:
        return update.next_sync_committee is not None and bool(
            update.next_sync_committee_branch
        )
    except (AttributeError, KeyError):
        return False


def _has_finality(update) -> bool:
    try:
        fin = update.finalized_header
    except (AttributeError, KeyError):
        return False
    return fin is not None and (
        fin.slot != 0 or bytes(fin.state_root) != b"\x00" * 32
    )


class LightClient:
    def __init__(self, preset: Preset, cfg: ChainConfig, bootstrap,
                 genesis_validators_root: bytes):
        self.p = preset
        self.cfg = cfg
        self.t = get_types(preset)
        self.gvr = genesis_validators_root
        from ..config.fork_config import ForkConfig

        self.fork_config = ForkConfig(cfg)
        self.finalized_header = bootstrap.header
        self.optimistic_header = bootstrap.header
        self.current_sync_committee = bootstrap.current_sync_committee
        self.next_sync_committee = None
        # spec LightClientStore tail: candidate update for forced advance +
        # participation watermarks feeding the optimistic safety threshold
        self.best_valid_update = None
        self.previous_max_active_participants = 0
        self.current_max_active_participants = 0
        # the sync-committee period the watermarks currently describe —
        # rotation is keyed on this so the clock hook (process_slot) and
        # the update path (_apply) rotate at most once per period
        self._participants_period = self._sync_period(bootstrap.header.slot)
        # verify the bootstrap proof against the trusted header state root
        st_alt = self.t.altair
        leaf = st_alt.SyncCommittee.hash_tree_root(bootstrap.current_sync_committee)
        idx = self._field_index("current_sync_committee")
        if not _verify_branch(
            leaf, bootstrap.current_sync_committee_branch, idx,
            bytes(bootstrap.header.state_root),
        ):
            raise LightClientError("invalid bootstrap sync committee proof")

    def _sync_period(self, slot: int) -> int:
        return (
            compute_epoch_at_slot(self.p, slot)
            // self.p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )

    def _field_index(self, name: str) -> int:
        fields = [f for f, _ in self.t.altair.BeaconState.fields]
        return fields.index(name)

    def _rotate_participants(self, period: int) -> None:
        """Roll the previous/current max-participation watermarks forward
        to ``period`` (idempotent; a multi-period gap clears both)."""
        if period <= self._participants_period:
            return
        if period == self._participants_period + 1:
            self.previous_max_active_participants = (
                self.current_max_active_participants
            )
        else:
            self.previous_max_active_participants = 0
        self.current_max_active_participants = 0
        self._participants_period = period

    def process_slot(self, current_slot: int) -> None:
        """Clock-driven per-period hook (ADVICE r5): rotate the
        participation watermarks when the WALL CLOCK crosses into a new
        sync-committee period — keyed on
        compute_sync_committee_period(current_slot), not only on the
        update path (_apply).  Without this, a store that stops receiving
        period-crossing finalized updates keeps an ancient
        current_max_active_participants and the optimistic safety
        threshold (max of the two watermarks / 2) can hold the head back
        forever.  Drive it once per slot (or per poll) from the follow
        loop."""
        self._rotate_participants(self._sync_period(int(current_slot)))

    # -- validation (spec validate_light_client_update) ------------------------

    def _validate(self, update) -> None:
        agg = update.sync_aggregate
        participation = sum(agg.sync_committee_bits)
        if participation < self.p.MIN_SYNC_COMMITTEE_PARTICIPANTS:
            raise LightClientError("insufficient sync committee participation")
        attested = update.attested_header
        state_root = bytes(attested.state_root)
        st_alt = self.t.altair

        if _has_sync_committee(update):
            nsc_leaf = st_alt.SyncCommittee.hash_tree_root(update.next_sync_committee)
            if not _verify_branch(
                nsc_leaf, update.next_sync_committee_branch,
                self._field_index("next_sync_committee"), state_root,
            ):
                raise LightClientError("invalid next_sync_committee branch")

        if _has_finality(update):
            fin_root = self.t.phase0.BeaconBlockHeader.hash_tree_root(
                update.finalized_header
            )
            # path: root within Checkpoint (index 1), checkpoint in state
            idx = 1 + 2 * self._field_index("finalized_checkpoint")
            if not _verify_branch(fin_root, update.finality_branch, idx, state_root):
                raise LightClientError("invalid finality branch")

        # sync aggregate signature over the attested header under
        # DOMAIN_SYNC_COMMITTEE.  The signing committee is the one of the
        # SIGNATURE slot's period: the store's current committee for a
        # same-period update, the proven next committee for the update
        # that crosses into the following period (spec
        # validate_light_client_update committee selection).  The fork
        # version is derived from OUR fork schedule at the signature slot —
        # trusting an update-supplied fork_version would let a malicious
        # server pick whichever domain it likes (ADVICE r3)
        from ..crypto.bls.api import PublicKey
        from ..state_transition.altair import eth_fast_aggregate_verify

        store_period = self._sync_period(self.finalized_header.slot)
        sig_slot = self._signature_slot(update)
        if sig_slot <= attested.slot:
            raise LightClientError("signature slot not after attested header")
        sig_period = self._sync_period(sig_slot)
        if sig_period == store_period:
            committee = self.current_sync_committee
        elif sig_period == store_period + 1 and self.next_sync_committee is not None:
            committee = self.next_sync_committee
        else:
            raise LightClientError(
                f"update signature period {sig_period} outside known committees"
                f" (store period {store_period})"
            )
        # spec validate_light_client_update: fork version at
        # epoch(max(signature_slot, 1) - 1) — the aggregate is signed with
        # the domain of the slot BEFORE the signature slot, so an update
        # straddling a fork activation must use the pre-fork version
        sig_epoch = compute_epoch_at_slot(self.p, max(sig_slot, 1) - 1)
        fork_version = self.fork_config.get_fork_version(sig_epoch)
        domain = compute_domain(
            self.p, DOMAIN_SYNC_COMMITTEE, fork_version, self.gvr
        )
        signing_root = self.t.phase0.SigningData.hash_tree_root(
            Fields(
                object_root=self.t.phase0.BeaconBlockHeader.hash_tree_root(attested),
                domain=domain,
            )
        )
        pks = [
            PublicKey.from_bytes(bytes(pk))
            for pk, bit in zip(committee.pubkeys, agg.sync_committee_bits)
            if bit
        ]
        if not eth_fast_aggregate_verify(
            pks, signing_root, bytes(agg.sync_committee_signature)
        ):
            raise LightClientError("invalid sync aggregate signature")

    def _signature_slot(self, update) -> int:
        try:
            s = update.signature_slot
            if s:
                return int(s)
        except (AttributeError, KeyError):
            pass
        return update.attested_header.slot + 1

    # -- update ranking (spec is_better_update) --------------------------------

    def _is_better_update(self, new, old) -> bool:
        max_bits = len(new.sync_aggregate.sync_committee_bits)
        new_n = sum(new.sync_aggregate.sync_committee_bits)
        old_n = sum(old.sync_aggregate.sync_committee_bits)
        new_sup = new_n * 3 >= max_bits * 2
        old_sup = old_n * 3 >= max_bits * 2
        if new_sup != old_sup:
            return new_sup
        if not new_sup and new_n != old_n:
            return new_n > old_n
        new_rel = _has_sync_committee(new) and self._sync_period(
            new.attested_header.slot
        ) == self._sync_period(self._signature_slot(new))
        old_rel = _has_sync_committee(old) and self._sync_period(
            old.attested_header.slot
        ) == self._sync_period(self._signature_slot(old))
        if new_rel != old_rel:
            return new_rel
        new_fin = _has_finality(new)
        old_fin = _has_finality(old)
        if new_fin != old_fin:
            return new_fin
        # sync-committee finality: a finalized header in the attested
        # header's own period keeps the committee rotation sound — without
        # this, force_update can adopt a candidate whose finalized header
        # crosses periods and strand the store (spec is_better_update)
        if new_fin:
            new_scf = self._sync_period(new.finalized_header.slot) == self._sync_period(
                new.attested_header.slot
            )
            old_scf = self._sync_period(old.finalized_header.slot) == self._sync_period(
                old.attested_header.slot
            )
            if new_scf != old_scf:
                return new_scf
        if new_n != old_n:
            return new_n > old_n
        if new.attested_header.slot != old.attested_header.slot:
            return new.attested_header.slot < old.attested_header.slot
        return self._signature_slot(new) < self._signature_slot(old)

    # -- update processing (spec process_light_client_update) ------------------

    def process_update(self, update) -> None:
        self._validate(update)
        bits = update.sync_aggregate.sync_committee_bits
        participation = sum(bits)
        max_bits = len(bits)
        self.current_max_active_participants = max(
            self.current_max_active_participants, participation
        )
        # optimistic advance past the safety threshold (spec
        # get_safety_threshold: half the best participation seen across the
        # current + previous periods — a dip below it signals a possible
        # committee split and holds the head back)
        threshold = max(
            self.previous_max_active_participants,
            self.current_max_active_participants,
        ) // 2
        attested = update.attested_header
        if participation > threshold and attested.slot > self.optimistic_header.slot:
            self.optimistic_header = attested

        supermajority = participation * 3 >= max_bits * 2
        fills_committee = (
            self.next_sync_committee is None
            and _has_sync_committee(update)
            and _has_finality(update)
            and self._sync_period(update.finalized_header.slot)
            == self._sync_period(attested.slot)
        )
        if supermajority and (
            (_has_finality(update)
             and update.finalized_header.slot > self.finalized_header.slot)
            or fills_committee
        ):
            self._apply(update)
            self.best_valid_update = None
        elif self.best_valid_update is None or self._is_better_update(
            update, self.best_valid_update
        ):
            self.best_valid_update = update

    def process_finality_update(self, update) -> None:
        """A finality update is an update without a sync-committee proof
        (routes/lightclient.ts:60 getLightClientFinalityUpdate)."""
        if _has_sync_committee(update):
            raise LightClientError("finality update must not carry a committee proof")
        self.process_update(update)

    def process_optimistic_update(self, update) -> None:
        """Head-only update: attested header + aggregate, no proofs
        (routes/lightclient.ts:60 getLightClientOptimisticUpdate)."""
        if _has_sync_committee(update) or _has_finality(update):
            raise LightClientError("optimistic update must carry no proofs")
        self.process_update(update)

    # -- forced committee advance (spec process_..._store_force_update) --------

    def force_update(self, current_slot: int) -> bool:
        """Advance on timeout: when no finalized update arrived for a whole
        UPDATE_TIMEOUT window but a valid candidate exists, adopt it —
        treating its attested header as finalized — so the store's committee
        knowledge doesn't fall more than a period behind the chain
        (light-client/src/index.ts:110 forced advance)."""
        u = self.best_valid_update
        if u is None:
            return False
        if current_slot <= self.finalized_header.slot + self.p.UPDATE_TIMEOUT:
            return False
        update = u
        if not _has_finality(u) or (
            u.finalized_header.slot <= self.finalized_header.slot
        ):
            # no usable finalized header: promote the attested one (spec
            # force update substitutes attested_header)
            update = Fields(**{k: u[k] for k in u.keys()})
            update.finalized_header = u.attested_header
        try:
            self._apply(update)
        except LightClientError:
            # a candidate the store cannot apply (e.g. cross-period
            # finality with no committee) must not wedge the store: drop
            # it so a better one can take the slot
            self.best_valid_update = None
            return False
        self.best_valid_update = None
        logger.info(
            "light client FORCED advance to slot %d (period %d)",
            self.finalized_header.slot,
            self._sync_period(self.finalized_header.slot),
        )
        return True

    # -- application (spec apply_light_client_update) --------------------------

    def _apply(self, update) -> None:
        store_period = self._sync_period(self.finalized_header.slot)
        fin = update.finalized_header
        new_period = self._sync_period(fin.slot)
        if self.next_sync_committee is None:
            if _has_sync_committee(update):
                # committee backfill is only sound within the store's period
                if new_period != store_period:
                    raise LightClientError(
                        "cannot learn next committee from a cross-period update"
                    )
                self.next_sync_committee = update.next_sync_committee
            elif new_period != store_period:
                raise LightClientError("period rotation without known next committee")
        elif new_period == store_period + 1:
            self.current_sync_committee = self.next_sync_committee
            self.next_sync_committee = (
                update.next_sync_committee if _has_sync_committee(update) else None
            )
            # watermark rotation shares the clock hook's idempotent path:
            # if process_slot already rolled this period, don't double-clear
            self._rotate_participants(new_period)
        elif new_period > store_period + 1:
            raise LightClientError("update skips a sync-committee period")
        if fin.slot > self.finalized_header.slot:
            self.finalized_header = fin
            if fin.slot > self.optimistic_header.slot:
                self.optimistic_header = fin
        logger.info(
            "light client advanced: optimistic slot %d, finalized slot %d",
            self.optimistic_header.slot, self.finalized_header.slot,
        )
