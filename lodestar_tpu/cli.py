"""CLI: beacon / dev / validator / lightclient commands.

Reference: packages/cli/src/cli.ts:20-47 (yargs command tree) and
cmds/{beacon,dev,validator,lightclient}/.  argparse equivalent with the
same command surface; options mirror the flag groups the reference
exposes (network, api, metrics, db, interop validators).

Entry: ``python -m lodestar_tpu.cli <cmd> [flags]``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from typing import Optional

from .config.chain_config import ChainConfig
from .params import MAINNET, MINIMAL, Preset
from .utils.logger import get_logger

logger = get_logger("cli")


def _hex_bytes(value: str, length: int, flag: str) -> bytes:
    """Parse a CLI hex argument (0x optional) and FAIL at config time on a
    wrong length — a silent [2:] slice of an unprefixed value would drop
    its first byte and mis-route funds long after startup."""
    raw = value[2:] if value.startswith("0x") else value
    try:
        out = bytes.fromhex(raw)
    except ValueError:
        raise SystemExit(f"{flag}: not valid hex: {value!r}")
    if len(out) != length:
        raise SystemExit(
            f"{flag}: expected {length} bytes ({length * 2} hex chars), got {len(out)}"
        )
    return out


def _preset(name: str) -> Preset:
    return {"mainnet": MAINNET, "minimal": MINIMAL}[name]


def _chain_config(args) -> ChainConfig:
    kw = dict(
        PRESET_BASE=args.preset,
        MIN_GENESIS_TIME=0,
        SHARD_COMMITTEE_PERIOD=0 if args.preset == "minimal" else 256,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=args.validators or 16,
    )
    if args.altair_epoch is not None:
        kw["ALTAIR_FORK_EPOCH"] = args.altair_epoch
    if args.bellatrix_epoch is not None:
        kw["BELLATRIX_FORK_EPOCH"] = args.bellatrix_epoch
    return ChainConfig(**kw)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="lodestar-tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
        p.add_argument("--db", help="sqlite db path (default: in-memory)")
        p.add_argument("--rest-port", type=int, default=9596)
        p.add_argument("--metrics", action="store_true")
        p.add_argument("--listen-port", type=int, default=9000)
        p.add_argument("--connect", action="append", default=[],
                       help="peer host:port to dial (repeatable)")
        p.add_argument("--altair-epoch", type=int, default=None)
        p.add_argument("--bellatrix-epoch", type=int, default=None)
        p.add_argument("--validators", type=int, default=16)
        p.add_argument("--config", help="JSON rc file of persisted flag values "
                       "(written by `init`; explicit CLI flags win)")
        p.add_argument(
            "--bls-verifier",
            choices=("auto", "tpu", "native", "python"),
            default="auto",
            help="signature verifier backend (auto: TPU kernel when a TPU "
            "is present, else native C, else pure python) — the selection "
            "seam of chain/chain.ts:146-148",
        )
        p.add_argument(
            "--bls-buckets", default="4,16,64,128,256",
            help="padding bucket sizes for the batched TPU dispatch "
            "(comma-separated; one compiled program per bucket)",
        )
        p.add_argument(
            "--bls-pipeline-depth", type=int, default=2,
            help="merged batches kept in flight on the device pipeline "
            "(pack N+1 while N computes and N-1 finishes on the host)",
        )
        p.add_argument(
            "--bls-flush-threshold", type=int, default=128,
            help="buffered signature sets that trigger an immediate flush",
        )
        p.add_argument(
            "--bls-buffer-wait-ms", type=float, default=20.0,
            help="max time a batchable job waits to share a dispatch "
            "(MAX_BUFFER_WAIT_MS analog)",
        )
        p.add_argument(
            "--bls-warmup", choices=("background", "blocking", "off"),
            default="background",
            help="AOT-compile every bucket's program at startup so the "
            "first block import doesn't eat a cold compile",
        )
        p.add_argument(
            "--bls-fused", choices=("auto", "on", "off"), default="auto",
            help="fused Pallas kernel path (auto: on only on real TPU "
            "backends; off: portable XLA-graph kernels)",
        )
        p.add_argument(
            "--bls-sharded", choices=("auto", "on", "off"), default="auto",
            help="cross-chip sharded pairing tier: merged batches at the "
            "bucket ladder's top end ride ONE shard_map program spanning "
            "the whole --bls-devices pool, final exponentiation once per "
            "batch (auto: on for multi-device TPU pools; "
            "docs/multichip.md)",
        )
        p.add_argument(
            "--bls-sharded-min-batch", type=int, default=0,
            help="smallest merged batch the sharded tier takes "
            "(0 = the largest --bls-buckets entry)",
        )
        p.add_argument(
            "--bls-cache-dir", default=None,
            help="persistent XLA compilation cache directory "
            "(default: $LODESTAR_TPU_JAX_CACHE or repo-local .jax_cache)",
        )
        p.add_argument(
            "--bls-aot-store", default=None, metavar="DIR",
            help="durable AOT executable store: fully-compiled XLA "
            "executables persisted across restarts (populate with "
            "tools/prewarm.py; default: $LODESTAR_TPU_AOT_STORE, else "
            "the tier is off; docs/aot.md)",
        )
        p.add_argument(
            "--bls-warmup-load-only", action="store_true",
            help="production rolling-restart mode: warmup NEVER traces "
            "or compiles — programs come from the AOT store or the "
            "verifier walks the fused→XLA→native degradation ladder "
            "(forces a blocking warmup; docs/aot.md runbook)",
        )
        p.add_argument(
            "--bls-devices", type=int, default=1,
            help="device executors in the BLS pool: 1 = single device "
            "(default), N = the first N local devices, 0 = every local "
            "device; each chip gets its own AOT-compiled programs and the "
            "scheduler places whole merged batches least-loaded "
            "(docs/dispatch_pipeline.md)",
        )
        p.add_argument(
            "--bls-max-queue-length", type=int, default=8192,
            help="verification jobs the pool queue holds before the "
            "overflow policy evicts the oldest job of the lowest QoS "
            "lane (docs/overload.md; the pre-overload behavior raised "
            "QUEUE_MAX_LENGTH into gossip validation instead)",
        )
        p.add_argument(
            "--bls-high-water", type=int, default=0,
            help="pending signature sets that flip the pool into "
            "backpressure (gossip slows storm-topic intake; released at "
            "half).  0 = half of --bls-max-queue-length",
        )
        p.add_argument(
            "--bls-overload-bundle-threshold", type=int, default=256,
            help="shed sets within a 10s window that trigger ONE "
            "rate-limited 'overload' diagnostic bundle with per-lane "
            "shed counts (0 disables; docs/overload.md)",
        )
        p.add_argument(
            "--bls-point-cache-size", type=int, default=8192,
            help="entries in the pack-stage LRU of decompressed/affine "
            "points keyed by compressed bytes (0 disables; attestation "
            "pubkeys and committee aggregates repeat epoch-to-epoch)",
        )
        p.add_argument(
            "--bls-quarantine-threshold", type=int, default=2,
            help="consecutive verdict/dispatch failures on one device "
            "executor before it is quarantined out of the placement "
            "rotation (docs/chaos.md self-healing pool)",
        )
        p.add_argument(
            "--bls-quarantine-backoff-s", type=float, default=1.0,
            help="first quarantine duration; a failed re-admission probe "
            "doubles it (capped at 60s), a successful probe resets it",
        )
        p.add_argument(
            "--trace-dump", default=None, metavar="PATH",
            help="enable hot-path span tracing and write a Chrome trace-"
            "event JSON (open in Perfetto / chrome://tracing) to PATH on "
            "shutdown (docs/observability.md)",
        )
        p.add_argument(
            "--trace-buffer-size", type=int, default=8192,
            help="span ring-buffer capacity when tracing is enabled "
            "(old spans are evicted, never accumulated)",
        )
        p.add_argument(
            "--jax-profile", default=None, metavar="DIR",
            help="device-profile capture root (docs/observability.md "
            "§Mesh observatory): jax.profiler brackets the (blocking) "
            "BLS warmup AND a steady-state dispatch window "
            "(--profile-window flushes, default 4), and the merged "
            "host+device Chrome trace lands in DIR/merged_trace.json "
            "on shutdown",
        )
        p.add_argument(
            "--profile-window", type=int, default=0, metavar="N",
            help="arm a device-profile window over the next N BLS pool "
            "flushes at startup (0 = only on POST "
            "/eth/v1/lodestar/profile; with --jax-profile the default "
            "becomes 4)",
        )
        p.add_argument(
            "--forensics-dir", default=None, metavar="DIR",
            help="diagnostic bundle directory (default: "
            "$LODESTAR_TPU_FORENSICS_DIR or <tmp>/lodestar-tpu-forensics); "
            "bundles are written on crash, SIGTERM/SIGUSR2, watchdog "
            "stall, and GET /eth/v1/lodestar/forensics "
            "(docs/observability.md §Failure forensics)",
        )
        p.add_argument(
            "--watchdog-deadline-s", type=float, default=30.0,
            help="flag any dispatched BLS batch still unresolved after "
            "this many seconds: journal ERROR + "
            "bls_watchdog_stalls_total{device} + one automatic bundle "
            "(0 disables the watchdog)",
        )
        p.add_argument(
            "--log-format", choices=("text", "json"), default=None,
            help="stderr log line format; json emits one machine-"
            "ingestable object per line stamped with the batch "
            "correlation id (default: text, or $LODESTAR_LOG_FORMAT)",
        )
        p.add_argument(
            "--telemetry-interval-s", type=float, default=5.0,
            help="device telemetry sampler period: per-device HBM "
            "(Device.memory_stats) and busy-ratio gauges + periodic "
            "journal events, published at /metrics and "
            "GET /eth/v1/lodestar/observatory (0 disables; runs only "
            "with the TPU verifier — it never initializes a JAX "
            "backend on its own; docs/observability.md §Performance "
            "observatory)",
        )

    dev = sub.add_parser("dev", help="single-process interop chain (cmds/dev)")
    common(dev)
    dev.add_argument("--slots", type=int, default=32, help="slots to run (0 = forever)")
    dev.add_argument("--tpu-bls", action="store_true",
                     help="alias for --bls-verifier tpu")

    beacon = sub.add_parser("beacon", help="beacon node (cmds/beacon)")
    common(beacon)
    beacon.add_argument("--genesis-state", help="SSZ genesis state file")
    beacon.add_argument("--discovery-port", type=int, default=None,
                        help="UDP discovery port (0 = ephemeral; omit to disable)")
    beacon.add_argument("--bootnode", action="append", default=[],
                        help="discovery bootstrap host:udp_port (repeatable)")
    beacon.add_argument(
        "--checkpoint-sync-url",
        help="trusted beacon REST URL to fetch the finalized state from "
        "(initBeaconState.ts:104-136); backfill then earns history backwards",
    )
    beacon.add_argument("--execution-url",
                        help="Engine API JSON-RPC endpoint (execution/engine/http.ts)")
    beacon.add_argument("--jwt-secret",
                        help="file holding the hex-encoded engine jwt secret")
    beacon.add_argument("--builder-url",
                        help="MEV builder REST endpoint (execution/builder/http.ts)")
    beacon.add_argument("--builder-pubkey",
                        help="hex BLS pubkey pinning the builder identity; "
                        "bids signed by any other key are refused")
    beacon.add_argument("--suggested-fee-recipient", default="0x" + "00" * 20,
                        help="node-default fee recipient when a proposer sent "
                        "no preparation")

    vc = sub.add_parser("validator", help="validator client (cmds/validator)")
    vc.add_argument("--beacon-url", default="http://127.0.0.1:9596")
    vc.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    vc.add_argument("--interop-indices", default="0..15",
                    help="interop key range, e.g. 0..15")
    vc.add_argument("--slashing-protection-db", help="EIP-3076 JSON path")
    vc.add_argument("--keystores-dir",
                    help="directory of EIP-2335 keystore-*.json files "
                    "(overrides --interop-indices; cmds/account import flow)")
    vc.add_argument("--keystores-password-file",
                    help="file holding the shared keystore password")
    vc.add_argument("--remote-signer-url",
                    help="web3signer-compatible remote signer URL "
                    "(validatorStore.ts SignerType.Remote)")
    vc.add_argument("--fee-recipient", default="0x" + "00" * 20,
                    help="suggested fee recipient, sent via "
                    "prepareBeaconProposer each epoch")
    vc.add_argument("--gas-limit", type=int, default=30_000_000)
    vc.add_argument("--builder", action="store_true",
                    help="prefer blinded (MEV builder) block production")
    vc.add_argument("--dev-signing", action="store_true",
                    help="DEV/INTEROP ONLY: use the variable-time native "
                    "signing ladder (fb_sign) instead of the default "
                    "constant-time-safe path — its timing leaks the key, "
                    "acceptable only for published interop secrets")

    init_cmd = sub.add_parser("init", help="persist flag values to an rc file (cmds/init)")
    common(init_cmd)
    init_cmd.add_argument("--out", default="lodestar-tpu.rc.json")

    acct = sub.add_parser("account", help="keystore management (cmds/account)")
    acct_sub = acct.add_subparsers(dest="account_cmd", required=True)
    acct_create = acct_sub.add_parser("create", help="generate EIP-2335 keystores")
    acct_create.add_argument("--out-dir", required=True)
    acct_create.add_argument("--password-file", required=True)
    acct_create.add_argument("--count", type=int, default=1)
    acct_create.add_argument("--kdf", choices=("scrypt", "pbkdf2"), default="pbkdf2")
    acct_list = acct_sub.add_parser("list", help="list keystore pubkeys")
    acct_list.add_argument("--keystores-dir", required=True)

    lc = sub.add_parser("lightclient", help="light client (cmds/lightclient)")
    lc.add_argument("--beacon-url", default="http://127.0.0.1:9596")
    lc.add_argument("--checkpoint-root", required=False,
                    help="trusted block root (default: the node's finalized root)")
    lc.add_argument("--preset", choices=["mainnet", "minimal"], default="minimal")
    lc.add_argument("--poll-seconds", type=float, default=12.0)
    lc.add_argument("--max-polls", type=int, default=0, help="0 = forever")
    return ap


async def run_dev(args) -> int:
    from .api import RestApiServer
    from .chain.handlers import GossipHandlers
    from .chain.light_client import LightClientServer
    from .crypto.bls.verifier import PyBlsVerifier
    from .db.beacon import BeaconDb
    from .db.controller import MemoryDbController, SqliteDbController
    from .metrics import create_metrics
    from .network import Network
    from .node.dev_chain import DevChain

    preset = _preset(args.preset)
    cfg = _chain_config(args)
    _configure_tracing(args)
    # full Metrics group (not just the registry) so the pool/verifier
    # observe the new pipeline-stage histograms in dev mode too
    metrics = create_metrics() if args.metrics else None
    pool = _make_pool(args, metrics=metrics)
    _configure_forensics(args, metrics=metrics, pool=pool)
    controller = SqliteDbController(args.db) if args.db else MemoryDbController()
    db = BeaconDb(preset, controller)
    dev = DevChain(preset, cfg, args.validators, pool, db=db)
    handlers = GossipHandlers(dev.chain)
    lc_server = LightClientServer(preset, dev.chain)
    network = Network(preset, dev.chain, handlers)
    await network.listen(args.listen_port)
    for target in args.connect:
        host, _, port = target.partition(":")
        await network.connect(host, int(port))
    rest = RestApiServer(preset, dev.chain, network=network,
                         metrics_registry=metrics.reg if metrics else None)
    rest.gossip_handlers = handlers
    rest.light_client_server = lc_server
    await rest.listen(args.rest_port)
    logger.info("dev chain: %d validators, %s preset", args.validators, args.preset)
    n = args.slots if args.slots else 1 << 62
    await dev.run(n)
    state = dev.chain.head_state()
    print(
        json.dumps(
            {
                "head_slot": int(state.slot),
                "justified_epoch": int(state.current_justified_checkpoint.epoch),
                "finalized_epoch": int(state.finalized_checkpoint.epoch),
            }
        )
    )
    await network.close()
    await rest.close()
    pool.close()
    return 0


def _configure_tracing(args) -> None:
    """Enable the span tracer when --trace-dump asks for it.  Called
    before the pool is built so warmup and the first dispatches land in
    the buffer; the dump itself happens in main()'s finally so Ctrl-C on
    a forever-running node still writes the file."""
    dump = getattr(args, "trace_dump", None)
    if dump:
        from . import tracing

        tracing.enable(getattr(args, "trace_buffer_size", 8192))
        logger.info("span tracing on (buffer %d); dump -> %s",
                    tracing.TRACER.capacity, dump)


def _configure_forensics(args, metrics=None, pool=None) -> None:
    """Flight-recorder bring-up (docs/observability.md §Failure
    forensics): log format, bundle directory, crash/signal hooks,
    faulthandler, and the in-flight stall watchdog."""
    from .forensics import RECORDER
    from .utils.logger import set_format

    fmt = getattr(args, "log_format", None)
    if fmt:
        set_format(fmt)
    RECORDER.configure(
        forensics_dir=getattr(args, "forensics_dir", None),
        metrics=metrics, pool=pool,
    )
    deadline = getattr(args, "watchdog_deadline_s", 30.0)
    RECORDER.install(watchdog_deadline_s=deadline if deadline > 0 else None)
    logger.info("flight recorder on: bundles -> %s (watchdog %s)",
                RECORDER.dir,
                f"{deadline:.1f}s" if deadline > 0 else "off")
    _configure_observatory(args, metrics=metrics, pool=pool)


def _configure_observatory(args, metrics=None, pool=None) -> None:
    """Performance-observatory bring-up: hand the compile ledger its
    metrics registry and start the device telemetry sampler — but only
    when the verifier actually drives devices (TpuBlsVerifier): the
    sampler resolves jax.devices() lazily, and a native/python run must
    not initialize a JAX backend just to read zero telemetry."""
    from .observatory import COMPILE_LEDGER, start_sampler

    if metrics is not None:
        COMPILE_LEDGER.configure(metrics=metrics)
    interval = getattr(args, "telemetry_interval_s", 5.0)
    verifier = getattr(pool, "verifier", None)
    if interval and interval > 0 and hasattr(verifier, "_executors"):
        devices = [ex.device for ex in verifier._executors if ex.device is not None]
        start_sampler(
            interval_s=interval, metrics=metrics,
            devices=devices or None,
        )
        logger.info("device telemetry sampler on (every %.1fs)", interval)
    _configure_profile(args, metrics=metrics)


def _configure_profile(args, metrics=None) -> None:
    """Steady-state profile-window bring-up (ISSUE 20: --jax-profile
    used to bracket only the blocking warmup; the dispatch-time windows
    it was blind to are the whole point).  --jax-profile alone arms a
    default 4-flush window; --profile-window N overrides the count and
    also works standalone (capture dir under the tmp default)."""
    from .observatory import xprof

    profile_dir = getattr(args, "jax_profile", None)
    window = getattr(args, "profile_window", 0) or (4 if profile_dir else 0)
    if not profile_dir and not window:
        return
    cap = xprof.get_capture()  # _make_verifier may have configured it
    if cap is None:
        cap = xprof.configure_capture(profile_dir=profile_dir, metrics=metrics)
    else:
        cap.metrics = metrics
    if window:
        cap.request_window(window)
        logger.info(
            "profile window armed: next %d pool flushes -> %s",
            window, cap.profile_dir,
        )


def _finalize_profile(args) -> None:
    """Shutdown twin of _dump_trace: close any still-open window and
    write the merged host+device Chrome trace next to the profile data."""
    if not (getattr(args, "jax_profile", None)
            or getattr(args, "profile_window", 0)):
        return
    from .observatory import xprof

    cap = xprof.get_capture()
    if cap is None:
        return
    cap.wait_idle(timeout=10.0)
    last = cap.finalize()
    if last is not None:
        path = cap.write_merged(os.path.join(cap.profile_dir, "merged_trace.json"))
        logger.info("wrote merged host+device trace to %s", path)


def _dump_trace(path) -> None:
    if not path:
        return
    from . import tracing

    tracing.write_chrome_trace(tracing.TRACER, path)
    logger.info("wrote %d spans (%d dropped) to %s",
                len(tracing.TRACER), tracing.TRACER.dropped, path)


def _make_pool(args, metrics=None):
    """Verifier + batch pool with the dispatch-pipeline knobs applied
    (docs/dispatch_pipeline.md)."""
    from .chain.bls_pool import BlsBatchPool

    return BlsBatchPool(
        _make_verifier(args),
        max_buffer_wait=getattr(args, "bls_buffer_wait_ms", 20.0) / 1e3,
        flush_threshold=getattr(args, "bls_flush_threshold", 128),
        pipeline_depth=getattr(args, "bls_pipeline_depth", 2),
        max_queue_length=getattr(args, "bls_max_queue_length", 8192),
        high_water=getattr(args, "bls_high_water", 0) or None,
        overload_shed_threshold=getattr(
            args, "bls_overload_bundle_threshold", 256
        ),
        metrics=metrics,
    )


def _make_verifier(args):
    """The verifier selection seam (reference chain.ts:146-148 picks the
    worker pool by default; here: TPU kernel by default when a TPU backend
    exists, native C otherwise, pure-Python oracle as last resort)."""
    choice = getattr(args, "bls_verifier", "auto")
    if getattr(args, "tpu_bls", False):
        choice = "tpu"
    if choice == "auto":
        try:
            import jax

            choice = "tpu" if jax.default_backend() not in ("cpu",) else "native"
        except Exception:
            choice = "native"
    if choice == "tpu":
        from .crypto.bls.tpu_verifier import TpuBlsVerifier, configure_persistent_cache

        configure_persistent_cache(getattr(args, "bls_cache_dir", None))
        from .aot import configure_aot_store

        aot_store = configure_aot_store(getattr(args, "bls_aot_store", None))
        load_only = bool(getattr(args, "bls_warmup_load_only", False))
        if load_only and not aot_store.enabled:
            logger.warning(
                "--bls-warmup-load-only without an AOT store "
                "(--bls-aot-store / $LODESTAR_TPU_AOT_STORE): every "
                "program will miss and the verifier degrades to native"
            )
        buckets = tuple(
            int(b) for b in str(getattr(args, "bls_buckets", "4,16,64,128,256")).split(",") if b
        )
        fused_flag = getattr(args, "bls_fused", "auto")
        fused = None if fused_flag == "auto" else fused_flag == "on"
        n_dev = getattr(args, "bls_devices", 1)
        if n_dev < 0:
            raise SystemExit(f"--bls-devices: expected 0 (all) or a positive count, got {n_dev}")
        devices = None
        if n_dev != 1:
            import jax

            local = jax.devices()
            devices = local if n_dev == 0 else local[:n_dev]
            logger.info("bls executor pool: %d of %d local devices",
                        len(devices), len(local))
        sharded_flag = getattr(args, "bls_sharded", "auto")
        sharded = None if sharded_flag == "auto" else sharded_flag == "on"
        v = TpuBlsVerifier(
            buckets=buckets, fused=fused, devices=devices,
            sharded=sharded,
            sharded_min_batch=getattr(args, "bls_sharded_min_batch", 0) or None,
            point_cache_size=getattr(args, "bls_point_cache_size", 8192),
            quarantine_threshold=getattr(args, "bls_quarantine_threshold", 2),
            quarantine_backoff_s=getattr(args, "bls_quarantine_backoff_s", 1.0),
            load_only=load_only,
        )
        warm = getattr(args, "bls_warmup", "background")
        profile_dir = getattr(args, "jax_profile", None)
        capture = None
        if profile_dir:
            # one ProfileCapture owns the whole session: the warmup
            # window here, the steady-state dispatch window armed by
            # _configure_observatory, and any POST .../profile windows —
            # all merged against the span tracer's clock
            from .observatory import xprof

            capture = xprof.configure_capture(profile_dir=profile_dir)
        if load_only and warm != "off":
            # load-only warmup is seconds (deserialize, no compile) and
            # its degradation verdict decides the serving tier — block.
            # --jax-profile still brackets it: the deserialize path is
            # exactly what a restart profile should show
            if capture is not None:
                dt = capture.run_window(
                    lambda: v.warmup(load_only=True), label="warmup-load"
                )
            else:
                dt = v.warmup(load_only=True)
            logger.info(
                "bls AOT load-only warmup: %d buckets in %.1fs "
                "(fused=%s, native_only=%s)", len(buckets), dt, v.fused,
                v._native_tier_only,
            )
        elif capture is not None and warm != "off":
            # device-level profile of the AOT compiles + first dispatches;
            # forces blocking warmup so the window closes on real work
            dt = capture.run_window(v.warmup, label="warmup")
            logger.info("bls AOT warmup under jax.profiler: %d buckets in "
                        "%.1fs -> %s", len(buckets), dt, profile_dir)
        elif warm == "blocking":
            dt = v.warmup()
            logger.info("bls AOT warmup: %d buckets in %.1fs", len(buckets), dt)
        elif warm == "background":
            v.warmup_async()
        logger.info("bls verifier: TPU batched kernel (host final exp)")
        return v
    if choice == "native":
        from .crypto.bls.native_verifier import FastBlsVerifier

        v = FastBlsVerifier()
        if v.native:
            logger.info("bls verifier: native C (csrc/fastbls.c)")
            return v
        logger.warning("native bls unavailable; falling back to python oracle")
    from .crypto.bls.verifier import PyBlsVerifier

    logger.info("bls verifier: pure-python oracle")
    return PyBlsVerifier()


async def run_beacon(args) -> int:
    """Boot a (non-producing) beacon node: db-resumed or genesis state,
    network listener, REST API; follows peers via range sync + gossip.
    Reference: cmds/beacon/handler.ts + initBeaconState.ts:104-136."""
    from .api import RestApiServer
    from .chain.beacon_chain import BeaconChain
    from .chain.handlers import GossipHandlers
    from .crypto.bls.verifier import PyBlsVerifier
    from .db.beacon import BeaconDb
    from .db.controller import MemoryDbController, SqliteDbController
    from .network import Network
    from .state_transition import interop_genesis_state
    from .sync import RangeSync

    preset = _preset(args.preset)
    cfg = _chain_config(args)
    _configure_tracing(args)
    controller = SqliteDbController(args.db) if args.db else MemoryDbController()
    db = BeaconDb(preset, controller)
    anchor_block_root = None
    if args.checkpoint_sync_url:
        from .node.checkpoint_sync import fetch_checkpoint_state

        genesis, anchor_block, anchor_block_root = await fetch_checkpoint_state(
            preset, cfg, args.checkpoint_sync_url
        )
        db.block.put(anchor_block_root, anchor_block)
        db.archive_block(anchor_block, anchor_block_root)
    elif args.genesis_state:
        from .types import get_types

        raw = open(args.genesis_state, "rb").read()
        genesis = get_types(preset).phase0.BeaconState.deserialize(raw)
    else:
        resumed = db.last_archived_state()
        genesis = resumed or interop_genesis_state(preset, cfg, args.validators, 1)
    from .metrics import create_metrics

    metrics = create_metrics()
    pool = _make_pool(args, metrics=metrics)
    _configure_forensics(args, metrics=metrics, pool=pool)
    execution_engine = None
    if args.execution_url:
        from urllib.parse import urlparse as _urlparse

        from .execution.engine import ExecutionEngineHttp, jwt_supplier_from_secret

        jwt_supplier = None
        if args.jwt_secret:
            jwt_supplier = jwt_supplier_from_secret(
                bytes.fromhex(open(args.jwt_secret).read().strip().replace("0x", ""))
            )
        eu = _urlparse(args.execution_url)
        execution_engine = ExecutionEngineHttp(
            eu.hostname or "127.0.0.1", eu.port or 8551, jwt_supplier=jwt_supplier
        )
    builder = None
    if args.builder_url:
        from urllib.parse import urlparse as _urlparse

        from .execution.builder import ExecutionBuilderHttp

        bu = _urlparse(args.builder_url)
        builder = ExecutionBuilderHttp(
            bu.hostname or "127.0.0.1", bu.port or 18550,
            pubkey=_hex_bytes(args.builder_pubkey, 48, "--builder-pubkey")
            if args.builder_pubkey else None,
        )
    chain = BeaconChain(
        preset, cfg, genesis, pool, db=db, metrics=metrics,
        execution_engine=execution_engine, builder=builder,
        default_fee_recipient=_hex_bytes(
            args.suggested_fee_recipient, 20, "--suggested-fee-recipient"
        ),
    )
    handlers = GossipHandlers(chain)
    network = Network(preset, chain, handlers, metrics=metrics)
    await network.listen(args.listen_port)
    for target in args.connect:
        host, _, port = target.partition(":")
        peer = await network.connect(host, int(port))
        logger.info("connected to %s (head slot %s)", target, peer.status.head_slot)
    rest = RestApiServer(preset, chain, network=network,
                         metrics_registry=metrics.reg, metrics=metrics)
    rest.gossip_handlers = handlers
    await rest.listen(args.rest_port)
    if args.discovery_port is not None:
        from .crypto.bls.api import SecretKey as _SK
        import secrets as _secrets

        from .crypto.bls.fields import R as _R

        identity = _SK.from_bytes(
            (int.from_bytes(_secrets.token_bytes(32), "big") % (_R - 1) + 1).to_bytes(32, "big")
        )
        boots = []
        for b in args.bootnode:
            bh, _, bp = b.partition(":")
            boots.append((bh, int(bp)))
        await network.enable_discovery(identity, args.discovery_port, bootstrap=boots)
    backfill_task = None
    if anchor_block_root is not None:
        from .sync.backfill import BackfillSync

        backfill = BackfillSync(
            preset, cfg, db, pool, genesis, anchor_block_root,
            network.peer_manager, metrics=metrics,
        )
        backfill_task = asyncio.create_task(backfill.run())
    sync = RangeSync(
        preset, chain, network.peer_manager, metrics=metrics,
        report_peer=network.report_peer,
    )
    imported = await sync.run_to_head()
    if backfill_task is not None:
        stored = await backfill_task
        logger.info("backfill stored %d historical blocks", stored)
    logger.info("synced %d blocks; following gossip (ctrl-c to stop)", imported)
    try:
        while True:
            await asyncio.sleep(3600)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    await network.close()
    await rest.close()
    pool.close()
    return 0


async def run_validator(args) -> int:
    from .api.client import ApiClient
    from .crypto.bls.api import interop_secret_key
    from .validator import SlashingProtection, ValidatorClient, ValidatorStore

    preset = _preset(args.preset)
    cfg = ChainConfig(PRESET_BASE=args.preset, MIN_GENESIS_TIME=0,
                      SHARD_COMMITTEE_PERIOD=0,
                      MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16)
    url = args.beacon_url.rstrip("/")
    host = url.split("//")[-1].split(":")[0]
    port = int(url.rsplit(":", 1)[-1])
    api = ApiClient(host, port)
    if args.keystores_dir:
        from .crypto.bls.api import SecretKey
        from .validator.keystore import load_keystores_dir

        password = ""
        if args.keystores_password_file:
            password = open(args.keystores_password_file).read().strip()
        loaded = load_keystores_dir(args.keystores_dir, password)
        if not loaded:
            logger.error("no keystores found in %s", args.keystores_dir)
            return 1
        # resolve validator indices over the API (IndicesService role,
        # validator/src/services/indices.ts:17); unresolved pubkeys stay
        # pending and are retried every epoch — a not-yet-activated key
        # must start signing the moment it activates, not never
        keys = {}
        pending_secrets = {pk: SecretKey.from_bytes(sec) for pk, sec in loaded.items()}

        async def resolve_pending(store=None):
            for pk in list(pending_secrets):
                try:
                    info = await api.get(
                        f"/eth/v1/beacon/states/head/validators/0x{pk.hex()}"
                    )
                    idx = int(info["data"]["index"])
                except Exception:
                    continue
                sk = pending_secrets.pop(pk)
                keys[idx] = sk
                if store is not None:
                    store.keys[idx] = sk
                    store.pubkeys[idx] = pk
                logger.info("validator 0x%s... resolved to index %d", pk.hex()[:12], idx)

        await resolve_pending()
        if pending_secrets:
            logger.warning("%d keystore pubkeys not yet active; will retry", len(pending_secrets))
        logger.info("loaded %d keystore validators", len(keys))
    else:
        lo, _, hi = args.interop_indices.partition("..")
        keys = {i: interop_secret_key(i) for i in range(int(lo), int(hi) + 1)}
    # persist_path: every accepted record is WAL'd before the signature is
    # released, so a crash/SIGKILL cannot lose signing history (ADVICE r3)
    protection = SlashingProtection(persist_path=args.slashing_protection_db)
    genesis = await api.get("/eth/v1/beacon/genesis")
    gvr = bytes.fromhex(genesis["data"]["genesis_validators_root"][2:])
    # remote signer (validatorStore.ts SignerType.Remote): pull the key
    # list from the signer and resolve indices over the beacon API
    remote_signer = None
    remote_keys = {}
    if getattr(args, "remote_signer_url", None):
        from .validator.remote_signer import RemoteSignerClient

        remote_signer = RemoteSignerClient(args.remote_signer_url)
        for pk in remote_signer.public_keys():
            try:
                info = await api.get(
                    f"/eth/v1/beacon/states/head/validators/0x{pk.hex()}"
                )
                remote_keys[int(info["data"]["index"])] = pk
            except Exception:
                logger.warning("remote key 0x%s... not yet active", pk.hex()[:12])
        logger.info("remote signer: %d keys from %s", len(remote_keys), args.remote_signer_url)
    store = ValidatorStore(preset, cfg, keys, protection, genesis_validators_root=gvr,
                           remote_signer=remote_signer, remote_keys=remote_keys,
                           dev_signing=getattr(args, "dev_signing", False))
    fee_recipient = _hex_bytes(
        getattr(args, "fee_recipient", "0x" + "00" * 20), 20, "--fee-recipient"
    )
    vc = ValidatorClient(preset, cfg, store, api,
                         fee_recipient=fee_recipient,
                         gas_limit=getattr(args, "gas_limit", 30_000_000),
                         builder_enabled=getattr(args, "builder", False))
    from .validator import ChainHeaderTracker

    tracker = ChainHeaderTracker(api)
    tracker.start()
    vc.header_tracker = tracker
    logger.info("validator client: %d keys against %s", len(keys), args.beacon_url)
    slot = 1
    try:
        while True:
            syncing = await api.get("/eth/v1/node/syncing")
            head = int(syncing["data"]["head_slot"])
            slot = max(slot, head + 1)
            if args.keystores_dir and pending_secrets and slot % 8 == 0:
                await resolve_pending(store)
            # wait up to 1/3 slot for the head event before attesting
            await vc.run_slot(slot, head_wait_s=cfg.SECONDS_PER_SLOT / 3)
            slot += 1
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await tracker.stop()
        protection.close()  # fold the WAL into the interchange file
    return 0


async def run_lightclient(args) -> int:
    """Follow the chain as a light client over the REST API
    (cmds/lightclient/handler.ts)."""
    from .api.client import ApiClient
    from .api.serde import from_json
    from .light_client import LightClient

    preset = _preset(args.preset)
    cfg = ChainConfig(PRESET_BASE=args.preset, MIN_GENESIS_TIME=0,
                      SHARD_COMMITTEE_PERIOD=0,
                      MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16)
    url = args.beacon_url.rstrip("/")
    host = url.split("//")[-1].split(":")[0]
    port = int(url.rsplit(":", 1)[-1])
    api = ApiClient(host, port)
    genesis = await api.get("/eth/v1/beacon/genesis")
    gvr = bytes.fromhex(genesis["data"]["genesis_validators_root"][2:])
    genesis_time = int(genesis["data"].get("genesis_time", 0))
    root = args.checkpoint_root
    if not root:
        fc = await api.get("/eth/v1/beacon/states/head/finality_checkpoints")
        root = fc["data"]["finalized"]["root"]
    boot = await api.get(f"/eth/v1/beacon/light_client/bootstrap/{root}")
    client = LightClient(preset, cfg, from_json(boot["data"]), gvr)
    logger.info("light client bootstrapped at slot %d", client.finalized_header.slot)
    polls = 0
    slots_per_period = preset.SLOTS_PER_EPOCH * preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    while args.max_polls == 0 or polls < args.max_polls:
        polls += 1
        # clock-driven per-period hook: rotate the participation
        # watermarks even when no update crosses the period boundary
        import time as _time

        if genesis_time:
            wall_slot = max(
                0, int(_time.time() - genesis_time) // cfg.SECONDS_PER_SLOT
            )
            client.process_slot(wall_slot)
        try:
            # resume from the period of our best header so the follow loop
            # advances with the chain instead of refetching period 0
            period = int(client.finalized_header.slot) // slots_per_period
            ups = await api.get(
                f"/eth/v1/beacon/light_client/updates?start_period={period}&count=4"
            )
            for u in ups["data"]:
                client.process_update(from_json(u))
            print(
                json.dumps(
                    {
                        "optimistic_slot": int(client.optimistic_header.slot),
                        "finalized_slot": int(client.finalized_header.slot),
                    }
                ),
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("update poll failed: %s", e)
        if args.max_polls and polls >= args.max_polls:
            break
        await asyncio.sleep(args.poll_seconds)
    return 0


def run_account(args) -> int:
    """Keystore management (reference cmds/account: create/list)."""
    import json as _json
    import os as _os
    import secrets as _secrets

    from .validator.keystore import create_keystore

    if args.account_cmd == "create":
        password = open(args.password_file).read().strip()
        _os.makedirs(args.out_dir, exist_ok=True)
        from .crypto.bls.fields import R as _R

        for i in range(args.count):
            secret = (int.from_bytes(_secrets.token_bytes(32), "big") % (_R - 1) + 1).to_bytes(32, "big")
            ks = create_keystore(secret, password, kdf=args.kdf)
            path = _os.path.join(args.out_dir, f"keystore-{ks['pubkey'][:12]}.json")
            with open(path, "w") as f:
                _json.dump(ks, f, indent=2)
            print(f"wrote {path}")
        return 0
    if args.account_cmd == "list":
        for name in sorted(_os.listdir(args.keystores_dir)):
            if name.endswith(".json"):
                with open(_os.path.join(args.keystores_dir, name)) as f:
                    ks = _json.load(f)
                print(f"0x{ks.get('pubkey', '?')}  {name}")
        return 0
    return 2


def _apply_config_file(args, argv) -> None:
    """Overlay persisted rc values (cmds/init persistence): an rc value
    applies unless the same flag was given explicitly on the command
    line."""
    path = getattr(args, "config", None)
    if not path:
        return
    with open(path) as f:
        persisted = json.load(f)
    explicit = set()
    for tok in argv or sys.argv[1:]:
        if tok.startswith("--"):
            explicit.add(tok[2:].split("=", 1)[0].replace("-", "_"))
    for key, value in persisted.items():
        if key in ("cmd", "out", "config") or key in explicit:
            continue
        if hasattr(args, key):
            setattr(args, key, value)


def run_init(args) -> int:
    """Write the resolved flag values to an rc file (cmds/init/handler.ts
    persistOptionsAndConfig)."""
    payload = {
        k: v for k, v in vars(args).items()
        if k not in ("cmd", "out", "config") and not callable(v)
    }
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2, default=str)
    print(f"wrote {args.out}")
    return 0


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        _apply_config_file(args, argv)
    except (OSError, ValueError) as e:
        print(f"bad --config file: {e}", file=sys.stderr)
        return 2
    if args.cmd == "dev":
        try:
            return asyncio.run(run_dev(args))
        finally:
            # synchronous write in the finally: a Ctrl-C on a forever
            # node (--slots 0) must still produce the trace artifact
            _dump_trace(getattr(args, "trace_dump", None))
            _finalize_profile(args)
    if args.cmd == "beacon":
        try:
            return asyncio.run(run_beacon(args))
        finally:
            _dump_trace(getattr(args, "trace_dump", None))
            _finalize_profile(args)
    if args.cmd == "validator":
        return asyncio.run(run_validator(args))
    if args.cmd == "lightclient":
        return asyncio.run(run_lightclient(args))
    if args.cmd == "account":
        return run_account(args)
    if args.cmd == "init":
        return run_init(args)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
