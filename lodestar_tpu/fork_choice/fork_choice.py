"""ForkChoice: the stateful wrapper over ProtoArray.

Reference: packages/fork-choice/src/forkChoice/forkChoice.ts:46 and
interface.ts (IForkChoice), store.ts (IForkChoiceStore).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from .proto_array import ProtoArray, ProtoNode, VoteTracker, compute_deltas


@dataclasses.dataclass
class Checkpoint:
    epoch: int
    root: bytes


@dataclasses.dataclass
class ForkChoiceStore:
    """Justified/finalized tracking + justified balances (store.ts)."""

    current_slot: int
    justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    justified_balances: np.ndarray
    best_justified_checkpoint: Optional[Checkpoint] = None


class ForkChoiceError(Exception):
    pass


class ForkChoice:
    """on_block / on_attestation / update_head / prune.

    Proposer boost (PROPOSER_SCORE_BOOST) is applied as a transient weight
    delta on the next score pass (forkChoice.ts proposerBoostRoot).
    """

    def __init__(
        self,
        store: ForkChoiceStore,
        anchor: ProtoNode,
        proposer_boost_pct: int = 40,
        slots_per_epoch: int = 32,
    ):
        self.store = store
        self.proto = ProtoArray(
            justified_epoch=store.justified_checkpoint.epoch,
            finalized_epoch=store.finalized_checkpoint.epoch,
        )
        self.proto.on_block(anchor)
        self.votes: List[VoteTracker] = []
        self.balances = store.justified_balances.copy()
        self.proposer_boost_root: Optional[bytes] = None
        self.proposer_boost_pct = proposer_boost_pct
        self.slots_per_epoch = slots_per_epoch
        self._applied_boost: Optional[tuple] = None  # (root, amount) in current weights
        self._head: Optional[bytes] = None

    # -- time ---------------------------------------------------------------

    def update_time(self, slot: int) -> None:
        # boost lives for one slot: clear it only when the slot ADVANCES —
        # spec on_tick resets proposer_boost_root at slot boundaries, so an
        # intra-slot tick (e.g. the 1/3-slot attestation mark) must keep it
        if slot > self.store.current_slot:
            self.proposer_boost_root = None
        self.store.current_slot = slot

    # -- block import --------------------------------------------------------

    def on_block(
        self,
        slot: int,
        block_root: bytes,
        parent_root: bytes,
        state_root: bytes,
        target_root: bytes,
        justified_checkpoint: Checkpoint,
        finalized_checkpoint: Checkpoint,
        justified_balances: Optional[np.ndarray] = None,
        is_timely_proposal: bool = False,
        execution_status: str = "pre-merge",
        execution_block_hash: bytes = b"\x00" * 32,
    ) -> None:
        if not self.proto.has_block(parent_root):
            raise ForkChoiceError("unknown parent")
        if justified_checkpoint.epoch > self.store.justified_checkpoint.epoch:
            self.store.justified_checkpoint = justified_checkpoint
            if justified_balances is not None:
                self.store.justified_balances = justified_balances
        if finalized_checkpoint.epoch > self.store.finalized_checkpoint.epoch:
            self.store.finalized_checkpoint = finalized_checkpoint
        if is_timely_proposal:
            self.proposer_boost_root = block_root
        self.proto.on_block(
            ProtoNode(
                slot=slot,
                block_root=block_root,
                parent_root=parent_root,
                state_root=state_root,
                target_root=target_root,
                justified_epoch=justified_checkpoint.epoch,
                finalized_epoch=finalized_checkpoint.epoch,
                execution_status=execution_status,
                execution_block_hash=execution_block_hash,
            )
        )

    # -- attestations --------------------------------------------------------

    def on_attestation(self, validator_indices: Sequence[int], block_root: bytes, target_epoch: int) -> None:
        """Record LMD votes (forkChoice.ts onAttestation).  Unknown blocks
        must be filtered by the caller (unknown-block sync queue)."""
        for vi in validator_indices:
            vi = int(vi)
            while len(self.votes) <= vi:
                self.votes.append(VoteTracker())
            vote = self.votes[vi]
            if target_epoch > vote.next_epoch:
                vote.next_epoch = target_epoch
                vote.next_root = block_root

    # -- head ----------------------------------------------------------------

    def update_head(self) -> bytes:
        """Score pass + find_head (forkChoice.ts updateHead)."""
        new_balances = self.store.justified_balances
        deltas = compute_deltas(self.proto.indices, self.votes, self.balances, new_balances)
        # undo the previously applied boost, apply the current one
        # (forkChoice.ts previousProposerBoostRoot handling)
        if self._applied_boost is not None:
            old_root, old_amount = self._applied_boost
            oi = self.proto.indices.get(old_root)
            if oi is not None:
                deltas[oi] -= old_amount
            self._applied_boost = None
        if self.proposer_boost_root is not None:
            bi = self.proto.indices.get(self.proposer_boost_root)
            if bi is not None:
                # average committee weight per slot (getProposerScore:
                # total active balance / SLOTS_PER_EPOCH — preset-dependent,
                # 8 on minimal, 32 on mainnet)
                committee_weight = int(new_balances.sum()) // max(1, self.slots_per_epoch)
                boost = committee_weight * self.proposer_boost_pct // 100
                deltas[bi] += boost
                self._applied_boost = (self.proposer_boost_root, boost)
        self.proto.apply_score_changes(
            deltas,
            self.store.justified_checkpoint.epoch,
            self.store.finalized_checkpoint.epoch,
        )
        self.balances = new_balances.copy()
        self._head = self.proto.find_head(self.store.justified_checkpoint.root)
        return self._head

    def get_head(self) -> bytes:
        if self._head is None:
            return self.update_head()
        return self._head

    # -- maintenance ---------------------------------------------------------

    def prune(self, finalized_root: bytes):
        return self.proto.prune(finalized_root)

    def has_block(self, root: bytes) -> bool:
        return self.proto.has_block(root)

    def get_block(self, root: bytes):
        return self.proto.get_node(root)

    def is_descendant(self, ancestor: bytes, descendant: bytes) -> bool:
        return self.proto.is_descendant(ancestor, descendant)

    def get_ancestor(self, root: bytes, slot: int) -> Optional[bytes]:
        return self.proto.get_ancestor(root, slot)

    # -- optimistic sync (forkChoice.ts validateLatestHash) ------------------

    def on_valid_execution(self, root: bytes) -> None:
        for node in self.proto.iterate_ancestors(root):
            if node.execution_status == "syncing":
                node.execution_status = "valid"

    def on_invalid_execution(self, root: bytes) -> None:
        """Mark a block and all its descendants invalid, zero their weight
        out of every ancestor, and refresh best-child/best-descendant
        pointers so the next find_head provably lands on a valid branch
        (protoArray.ts propagateInvalidation + the applyScoreChanges
        invalid-node delta override)."""
        idx = self.proto.indices.get(root)
        if idx is None:
            return
        bad = {root}
        self.proto.nodes[idx].execution_status = "invalid"
        # descendants come after the parent: ProtoArray.on_block appends and
        # prune() preserves order, so one forward sweep covers the subtree
        for i in range(idx + 1, len(self.proto.nodes)):
            node = self.proto.nodes[i]
            if node.parent_root in bad:
                node.execution_status = "invalid"
                bad.add(node.block_root)
        # zero-delta score pass: apply_score_changes forces invalid nodes'
        # weight to 0 (subtracting the subtree from ancestors) and re-runs
        # the best-pointer bubble so pointers never target invalid nodes
        self.proto.apply_score_changes(
            np.zeros(len(self.proto.nodes), dtype=np.int64),
            self.store.justified_checkpoint.epoch,
            self.store.finalized_checkpoint.epoch,
        )
        self._head = None
