"""Proto-array LMD-GHOST fork choice.

Reference: packages/fork-choice (SURVEY §2.3).
"""

from .fork_choice import Checkpoint, ForkChoice, ForkChoiceError, ForkChoiceStore  # noqa: F401
from .proto_array import (  # noqa: F401
    ProtoArray,
    ProtoArrayError,
    ProtoNode,
    VoteTracker,
    compute_deltas,
)
