"""Proto-array LMD-GHOST fork choice core.

Reference: packages/fork-choice/src/protoArray/protoArray.ts:9 and
computeDeltas.ts:14.  The proto-array idea: keep blocks in insertion order
(parents before children), store per-node weight, and maintain
best_child/best_descendant pointers so find_head is O(1) after an O(n)
backward score pass.

The score pass is array-oriented (flat numpy deltas; single reversed
sweep) which is both the reference's own design and the layout a device
offload of the weight accumulation would use.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class ProtoNode:
    slot: int
    block_root: bytes
    parent_root: Optional[bytes]
    state_root: bytes
    target_root: bytes
    justified_epoch: int
    finalized_epoch: int
    parent: Optional[int] = None
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None
    # execution status for optimistic sync (forkChoice.ts ExecutionStatus)
    execution_status: str = "pre-merge"  # pre-merge | syncing | valid | invalid
    # EL block hash carried for engine_forkchoiceUpdated calls
    execution_block_hash: bytes = b"\x00" * 32


@dataclasses.dataclass
class VoteTracker:
    """One attester's latest vote (computeDeltas.ts VoteTracker).

    ``next_epoch`` starts at -1, NOT 0: the spec updates a latest message
    whenever none exists yet, so a genesis-epoch attestation
    (target_epoch == 0) must pass the ``target_epoch > next_epoch``
    freshness check on a fresh tracker — with a 0 sentinel every epoch-0
    vote was silently dropped from fork choice."""

    current_root: bytes = b"\x00" * 32
    next_root: bytes = b"\x00" * 32
    next_epoch: int = -1


def compute_deltas(
    indices: Dict[bytes, int],
    votes: List[VoteTracker],
    old_balances: np.ndarray,
    new_balances: np.ndarray,
) -> np.ndarray:
    """Per-node weight deltas from vote movements (computeDeltas.ts:14)."""
    deltas = np.zeros(len(indices), dtype=np.int64)
    zero = b"\x00" * 32
    for i, vote in enumerate(votes):
        if vote.current_root == zero and vote.next_root == zero:
            continue
        old_bal = int(old_balances[i]) if i < len(old_balances) else 0
        new_bal = int(new_balances[i]) if i < len(new_balances) else 0
        if vote.current_root != vote.next_root or old_bal != new_bal:
            # the zero root is the "no vote yet" sentinel, never a block —
            # skip it explicitly so an anchor whose root happens to be low
            # can't absorb phantom deltas
            cur = indices.get(vote.current_root) if vote.current_root != zero else None
            if cur is not None:
                deltas[cur] -= old_bal
            nxt = indices.get(vote.next_root) if vote.next_root != zero else None
            if nxt is not None:
                deltas[nxt] += new_bal
            vote.current_root = vote.next_root
    return deltas


class ProtoArrayError(Exception):
    pass


class ProtoArray:
    def __init__(self, justified_epoch: int, finalized_epoch: int):
        self.prune_threshold = 256
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[bytes, int] = {}

    # -- insertion -----------------------------------------------------------

    def on_block(self, node: ProtoNode) -> None:
        if node.block_root in self.indices:
            return
        node_index = len(self.nodes)
        node.parent = self.indices.get(node.parent_root) if node.parent_root else None
        self.indices[node.block_root] = node_index
        self.nodes.append(node)
        if node.parent is not None:
            self._maybe_update_best_child_and_descendant(node.parent, node_index)

    # -- scoring -------------------------------------------------------------

    def apply_score_changes(
        self, deltas: np.ndarray, justified_epoch: int, finalized_epoch: int
    ) -> None:
        """Backward pass: add deltas, bubble child weights into parents,
        refresh best pointers (protoArray.ts applyScoreChanges)."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("delta length mismatch")
        if justified_epoch != self.justified_epoch or finalized_epoch != self.finalized_epoch:
            self.justified_epoch = justified_epoch
            self.finalized_epoch = finalized_epoch
        deltas = deltas.copy()
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            delta = int(deltas[i])
            if node.execution_status == "invalid":
                # EL-invalidated subtree: force weight to 0 and propagate
                # only that change upward — stray vote-removal deltas on an
                # already-zeroed node are discarded (ancestors shed the
                # subtree the moment it was invalidated)
                delta = -node.weight
            node.weight += delta
            if node.weight < 0:
                raise ProtoArrayError("negative node weight")
            if node.parent is not None:
                deltas[node.parent] += delta
                self._maybe_update_best_child_and_descendant(node.parent, i)

    # -- head ----------------------------------------------------------------

    def find_head(self, justified_root: bytes) -> bytes:
        ji = self.indices.get(justified_root)
        if ji is None:
            raise ProtoArrayError("justified root unknown to proto array")
        node = self.nodes[ji]
        best = node.best_descendant if node.best_descendant is not None else ji
        head = self.nodes[best]
        if not self._node_is_viable_for_head(head) and head.block_root != justified_root:
            raise ProtoArrayError("head is not viable")
        return head.block_root

    # -- pruning -------------------------------------------------------------

    def prune(self, finalized_root: bytes) -> List[ProtoNode]:
        """Drop everything before the finalized root (protoArray.ts
        maybePrune); returns removed nodes for the caller to clean up."""
        fi = self.indices.get(finalized_root)
        if fi is None:
            raise ProtoArrayError("finalized root unknown")
        if fi < self.prune_threshold:
            return []
        removed = self.nodes[:fi]
        self.nodes = self.nodes[fi:]
        for n in removed:
            del self.indices[n.block_root]
        for root in list(self.indices):
            self.indices[root] -= fi
        for n in self.nodes:
            if n.parent is not None:
                n.parent = n.parent - fi if n.parent >= fi else None
            if n.best_child is not None:
                n.best_child = n.best_child - fi if n.best_child >= fi else None
            if n.best_descendant is not None:
                n.best_descendant = n.best_descendant - fi if n.best_descendant >= fi else None
        return removed

    # -- queries -------------------------------------------------------------

    def get_node(self, root: bytes) -> Optional[ProtoNode]:
        i = self.indices.get(root)
        return self.nodes[i] if i is not None else None

    def has_block(self, root: bytes) -> bool:
        return root in self.indices

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        ai = self.indices.get(ancestor_root)
        if ai is None:
            return False
        i = self.indices.get(descendant_root)
        while i is not None and i >= ai:
            if i == ai:
                return True
            i = self.nodes[i].parent
        return False

    def get_ancestor(self, root: bytes, slot: int) -> Optional[bytes]:
        i = self.indices.get(root)
        while i is not None:
            node = self.nodes[i]
            if node.slot <= slot:
                return node.block_root
            i = node.parent
        return None

    def iterate_ancestors(self, root: bytes):
        i = self.indices.get(root)
        while i is not None:
            node = self.nodes[i]
            yield node
            i = node.parent

    # -- internals -----------------------------------------------------------

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """Only vote for nodes whose justified/finalized agree with the
        store (protoArray.ts nodeIsViableForHead), and never for nodes the
        execution layer marked invalid."""
        if node.execution_status == "invalid":
            return False
        jus_ok = node.justified_epoch == self.justified_epoch or self.justified_epoch == 0
        fin_ok = node.finalized_epoch == self.finalized_epoch or self.finalized_epoch == 0
        return jus_ok and fin_ok

    def _node_leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child_and_descendant(self, parent_i: int, child_i: int) -> None:
        parent = self.nodes[parent_i]
        child = self.nodes[child_i]
        child_leads = self._node_leads_to_viable_head(child)

        child_best_desc = child.best_descendant if child.best_descendant is not None else child_i

        def make_child_best():
            parent.best_child = child_i
            parent.best_descendant = child_best_desc

        def make_no_best():
            parent.best_child = None
            parent.best_descendant = None

        if parent.best_child is None:
            if child_leads:
                make_child_best()
            return
        if parent.best_child == child_i:
            if not child_leads:
                make_no_best()
            else:
                parent.best_descendant = child_best_desc
            return
        best = self.nodes[parent.best_child]
        best_leads = self._node_leads_to_viable_head(best)
        if child_leads and not best_leads:
            make_child_best()
        elif child_leads and best_leads:
            # tie-break: higher weight wins; equal weights -> higher root
            if child.weight > best.weight or (
                child.weight == best.weight and child.block_root >= best.block_root
            ):
                make_child_best()
        elif not child_leads and best_leads:
            pass
        else:
            make_no_best()
