"""Pluggable AST checkers encoding the project's hot-path discipline.

Each checker is a small class with a stable ``rule`` id and a
``check(path, tree, source)`` method returning ``Violation``s; the
driver parses each file once and fans the tree out to every checker.
Scope is the ``lodestar_tpu/`` package (the shipping tree — tests and
tools lint themselves by running, not by rule).

Rules (rationale + incident history in docs/static_analysis.md):

- ``async-blocking-sync``   blocking device/future syncs lexically inside
  ``async def`` (``.result()``, ``.block_until_ready()``, ``device_get``,
  ``time.sleep``) — each one stalls the event loop for a whole dispatch
  latency; route through ``asyncio.to_thread`` instead (passing the bound
  method, e.g. ``to_thread(pending.result)``, is the sanctioned shape and
  does not trip the rule: it is a reference, not a call).
- ``tracing-wallclock``     ``time.time()`` in tracing code.  Spans from
  different threads must share one monotonic clock
  (``time.monotonic_ns``); wall clock steps under NTP and breaks span
  ordering.  Fires anywhere under ``lodestar_tpu/tracing/`` and on any
  ``time.time()`` nested inside a TRACER call's arguments elsewhere.
- ``await-holding-lock``    ``await`` lexically inside a ``with`` block
  whose context manager looks like a (threading) lock.  A thread lock
  held across a suspension point blocks every other thread touching that
  lock for the awaited duration — and deadlocks if the awaited task needs
  the lock.
- ``bls-silent-except``     ``except`` arms in ``crypto/bls/`` or
  ``chain/bls_pool.py`` that neither journal, count, nor re-raise.
  Silent swallows on the dispatch path hide exactly the faults the chaos
  plane injects (lost devices, failed compiles, dropped verdicts).
- ``metrics-coverage``      every metric registered in
  ``metrics/registry.py`` must be referenced by a dashboard or docs
  (absorbed from tools/check_metrics_coverage.py).

Suppression: ``# lint: disable=<rule>`` on the flagged line
(report.suppressed_rules).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence

from .report import Violation, filter_suppressed

# ---------------------------------------------------------------------------
# checker base + helpers
# ---------------------------------------------------------------------------


class Checker:
    rule: str = "base"
    description: str = ""

    def check(self, path: str, tree: ast.AST, source: str) -> List[Violation]:
        raise NotImplementedError


def _terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain (``a.b.c`` -> c)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_dotted(node: ast.AST, *parts: str) -> bool:
    """True when ``node`` is exactly the dotted name parts (e.g. time.time)."""
    for part in reversed(parts[1:]):
        if not (isinstance(node, ast.Attribute) and node.attr == part):
            return False
        node = node.value
    return isinstance(node, ast.Name) and node.id == parts[0]


def _walk_skip_nested_defs(body: Sequence[ast.stmt]):
    """Yield nodes in ``body`` without descending into nested function
    definitions (their bodies run in their own execution context)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# async-blocking-sync
# ---------------------------------------------------------------------------

_BLOCKING_ATTRS = {"result", "block_until_ready"}
_BLOCKING_DOTTED = (("time", "sleep"), ("jax", "device_get"))
_BLOCKING_NAMES = {"device_get"}


class AsyncBlockingSyncChecker(Checker):
    rule = "async-blocking-sync"
    description = "blocking sync call lexically inside async def"

    def check(self, path: str, tree: ast.AST, source: str) -> List[Violation]:
        out: List[Violation] = []
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _walk_skip_nested_defs(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                blocking = (
                    (isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS)
                    or any(_is_dotted(f, *d) for d in _BLOCKING_DOTTED)
                    or (isinstance(f, ast.Name) and f.id in _BLOCKING_NAMES)
                )
                if blocking:
                    name = _terminal_name(f) or "<call>"
                    out.append(
                        Violation(
                            self.rule, path, node.lineno,
                            f"blocking call {name}() inside async def "
                            f"{fn.name} — wrap in asyncio.to_thread "
                            f"(pass the bound method, don't call it)",
                        )
                    )
        return out


# ---------------------------------------------------------------------------
# tracing-wallclock
# ---------------------------------------------------------------------------


def _is_tracer_call(call: ast.Call) -> bool:
    """A call on the TRACER singleton (TRACER.add_span(...), tracer.instant
    via any name ending in the tracer method set)."""
    f = call.func
    for sub in ast.walk(f):
        if isinstance(sub, ast.Name) and sub.id == "TRACER":
            return True
    return isinstance(f, ast.Attribute) and f.attr in ("add_span", "instant")


class TracingWallclockChecker(Checker):
    rule = "tracing-wallclock"
    description = "time.time() in tracing code (monotonic_ns only)"

    def _flag(self, path, node, out, where):
        out.append(
            Violation(
                self.rule, path, node.lineno,
                f"time.time() {where} — tracing timestamps must be "
                f"time.monotonic_ns() (one clock across threads, no NTP steps)",
            )
        )

    def check(self, path: str, tree: ast.AST, source: str) -> List[Violation]:
        out: List[Violation] = []
        in_tracing_pkg = "tracing" in os.path.normpath(path).split(os.sep)
        if in_tracing_pkg:
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and _is_dotted(
                    node.func, "time", "time"
                ):
                    self._flag(path, node, out, "in the tracing package")
            return out
        # elsewhere: flag time.time() nested in a TRACER call's arguments
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_tracer_call(node)):
                continue
            for arg in node.args + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call) and _is_dotted(
                        sub.func, "time", "time"
                    ):
                        self._flag(path, sub, out, "feeding a TRACER span")
        return out


# ---------------------------------------------------------------------------
# await-holding-lock
# ---------------------------------------------------------------------------


def _looks_like_lock(expr: ast.AST) -> bool:
    name = _terminal_name(expr)
    if name is None and isinstance(expr, ast.Call):
        name = _terminal_name(expr.func)
    return name is not None and "lock" in name.lower()


class AwaitHoldingLockChecker(Checker):
    rule = "await-holding-lock"
    description = "await while holding a (threading) lock"

    def check(self, path: str, tree: ast.AST, source: str) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(tree):
            # sync `with` only: `async with` managers are asyncio locks,
            # which are designed to be held across awaits
            if not isinstance(node, ast.With):
                continue
            if not any(_looks_like_lock(i.context_expr) for i in node.items):
                continue
            for inner in _walk_skip_nested_defs(node.body):
                if isinstance(inner, ast.Await):
                    lock = next(
                        _terminal_name(i.context_expr) or "<lock>"
                        for i in node.items
                        if _looks_like_lock(i.context_expr)
                    )
                    out.append(
                        Violation(
                            self.rule, path, inner.lineno,
                            f"await while holding {lock} (acquired line "
                            f"{node.lineno}) — a thread lock held across a "
                            f"suspension point stalls every other thread",
                        )
                    )
        return out


# ---------------------------------------------------------------------------
# bls-silent-except
# ---------------------------------------------------------------------------

#: call terminal names that count as journaling / counting / propagating:
#: journal (JOURNAL.record), any logger method (WARNING+ mirrors into the
#: journal via utils/logger.JournalHandler), metric increments, and
#: exception propagation onto a future
_EXCEPT_HANDLED_CALLS = {
    "record", "debug", "info", "warning", "error", "exception", "critical",
    "log", "inc", "set_exception",
}
#: substrings marking a dedicated accounting helper (``_pack_reject``,
#: ``_count_drop``, ``_degrade``, ``_record_executor_failure``,
#: ``_native_fallback_verdict``, ``maybe_raise`` re-injection, ...)
_EXCEPT_HANDLED_SUBSTRINGS = (
    "reject", "drop", "count", "degrade", "record", "fallback", "requeue",
)


def _bls_scope(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    if "crypto" in parts and "bls" in parts:
        return True
    return parts[-2:] == ["chain", "bls_pool.py"]


class BlsSilentExceptChecker(Checker):
    """Every ``except`` arm on the BLS verification path must journal,
    count, or re-raise.  A silent swallow here turns a lost device, a
    failed compile, or a dropped verdict into an invisible non-event —
    exactly the faults the chaos plane (lodestar_tpu/chaos) injects to
    prove diagnosability.  Scope: ``crypto/bls/`` and
    ``chain/bls_pool.py`` (the dispatch path proper; the rest of the tree
    has its own disciplines)."""

    rule = "bls-silent-except"
    description = "except arm on the BLS path swallows without evidence"

    def _handled(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.AugAssign):
                return True  # counter += n
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name is None:
                    continue
                low = name.lower()
                if name in _EXCEPT_HANDLED_CALLS or any(
                    sub in low for sub in _EXCEPT_HANDLED_SUBSTRINGS
                ):
                    return True
        return False

    def check(self, path: str, tree: ast.AST, source: str) -> List[Violation]:
        if not _bls_scope(path):
            return []
        out: List[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if self._handled(handler):
                    continue
                exc = (
                    ast.unparse(handler.type) if handler.type is not None
                    else "<bare>"
                )
                out.append(
                    Violation(
                        self.rule, path, handler.lineno,
                        f"except {exc} swallows without journaling, "
                        f"counting, or re-raising — a fault on the BLS "
                        f"path must leave evidence (JOURNAL.record / "
                        f"logger.* / a counter / raise)",
                    )
                )
        return out


# ---------------------------------------------------------------------------
# metrics-coverage (absorbed from tools/check_metrics_coverage.py)
# ---------------------------------------------------------------------------


class MetricsCoverageChecker(Checker):
    """Repo-level checker: runs once (on registry.py) rather than per file."""

    rule = "metrics-coverage"
    description = "registered metric referenced by no dashboard and no doc"

    def __init__(self, repo: str):
        self.repo = repo

    def check(self, path: str, tree: ast.AST, source: str) -> List[Violation]:
        from . import metrics_coverage

        report = metrics_coverage.check(self.repo)
        out: List[Violation] = []
        for metric, cov in report.items():
            if cov["dashboards"] or cov["docs"]:
                continue
            line = 0
            for i, text in enumerate(source.splitlines(), 1):
                if metric in text:
                    line = i
                    break
            out.append(
                Violation(
                    self.rule, path, line,
                    f"metric {metric} appears in no dashboards/*.json and "
                    f"no docs/*.md — add a panel or a docs table row",
                )
            )
        return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

DEFAULT_CHECKERS = (
    AsyncBlockingSyncChecker,
    TracingWallclockChecker,
    AwaitHoldingLockChecker,
    BlsSilentExceptChecker,
)

_REGISTRY_REL = os.path.join("lodestar_tpu", "metrics", "registry.py")


def lint_source(
    source: str, path: str, checkers: Optional[Sequence[Checker]] = None
) -> List[Violation]:
    """Run checkers over one in-memory source (fixtures, editors).  ``path``
    is whatever the rules should scope on — it need not exist on disk."""
    if checkers is None:
        checkers = [c() for c in DEFAULT_CHECKERS]
    tree = ast.parse(source, filename=path)
    found: List[Violation] = []
    for checker in checkers:
        found.extend(checker.check(path, tree, source))
    return filter_suppressed(found, {path: source})


def iter_py_files(repo: str, rel_root: str = "lodestar_tpu"):
    root = os.path.join(repo, rel_root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                yield os.path.relpath(full, repo)


def run_ast_lint(
    repo: str,
    checkers: Optional[Sequence[Checker]] = None,
    with_metrics: bool = True,
) -> List[Violation]:
    """Lint every .py file under ``lodestar_tpu/`` plus the repo-level
    metrics-coverage rule.  Returns suppression-filtered violations."""
    if checkers is None:
        checkers = [c() for c in DEFAULT_CHECKERS]
    sources: Dict[str, str] = {}
    found: List[Violation] = []
    for rel in iter_py_files(repo):
        with open(os.path.join(repo, rel)) as f:
            src = f.read()
        sources[rel] = src
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            found.append(
                Violation("syntax-error", rel, e.lineno or 0, str(e.msg))
            )
            continue
        for checker in checkers:
            found.extend(checker.check(rel, tree, src))
    if with_metrics:
        reg = os.path.join(repo, _REGISTRY_REL)
        if os.path.exists(reg):
            with open(reg) as f:
                reg_src = f.read()
            sources[_REGISTRY_REL] = reg_src
            found.extend(
                MetricsCoverageChecker(repo).check(
                    _REGISTRY_REL, ast.parse(reg_src), reg_src
                )
            )
    return filter_suppressed(found, sources)
