"""Lock-discipline/race audit: instrumented locks + a deterministic
interleaving harness over the BLS hot path.

The PR-3 surface this exists for: ``BlsBatchPool._flush`` fans pack /
dispatch / result work out to ``asyncio.to_thread`` workers, which mutate
shared state on ``TpuBlsVerifier`` (stats counters, ``stage_seconds``),
its ``DeviceExecutor``s (the ``inflight`` slot accounting the least-loaded
scheduler reads), and the ``PointCache`` LRU.  A missed lock there is
invisible to tests that only check results — counters drift, the LRU
corrupts, placement double-books.

Detection is DETERMINISTIC, not probabilistic: guarded state is wrapped so
every mutation checks "does this thread hold the owning lock?" at the
call site.  The first unguarded mutation is flagged on its first
execution — no interleaving luck required; the multi-threaded stress run
exists to drive every hot-path code path (including the retry and
pipelined-flush arms) and to feed the lock-ORDER recorder, which builds
the acquisition graph across threads and reports cycles (inversions).

Pieces:

- ``AuditLock``       wraps ``threading.Lock``: owner thread tracking +
  acquisition-order edge recording.  Context-manager compatible, so it
  drops into any ``with self._lock:`` site unchanged.
- ``GuardedOrderedDict`` / ``GuardedDict``  mutation-checking containers.
- ``instrument_*``    swap a live verifier/cache's locks and containers
  for audited ones (reversible only by rebuilding the object — audits
  construct their own instances).
- ``audit_bls_pipeline``  the harness: a real ``TpuBlsVerifier`` with
  stub device programs (zero XLA work — the conftest compile guard stays
  quiet), a real ``BlsBatchPool`` flushing pipelined merged batches, real
  packing over real signature bytes, N worker threads + barrier-synced
  direct dispatch, tiny switch interval.  Returns the violations.
"""

from __future__ import annotations

import collections
import sys
import threading
from typing import Dict, List, Optional, Tuple

from .report import Violation

# ---------------------------------------------------------------------------
# auditor core
# ---------------------------------------------------------------------------


class LockAuditor:
    """Violation sink + lock-order graph for one audit run."""

    def __init__(self):
        self.violations: List[Violation] = []
        self._edges: Dict[Tuple[str, str], str] = {}
        self._meta = threading.Lock()
        self._tls = threading.local()

    # -- held-lock stack (per thread) --------------------------------------

    def _stack(self) -> List["AuditLock"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquire(self, lock: "AuditLock") -> None:
        st = self._stack()
        with self._meta:
            for held in st:
                if held is not lock:
                    self._edges.setdefault(
                        (held.name, lock.name),
                        f"{held.name} -> {lock.name} "
                        f"(thread {threading.current_thread().name})",
                    )
        st.append(lock)

    def on_release(self, lock: "AuditLock") -> None:
        st = self._stack()
        if lock in st:
            st.remove(lock)

    # -- findings ----------------------------------------------------------

    def record(self, rule: str, target: str, message: str) -> None:
        with self._meta:
            self.violations.append(
                Violation(rule, f"lock-audit:{target}", 0, message)
            )

    def unguarded(self, target: str, what: str, lock_name: str) -> None:
        self.record(
            "lock-unguarded-mutation",
            target,
            f"{what} mutated on thread "
            f"{threading.current_thread().name} without holding {lock_name}",
        )

    def lock_order_violations(self) -> List[Violation]:
        """Cycles in the acquisition graph = lock-order inversions."""
        with self._meta:
            edges = dict(self._edges)
        graph: Dict[str, List[str]] = collections.defaultdict(list)
        for a, b in edges:
            graph[a].append(b)
        out: List[Violation] = []
        seen_cycles = set()
        state: Dict[str, int] = {}  # 0 unvisited / 1 in-stack / 2 done

        def dfs(node: str, path: List[str]):
            state[node] = 1
            path.append(node)
            for nxt in graph.get(node, ()):
                if state.get(nxt, 0) == 1:
                    cycle = tuple(path[path.index(nxt):] + [nxt])
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(
                            Violation(
                                "lock-order-inversion",
                                "lock-audit:" + cycle[0],
                                0,
                                "lock acquisition cycle "
                                + " -> ".join(cycle)
                                + " — two threads taking these in opposite "
                                "order deadlock",
                            )
                        )
                elif state.get(nxt, 0) == 0:
                    dfs(nxt, path)
            path.pop()
            state[node] = 2

        for node in list(graph):
            if state.get(node, 0) == 0:
                dfs(node, [])
        return out

    def all_violations(self) -> List[Violation]:
        return list(self.violations) + self.lock_order_violations()


class AuditLock:
    """Instrumented ``threading.Lock``: drop-in for guard checks and
    acquisition-order recording.  NOT reentrant (same as threading.Lock)."""

    def __init__(self, auditor: LockAuditor, name: str):
        self.auditor = auditor
        self.name = name
        self._lock = threading.Lock()
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self.auditor.on_acquire(self)
        return got

    def release(self) -> None:
        self._owner = None
        self.auditor.on_release(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> "AuditLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# ---------------------------------------------------------------------------
# guarded containers + attribute guards
# ---------------------------------------------------------------------------


class GuardedOrderedDict(collections.OrderedDict):
    """OrderedDict flagging any mutation (or LRU read-reorder) performed
    without the owning AuditLock held."""

    def __init__(self, auditor, lock, target, items=()):
        # populate BEFORE arming the guard: OrderedDict.__init__ routes
        # every pre-existing item through our __setitem__, and a warm
        # cache being instrumented must not read as unguarded mutation
        super().__init__(items)
        self._aud = (auditor, lock, target)

    def _check(self, what: str) -> None:
        aud = getattr(self, "_aud", None)
        if aud is None:
            return
        auditor, lock, target = aud
        if not lock.held_by_current_thread():
            auditor.unguarded(target, what, lock.name)

    def __setitem__(self, key, value):
        self._check("item set")
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._check("item del")
        super().__delitem__(key)

    def get(self, key, default=None):
        self._check("LRU get")
        return super().get(key, default)

    def move_to_end(self, key, last=True):
        self._check("move_to_end")
        super().move_to_end(key, last)

    def popitem(self, last=True):
        self._check("popitem")
        return super().popitem(last)


class GuardedDict(dict):
    """dict flagging unguarded mutation (reads stay free: GIL-atomic)."""

    def __init__(self, auditor, lock, target, items=()):
        super().__init__(items)  # arm the guard only after pre-population
        self._aud = (auditor, lock, target)

    def __setitem__(self, key, value):
        aud = getattr(self, "_aud", None)
        if aud is not None:
            auditor, lock, target = aud
            if not lock.held_by_current_thread():
                auditor.unguarded(target, f"[{key!r}] set", lock.name)
        super().__setitem__(key, value)


# id(obj) -> (auditor, lock, target, guarded attr names); populated by the
# instrument_* helpers, consulted by the audited __setattr__ overrides
_ATTR_GUARDS: Dict[int, Tuple[LockAuditor, AuditLock, str, frozenset]] = {}


def _audited_setattr(obj, name: str, value) -> None:
    guard = _ATTR_GUARDS.get(id(obj))
    if guard is not None:
        auditor, lock, target, attrs = guard
        if name in attrs and not lock.held_by_current_thread():
            auditor.unguarded(target, f".{name} write", lock.name)


def _make_audited_class(base: type) -> type:
    """Subclass with a guard-checking __setattr__; __slots__ = () keeps the
    instance layout identical so live instances can be re-classed."""

    class Audited(base):
        __slots__ = ()

        def __setattr__(self, name, value):
            _audited_setattr(self, name, value)
            super().__setattr__(name, value)

    Audited.__name__ = f"Audited{base.__name__}"
    return Audited


# ---------------------------------------------------------------------------
# instrumentation of the real hot-path objects
# ---------------------------------------------------------------------------

# verifier counters that to_thread workers mutate concurrently — all must
# be written under TpuBlsVerifier._stats_lock
VERIFIER_GUARDED_ATTRS = frozenset(
    {
        "dispatches",
        "sets_verified",
        "padding_wasted",
        "host_final_exps",
        "fused_fallbacks",
        "pack_rejected",
        "pack_cache_hits",
        "pack_cache_misses",
        "batches_requeued",
        "native_fallbacks",
        "sharded_batches",
        "sharded_fallbacks",
    }
)

POINT_CACHE_GUARDED_ATTRS = frozenset({"hits", "misses"})


def instrument_point_cache(cache, auditor: LockAuditor, target: str = "PointCache"):
    from ..crypto.bls.verifier import PointCache

    lock = AuditLock(auditor, f"{target}._lock")
    cache._lock = lock
    cache._data = GuardedOrderedDict(auditor, lock, f"{target}._data", cache._data)
    cache.__class__ = _make_audited_class(PointCache)
    _ATTR_GUARDS[id(cache)] = (auditor, lock, target, POINT_CACHE_GUARDED_ATTRS)
    return cache


def instrument_verifier(verifier, auditor: LockAuditor, target: str = "TpuBlsVerifier"):
    """Swap the verifier's locks for AuditLocks and wrap every shared
    mutable surface: scheduler (executor ``inflight``), stats counters,
    ``stage_seconds``, and the pack-side ``PointCache``."""
    from ..crypto.bls.tpu_verifier import DeviceExecutor, TpuBlsVerifier

    sched = AuditLock(auditor, f"{target}._sched_lock")
    stats = AuditLock(auditor, f"{target}._stats_lock")
    verifier._sched_lock = sched
    verifier._stats_lock = stats
    verifier.stage_seconds = GuardedDict(
        auditor, stats, f"{target}.stage_seconds", verifier.stage_seconds
    )
    audited_exec = _make_audited_class(DeviceExecutor)
    for ex in verifier._executors:
        ex.__class__ = audited_exec
        _ATTR_GUARDS[id(ex)] = (
            auditor, sched, f"{target}.DeviceExecutor[{ex.name}]",
            frozenset({"inflight"}),
        )
    verifier.__class__ = _make_audited_class(TpuBlsVerifier)
    _ATTR_GUARDS[id(verifier)] = (auditor, stats, target, VERIFIER_GUARDED_ATTRS)
    instrument_point_cache(verifier.point_cache, auditor, f"{target}.point_cache")
    return verifier


def release_instrumentation(*objs) -> None:
    for obj in objs:
        _ATTR_GUARDS.pop(id(obj), None)


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------


def _make_sets(n: int, start: int = 0):
    from ..crypto.bls.api import interop_secret_key
    from ..crypto.bls.verifier import SingleSignatureSet

    out = []
    for i in range(start, start + n):
        sk = interop_secret_key(i % 64)
        msg = bytes([i % 256, (i // 256) % 256]) * 16
        out.append(
            SingleSignatureSet(
                pubkey=sk.to_public_key(),
                signing_root=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


def _stub_verifier(point_cache_size: int = 64):
    """Real TpuBlsVerifier (real pack, real scheduler, real counters) whose
    per-executor programs are host stubs — zero XLA trace/compile work."""
    from ..crypto.bls.tpu_verifier import TpuBlsVerifier

    v = TpuBlsVerifier(
        buckets=(4,), fused=False, host_final_exp=False,
        point_cache_size=point_cache_size,
    )
    for ex in v._executors:
        ex.compiled[(4, False, False)] = lambda *a: True
    return v


def audit_bls_pipeline(
    jobs: int = 6,
    sets_per_job: int = 2,
    threads: int = 4,
    point_cache_size: int = 64,
    verifier_mutator=None,
) -> List[Violation]:
    """Drive the instrumented BLS hot path end to end and return every
    lock-discipline violation observed.

    Two phases, both over ONE instrumented verifier:

    1. The asyncio pool path: a real ``BlsBatchPool`` (pipeline_depth=2)
       flushing concurrent jobs through ``to_thread`` pack workers — the
       exact PR-3 topology.
    2. Barrier-synced worker threads doing direct pack/dispatch/result
       cycles plus PointCache put/get hammering, with a tiny interpreter
       switch interval to shuffle thread interleavings for the lock-order
       recorder.

    ``verifier_mutator`` (tests): called with the verifier AFTER
    instrumentation — mutation tests use it to strip a lock and prove the
    audit turns red."""
    import asyncio
    import time

    auditor = LockAuditor()
    v = _stub_verifier(point_cache_size)
    instrument_verifier(v, auditor)
    if verifier_mutator is not None:
        verifier_mutator(v)
    guard_ids = [v, v.point_cache] + list(v._executors)

    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        # -- phase 1: the pool path (flush -> dispatch -> executor) --------
        from ..chain.bls_pool import BlsBatchPool

        async def pool_run():
            pool = BlsBatchPool(
                v, pipeline_depth=2, flush_threshold=4, max_buffer_wait=0.001
            )
            results = await asyncio.gather(
                *(
                    pool.verify_signature_sets(_make_sets(sets_per_job, i * 7))
                    for i in range(jobs)
                )
            )
            pool.close()
            return results

        asyncio.run(pool_run())

        # -- phase 2: barrier-synced direct dispatch + cache hammer --------
        barrier = threading.Barrier(threads)
        errors: List[BaseException] = []

        def worker(wid: int):
            try:
                sets = _make_sets(sets_per_job, 100 + wid * 3)
                barrier.wait(timeout=30)
                for rep in range(3):
                    pending = v.verify_signature_sets_async(sets)
                    for i in range(6):
                        key = b"K" + bytes([wid, rep, i % 2])
                        v.point_cache.put(key, (wid, rep))
                        v.point_cache.get(key)
                    pending.result()
            except BaseException as e:  # noqa: BLE001 - report, don't hang
                errors.append(e)

        ts = [
            threading.Thread(target=worker, args=(i,), name=f"lock-audit-{i}")
            for i in range(threads)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        if errors:
            auditor.record(
                "lock-audit-error", "harness",
                f"worker raised: {errors[0]!r}",
            )
        time.sleep(0)  # let released workers finish metric writes
    finally:
        sys.setswitchinterval(old_interval)
        release_instrumentation(*guard_ids)

    # dedupe: one finding per (rule, target, first line of message class)
    seen = set()
    out: List[Violation] = []
    for viol in auditor.all_violations():
        key = (viol.rule, viol.path, viol.message.split(" on thread ")[0])
        if key in seen:
            continue
        seen.add(key)
        out.append(viol)
    return out
