"""Interval analysis over limb-arithmetic jaxprs: ``jaxpr-limb-overflow``.

The limb format (ops/limbs.py) does exact multi-precision integer
arithmetic in f32 digits; every op contract is a *digit-magnitude bound*
(strict < 2^8, products < 2^16, anti-diagonal sums < 2^22, everything
< 2^24 = the largest range where f32 represents every integer exactly).
A bound violation does not crash — it silently rounds, and the fused
pairing kernels can only hit it at scale (a 256-lane batch on real
hardware), long after tier-1 passed.  BENCH_r05's Mosaic splice bug and
the round-3 Kogge-Stone miscompile were both caught by *structural*
jaxpr rules; this rule closes the remaining class: arithmetic whose
*values* leave the exactly-representable range.

The auditor abstract-interprets a traced jaxpr over the interval domain
[lo, hi] (one interval per array — digit bounds are uniform across the
limb axis by construction):

- elementwise arithmetic, shape ops, reductions, ``dot_general``,
  scatter/gather and ``select_n`` propagate intervals directly;
- ``dot_general`` against a KNOWN CONSTANT operand (the MXU limb-multiply
  mapping: one-hot REP/TIL/ACC contractions, the RED fold matrix) is
  bounded per output column from the constant's actual positive/negative
  column sums — max_c(hi * P_c - lo * N_c) — instead of the generic
  interval-product times contraction-size rule, which over-approximates a
  one-hot contraction by the full contraction width (2500x for the flat
  outer product) and would falsely flag the MXU path;
- ``scan``/``while`` bodies run to an inductive fixpoint (the carry
  interval is widened to TOP if it fails to stabilize, so the analysis
  always terminates and never *under*-approximates);
- the ``d - floor(d * 2^-8) * 2^8`` split idiom (``limbs._split``, the
  heart of every carry) is pattern-matched so the modulo's [0, 255]
  range survives — naive interval subtraction would lose the correlation
  between ``d`` and its own floor and the carry chain would never
  converge;
- unknown primitives go to TOP: the rule only reports *proven*
  may-overflows (a finite interval exceeding the dtype bound), never
  "I could not prove safety" — plus a coverage ratio so the tests can
  assert the core entries are FULLY proven, not just unflagged.

``audit_limb_overflow()`` runs the registry of ops/limbs.py entries at
their documented input contracts (strict digits, the fp_sub loose
bounds, the carry_exact 2^24 ceiling) and returns ``Violation``s whose
path/line point at the offending *source line* via the jaxpr's
source_info — which is how the known-bad fixture fires exactly on its
``# VIOLATION`` marks.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .report import Violation

RULE = "jaxpr-limb-overflow"

INF = math.inf
TOP = (-INF, INF)

# largest integer ranges represented exactly per float dtype
_EXACT_BOUNDS = {
    "float32": float(1 << 24),
    "float64": float(1 << 53),
    "bfloat16": 256.0,
    "float16": 2048.0,
}

_SCAN_FIXPOINT_ITERS = 12


def _union(a, b):
    return (min(a[0], b[0]), max(a[1], b[1]))


def _is_finite(iv) -> bool:
    return math.isfinite(iv[0]) and math.isfinite(iv[1])


@dataclass
class Finding:
    file: str
    line: int
    prim: str
    lo: float
    hi: float
    bound: float
    entry: str = ""


@dataclass
class LimbReport:
    findings: List[Finding]
    float_outputs: int
    bounded_outputs: int

    @property
    def coverage(self) -> float:
        if not self.float_outputs:
            return 1.0
        return self.bounded_outputs / self.float_outputs


class _Analyzer:
    # constants above this size are not retained for the const-aware
    # dot_general rule (memory bound; far above the 2500x99 MXU one-hots)
    _CONST_VAL_MAX_SIZE = 1 << 22

    def __init__(self):
        self.findings: List[Finding] = []
        self.float_outputs = 0
        self.bounded_outputs = 0
        self._flagged_lines: set = set()
        # constvar -> actual numpy array, for const-aware dot bounds
        self._const_vals: Dict = {}

    # -- source mapping ---------------------------------------------------
    @staticmethod
    def _eqn_site(eqn) -> Tuple[str, int]:
        try:
            from jax._src import source_info_util

            frame = source_info_util.user_frame(eqn.source_info)
            if frame is not None:
                return frame.file_name, frame.start_line
        except Exception:
            pass
        return "", 0

    # -- env --------------------------------------------------------------
    @staticmethod
    def _read(env, defs, v):
        from jax._src import core as jcore

        if isinstance(v, jcore.Literal):
            import numpy as np

            arr = np.asarray(v.val)
            if arr.size == 0:
                return (0.0, 0.0)
            return (float(arr.min()), float(arr.max()))
        return env.get(v, TOP)

    def _record(self, eqn, outvals, env, defs):
        for var, iv in zip(eqn.outvars, outvals):
            env[var] = iv
            defs[var] = eqn
            dtype = getattr(getattr(var, "aval", None), "dtype", None)
            bound = _EXACT_BOUNDS.get(str(dtype)) if dtype is not None else None
            if bound is None:
                continue
            self.float_outputs += 1
            if _is_finite(iv):
                self.bounded_outputs += 1
                if iv[1] > bound or iv[0] < -bound:
                    fname, line = self._eqn_site(eqn)
                    key = (fname, line)
                    if key not in self._flagged_lines:
                        self._flagged_lines.add(key)
                        self.findings.append(Finding(
                            file=fname, line=line,
                            prim=eqn.primitive.name,
                            lo=iv[0], hi=iv[1], bound=bound,
                        ))

    # -- the split idiom --------------------------------------------------
    @staticmethod
    def _const_of(env, defs, v) -> Optional[float]:
        from jax._src import core as jcore

        if isinstance(v, jcore.Literal):
            import numpy as np

            arr = np.asarray(v.val)
            if arr.size and float(arr.min()) == float(arr.max()):
                return float(arr.min())
        iv = env.get(v)
        if iv is not None and iv[0] == iv[1]:
            return iv[0]
        return None

    def _match_mod_split(self, eqn, env, defs):
        """sub(x, mul(floor(mul(x, c)), c')) with c*c' ~= 1 and x in
        [0, exact-bound] is exactly ``x mod c'`` -> [0, c' - 1]."""
        from jax._src import core as jcore

        x, y = eqn.invars
        if isinstance(y, jcore.Literal) or isinstance(x, jcore.Literal):
            return None
        mul_out = defs.get(y)
        if mul_out is None or mul_out.primitive.name != "mul":
            return None
        floor_v, c2 = None, None
        for a, b in (mul_out.invars, reversed(mul_out.invars)):
            cv = self._const_of(env, defs, b)
            if cv is not None and not isinstance(a, jcore.Literal):
                floor_v, c2 = a, cv
                break
        if floor_v is None:
            return None
        floor_eqn = defs.get(floor_v)
        if floor_eqn is None or floor_eqn.primitive.name != "floor":
            return None
        inner = defs.get(floor_eqn.invars[0])
        if inner is None or inner.primitive.name != "mul":
            return None
        c1, matches_x = None, False
        for a, b in (inner.invars, reversed(inner.invars)):
            cv = self._const_of(env, defs, b)
            if cv is not None and a is x:
                c1, matches_x = cv, True
                break
        if not matches_x or c1 is None or c2 <= 0:
            return None
        if abs(c1 * c2 - 1.0) > 1e-9:
            return None
        xiv = self._read(env, defs, x)
        dtype = str(getattr(getattr(x, "aval", None), "dtype", ""))
        bound = _EXACT_BOUNDS.get(dtype, float(1 << 24))
        if xiv[0] < 0 or xiv[1] > bound:
            return None
        return (0.0, c2 - 1.0)

    # -- jaxpr walk -------------------------------------------------------
    def run(self, jaxpr, consts, in_intervals) -> List[Tuple[float, float]]:
        import numpy as np

        env: Dict = {}
        defs: Dict = {}
        for var, c in zip(jaxpr.constvars, consts):
            try:
                arr = np.asarray(c)
                env[var] = (float(arr.min()), float(arr.max())) if arr.size \
                    else (0.0, 0.0)
                if 0 < arr.size <= self._CONST_VAL_MAX_SIZE:
                    self._const_vals[var] = arr
            except Exception:
                env[var] = TOP
        for var, iv in zip(jaxpr.invars, in_intervals):
            env[var] = tuple(iv)
        for eqn in jaxpr.eqns:
            outvals = self._eval_eqn(eqn, env, defs)
            self._record(eqn, outvals, env, defs)
            self._fwd_const(eqn)
        return [self._read(env, defs, v) for v in jaxpr.outvars]

    def _fwd_const(self, eqn):
        """Keep the const-aware dot rule's view of a constant alive across
        value-preserving plumbing (jnp.asarray of a host constant traces as
        device_put; casts and layout moves likewise)."""
        import numpy as np

        if len(eqn.outvars) != 1 or not eqn.invars:
            return
        name = eqn.primitive.name
        arr = self._const_arr(eqn.invars[0])
        if arr is None:
            return
        try:
            if name in ("device_put", "copy", "stop_gradient"):
                self._const_vals[eqn.outvars[0]] = arr
            elif name == "convert_element_type":
                # bound the CONVERTED values (a narrowing cast may round)
                self._const_vals[eqn.outvars[0]] = np.asarray(arr).astype(
                    eqn.params["new_dtype"]
                )
            elif name == "transpose":
                self._const_vals[eqn.outvars[0]] = np.transpose(
                    arr, eqn.params.get("permutation")
                )
            elif name == "reshape":
                self._const_vals[eqn.outvars[0]] = np.reshape(
                    arr, eqn.params["new_sizes"]
                )
        except Exception:
            pass

    def _seed_consts(self, analyzer, outer_atoms, inner_vars):
        """Forward statically-known arrays across a call/control-flow
        boundary (pjit consts are lifted into invars; scan/cond/while pass
        their closure constants positionally)."""
        for outer, inner in zip(outer_atoms, inner_vars):
            arr = self._const_arr(outer)
            if arr is not None:
                analyzer._const_vals[inner] = arr

    def _subjaxpr(self, closed, in_ivs):
        return self.run(closed.jaxpr, closed.consts, in_ivs)

    def _eval_eqn(self, eqn, env, defs) -> List[Tuple[float, float]]:
        name = eqn.primitive.name
        ins = [self._read(env, defs, v) for v in eqn.invars]
        n_out = len(eqn.outvars)

        def mulspan(a, b):
            cands = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
            cands = [c if not math.isnan(c) else 0.0 for c in cands]
            return (min(cands), max(cands))

        if name == "add" or name == "add_any":
            return [(ins[0][0] + ins[1][0], ins[0][1] + ins[1][1])]
        if name == "sub":
            m = self._match_mod_split(eqn, env, defs)
            if m is not None:
                return [m]
            return [(ins[0][0] - ins[1][1], ins[0][1] - ins[1][0])]
        if name == "mul":
            return [mulspan(ins[0], ins[1])]
        if name == "div":
            lo, hi = ins[1]
            if lo > 0 or hi < 0:
                inv = (1.0 / hi, 1.0 / lo)
                return [mulspan(ins[0], inv)]
            return [TOP]
        if name == "neg":
            return [(-ins[0][1], -ins[0][0])]
        if name == "abs":
            lo, hi = ins[0]
            alo = 0.0 if lo <= 0 <= hi else min(abs(lo), abs(hi))
            return [(alo, max(abs(lo), abs(hi)))]
        if name == "sign":
            return [(-1.0, 1.0)]
        if name == "floor":
            return [(math.floor(ins[0][0]) if math.isfinite(ins[0][0]) else -INF,
                     math.floor(ins[0][1]) if math.isfinite(ins[0][1]) else INF)]
        if name in ("ceil", "round", "round_nearest_even"):
            lo, hi = ins[0]
            return [(lo - 1 if math.isfinite(lo) else -INF,
                     hi + 1 if math.isfinite(hi) else INF)]
        if name == "max":
            return [(max(ins[0][0], ins[1][0]), max(ins[0][1], ins[1][1]))]
        if name == "min":
            return [(min(ins[0][0], ins[1][0]), min(ins[0][1], ins[1][1]))]
        if name == "clamp":
            lo = max(ins[0][0], min(ins[1][0], ins[0][1]))
            hi = min(ins[2][1], max(ins[1][1], ins[2][0]))
            return [(min(lo, hi), max(lo, hi))]
        if name == "integer_pow":
            p = eqn.params.get("y", 1)
            cands = [ins[0][0] ** p, ins[0][1] ** p]
            if ins[0][0] <= 0 <= ins[0][1]:
                cands.append(0.0)
            return [(min(cands), max(cands))]
        if name in ("square",):
            return [self._eval_pow2(ins[0])]
        if name == "sqrt":
            lo, hi = ins[0]
            return [(math.sqrt(max(lo, 0.0)),
                     math.sqrt(hi) if math.isfinite(hi) and hi >= 0 else INF)]
        if name in (
            "reshape", "squeeze", "expand_dims", "broadcast_in_dim",
            "transpose", "rev", "copy", "stop_gradient", "slice",
            "dynamic_slice", "gather", "device_put",
        ):
            return [ins[0]] * n_out
        if name == "convert_element_type":
            return [ins[0]]
        if name == "concatenate":
            out = ins[0]
            for iv in ins[1:]:
                out = _union(out, iv)
            return [out]
        if name == "pad":
            return [_union(ins[0], ins[1])]
        if name in ("dynamic_update_slice",):
            return [_union(ins[0], ins[1])]
        if name in ("scatter", "scatter-update"):
            return [_union(ins[0], ins[-1])]
        if name in ("scatter-add", "scatter_add"):
            op, upd = ins[0], ins[-1]
            return [(op[0] + min(0.0, upd[0]), op[1] + max(0.0, upd[1]))]
        if name == "select_n":
            out = ins[1]
            for iv in ins[2:]:
                out = _union(out, iv)
            return [out]
        if name in ("eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not",
                    "xor", "is_finite", "reduce_and", "reduce_or"):
            return [(0.0, 1.0)] * n_out
        if name == "iota":
            size = 1
            try:
                shape = eqn.params.get("shape") or ()
                dim = eqn.params.get("dimension", 0)
                size = shape[dim] if shape else 1
            except Exception:
                pass
            return [(0.0, float(max(size - 1, 0)))]
        if name in ("reduce_sum", "cumsum"):
            k = self._reduced_size(eqn)
            lo, hi = ins[0]
            return [(min(lo * k, 0.0) if lo < 0 else lo,
                     hi * k if hi > 0 else max(hi * k, hi))]
        if name in ("reduce_max", "cummax", "reduce_min", "cummin"):
            return [ins[0]]
        if name == "reduce_prod":
            return [TOP]
        if name == "dot_general":
            return [self._dot_interval(eqn, ins)]
        if name in ("pjit", "closed_call", "core_call", "remat",
                    "remat_call", "custom_jvp_call", "custom_vjp_call",
                    "custom_jvp_call_jaxpr", "checkpoint"):
            closed = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if closed is None:
                return [TOP] * n_out
            if hasattr(closed, "jaxpr"):
                self._seed_consts(self, eqn.invars, closed.jaxpr.invars)
                return self._subjaxpr(closed, ins)
            self._seed_consts(self, eqn.invars, closed.invars)
            return self.run(closed, [], ins)
        if name == "cond":
            branches = eqn.params.get("branches") or ()
            outs = None
            for br in branches:
                self._seed_consts(self, eqn.invars[1:], br.jaxpr.invars)
                o = self._subjaxpr(br, ins[1:])
                outs = o if outs is None else [
                    _union(a, b) for a, b in zip(outs, o)
                ]
            return outs if outs is not None else [TOP] * n_out
        if name == "scan":
            return self._eval_scan(eqn, ins)
        if name == "while":
            return self._eval_while(eqn, ins)
        return [TOP] * n_out

    @staticmethod
    def _eval_pow2(iv):
        cands = [iv[0] * iv[0], iv[1] * iv[1]]
        lo = 0.0 if iv[0] <= 0 <= iv[1] else min(cands)
        return (lo, max(cands))

    @staticmethod
    def _eval_mul_for_dot(a, b):
        cands = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
        cands = [c if not math.isnan(c) else 0.0 for c in cands]
        return (min(cands), max(cands))

    def _const_arr(self, v):
        """The actual array behind a jaxpr atom, if statically known."""
        from jax._src import core as jcore

        if isinstance(v, jcore.Literal):
            import numpy as np

            try:
                arr = np.asarray(v.val)
                return arr if 0 < arr.size <= self._CONST_VAL_MAX_SIZE else None
            except Exception:
                return None
        return self._const_vals.get(v)

    def _dot_interval(self, eqn, ins):
        """dot_general bounds.

        When one operand is a known constant W (the MXU mapping's one-hot
        REP/TIL/ACC and placement matrices, the RED fold rows), each output
        column c is sum_j W[j, c] * x_j with x_j in [lo, hi], so the exact
        interval hull is
            [ min_c(lo * P_c - hi * N_c),  max_c(hi * P_c - lo * N_c) ]
        with P_c / N_c the positive/negative parts of W summed over the
        contracted axes.  For a one-hot column this is just [lo, hi] —
        whereas the generic fallback (interval product x contraction size)
        multiplies by the full contraction width and cannot prove the MXU
        path.  Fallback keeps the old sound over-approximation when
        neither operand is statically known.
        """
        import numpy as np

        dn = eqn.params.get("dimension_numbers")
        if dn is not None:
            (lcd, rcd), (lbd, rbd) = dn
            for cidx, vidx, caxes in ((1, 0, tuple(rcd)), (0, 1, tuple(lcd))):
                arr = self._const_arr(eqn.invars[cidx])
                if arr is None:
                    continue
                lo, hi = ins[vidx]
                if not (math.isfinite(lo) and math.isfinite(hi)):
                    break  # unknown operand range: no better than fallback
                w = np.asarray(arr, dtype=np.float64)
                pos = np.maximum(w, 0.0)
                neg = np.maximum(-w, 0.0)
                if caxes:
                    pos = pos.sum(axis=caxes)
                    neg = neg.sum(axis=caxes)
                out_lo = float(np.min(lo * pos - hi * neg)) if pos.size else 0.0
                out_hi = float(np.max(hi * pos - lo * neg)) if pos.size else 0.0
                return (min(out_lo, out_hi), max(out_lo, out_hi))
        k = self._contract_size(eqn)
        span = self._eval_mul_for_dot(ins[0], ins[1])
        return (span[0] * k if span[0] < 0 else span[0],
                span[1] * k if span[1] > 0 else span[1])

    @staticmethod
    def _reduced_size(eqn) -> int:
        try:
            shape = eqn.invars[0].aval.shape
            axes = eqn.params.get("axes")
            if axes is None:  # cumsum: params axis
                axis = eqn.params.get("axis")
                return int(shape[axis]) if axis is not None else 1
            k = 1
            for ax in axes:
                k *= int(shape[ax])
            return max(k, 1)
        except Exception:
            return 1

    @staticmethod
    def _contract_size(eqn) -> int:
        try:
            ((lc, _rc), _batch) = eqn.params["dimension_numbers"]
            shape = eqn.invars[0].aval.shape
            k = 1
            for ax in lc:
                k *= int(shape[ax])
            return max(k, 1)
        except Exception:
            return 1

    def _eval_scan(self, eqn, ins):
        closed = eqn.params["jaxpr"]
        n_consts = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        length = eqn.params.get("length", 1) or 1
        consts = ins[:n_consts]
        carry = list(ins[n_consts:n_consts + n_carry])
        xs = ins[n_consts + n_carry:]
        ys_acc: Optional[List[Tuple[float, float]]] = None
        # fixpoint on the carry: silent sub-analyzer (findings only from
        # the final stabilized pass, so lines are not double-reported and
        # pre-widening transients don't fire)
        for _ in range(_SCAN_FIXPOINT_ITERS):
            sub = _Analyzer()
            self._seed_consts(sub, eqn.invars[:n_consts], closed.jaxpr.invars)
            outs = sub.run(closed.jaxpr, closed.consts, consts + carry + xs)
            new_carry = [
                _union(c, o) for c, o in zip(carry, outs[:n_carry])
            ]
            if new_carry == carry:
                break
            carry = new_carry
        else:
            carry = [TOP] * n_carry
        self._seed_consts(self, eqn.invars[:n_consts], closed.jaxpr.invars)
        final = self._subjaxpr(closed, consts + carry + xs)
        carry_out = [_union(c, o) for c, o in zip(carry, final[:n_carry])]
        ys = final[n_carry:]
        if ys_acc is None:
            ys_acc = ys
        return carry_out + ys_acc

    def _eval_while(self, eqn, ins):
        closed = eqn.params["body_jaxpr"]
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        consts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        for _ in range(_SCAN_FIXPOINT_ITERS):
            sub = _Analyzer()
            self._seed_consts(
                sub, eqn.invars[cn:cn + bn], closed.jaxpr.invars
            )
            outs = sub.run(closed.jaxpr, closed.consts, consts + carry)
            new_carry = [_union(c, o) for c, o in zip(carry, outs)]
            if new_carry == carry:
                break
            carry = new_carry
        else:
            carry = [TOP] * len(carry)
        self._seed_consts(self, eqn.invars[cn:cn + bn], closed.jaxpr.invars)
        final = self._subjaxpr(closed, consts + carry)
        return [_union(c, o) for c, o in zip(carry, final)]


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def analyze_callable(
    fn: Callable,
    in_shapes: Sequence[Tuple[int, ...]],
    in_intervals: Sequence[Tuple[float, float]],
    dtype=None,
) -> LimbReport:
    """Trace ``fn`` abstractly (make_jaxpr — no backend compile, compile-
    guard-safe) and interval-analyze the result."""
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    args = [jax.ShapeDtypeStruct(s, dtype) for s in in_shapes]
    closed = jax.make_jaxpr(fn)(*args)
    a = _Analyzer()
    a.run(closed.jaxpr, closed.consts, list(in_intervals))
    return LimbReport(
        findings=a.findings,
        float_outputs=a.float_outputs,
        bounded_outputs=a.bounded_outputs,
    )


@dataclass
class LimbEntry:
    name: str
    fn: Callable
    in_shapes: Sequence[Tuple[int, ...]]
    in_intervals: Sequence[Tuple[float, float]]
    # the documented contract the intervals encode, for the report
    contract: str = ""


def limb_entries() -> List[LimbEntry]:
    """The ops/limbs.py arithmetic core at its documented input
    contracts.  Strict digits are <= 2^8 (carry_exact's fixed point is
    256, not 255 — see its docstring), loose inputs go to the 2^24
    f32-exact ceiling."""
    from lodestar_tpu.ops import limbs as fl

    N = fl.NLIMBS
    STRICT = (0.0, 256.0)
    LOOSE = (0.0, float((1 << fl.LOOSE_BITS) - 1))
    SUB_A = (0.0, float((1 << 23) - 1))
    SUB_B = (0.0, float((1 << 12) - 1))
    return [
        LimbEntry("fp_strict", fl.fp_strict, [(N,)], [LOOSE],
                  contract="loose digits < 2^24 -> strict"),
        LimbEntry("fp_add", fl.fp_add, [(N,), (N,)], [STRICT, STRICT],
                  contract="lazy digitwise sum of two strict elements"),
        LimbEntry("fp_sub", fl.fp_sub, [(N,), (N,)], [SUB_A, SUB_B],
                  contract="a digits < 2^23, b digits < 2^12"),
        LimbEntry("fp_mul", lambda a, b: fl.fp_mul(a, b),
                  [(N,), (N,)], [STRICT, STRICT],
                  contract="strict x strict schoolbook (env-selected mode)"),
        # every LODESTAR_TPU_LIMB_MUL mode is proven individually — the
        # env default must never be the only path with a digit proof
        LimbEntry("fp_mul@ladder", lambda a, b: fl.fp_mul(a, b, mode="ladder"),
                  [(N,), (N,)], [STRICT, STRICT],
                  contract="strict x strict, VPU pad+add ladder"),
        LimbEntry("fp_mul@mxu", lambda a, b: fl.fp_mul(a, b, mode="mxu"),
                  [(N,), (N,)], [STRICT, STRICT],
                  contract="strict x strict, one-hot MXU contraction"),
        LimbEntry("fp_mul@mxu9", lambda a, b: fl.fp_mul(a, b, mode="mxu9"),
                  [(N,), (N,)], [STRICT, STRICT],
                  contract="strict x strict, 9-bit re-packed contraction"),
        LimbEntry("pack9", fl._pack9, [(N,)], [STRICT],
                  contract="strict 8-bit digits -> 45 x 9-bit digits"),
        LimbEntry("carry_base512",
                  lambda x: fl._carry_base(x, fl.LOOSE_BITS, fl.PACK9_BITS),
                  [(2 * fl.PACK9_NLIMBS - 1,)],
                  [(0.0, float(fl.PACK9_NLIMBS * (1 << 18)))],
                  contract="base-512 carry at the mxu9 product bound"),
        LimbEntry("fp_sqr", lambda a: fl.fp_sqr(a), [(N,)], [STRICT],
                  contract="strict square"),
        LimbEntry("fp_mul_small", lambda a: fl.fp_mul_small(a, (1 << 14) - 1),
                  [(N,)], [STRICT],
                  contract="strict x largest small multiplier"),
        LimbEntry("carry_exact", lambda x: fl.carry_exact(x), [(N,)], [LOOSE],
                  contract="loose -> semi-strict fold ladder"),
        LimbEntry("fp_reduce_full", fl.fp_reduce_full, [(N,)], [STRICT],
                  contract="semi-strict -> canonical (scan ripple + Barrett)"),
    ]


def audit_limb_overflow(
    entries: Optional[Sequence[LimbEntry]] = None,
    repo: Optional[str] = None,
) -> List[Violation]:
    """The jaxpr-limb-overflow rule over the limb entry registry."""
    if repo is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    if entries is None:
        entries = limb_entries()
    out: List[Violation] = []
    for entry in entries:
        report = analyze_callable(entry.fn, entry.in_shapes, entry.in_intervals)
        for f in report.findings:
            path = f.file
            if path.startswith(repo + os.sep):
                path = os.path.relpath(path, repo)
            out.append(Violation(
                rule=RULE,
                path=path or entry.name,
                line=f.line,
                message=(
                    f"{entry.name}: `{f.prim}` result proven to reach "
                    f"[{f.lo:.4g}, {f.hi:.4g}] under the entry's input "
                    f"contract ({entry.contract}) — exceeds the "
                    f"exactly-representable +/-{f.bound:.4g}; f32 limb "
                    "arithmetic silently rounds past this bound "
                    "(docs/static_analysis.md#jaxpr-limb-overflow)"
                ),
            ))
    return out
