"""Shared violation record + report formatting for all three analysis layers."""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional


@dataclasses.dataclass(frozen=True)
class Violation:
    """One invariant violation.

    ``rule`` is the stable kebab-case id from the docs/static_analysis.md
    catalogue; ``path`` is repo-relative (or an entry-point name for jaxpr
    findings); ``line`` is 0 when the finding has no source line (IR and
    runtime findings)."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


def format_report(violations: Iterable[Violation]) -> str:
    """Stable, grep-able one-line-per-violation report grouped by rule."""
    vs: List[Violation] = sorted(
        violations, key=lambda v: (v.rule, v.path, v.line)
    )
    if not vs:
        return "lint OK: 0 violations"
    lines = [f"{len(vs)} violation(s):"]
    lines += [f"  {v}" for v in vs]
    return "\n".join(lines)


def to_dicts(violations: Iterable[Violation]) -> List[dict]:
    """JSON-ready form (bench.py extras, --json output)."""
    return [dataclasses.asdict(v) for v in violations]


def suppressed_rules(source_line: str) -> Optional[set]:
    """Parse the inline suppression syntax on one source line.

    ``# lint: disable=rule-a,rule-b`` suppresses those rules on that line;
    ``# lint: disable`` (no ids) suppresses every rule on the line.
    Returns None when the line carries no suppression (including a
    MALFORMED directive — e.g. ``# lint: disable async-blocking-sync``
    with a space instead of ``=`` must not silently become disable-all;
    the still-reported violation is what surfaces the typo), the empty
    set for a bare disable-all, else the set of suppressed rule ids."""
    marker = "# lint: disable"
    idx = source_line.find(marker)
    if idx < 0:
        return None
    rest = source_line[idx + len(marker):].strip()
    if rest == "" or rest.startswith("#"):
        return set()  # bare disable-all (optionally a trailing comment)
    if not rest.startswith("="):
        return None  # malformed — do not suppress anything
    return {r.strip() for r in rest[1:].split(",") if r.strip()}


def filter_suppressed(
    violations: Iterable[Violation], source_by_path: dict
) -> List[Violation]:
    """Drop violations whose flagged source line carries a matching
    ``# lint: disable`` marker.  ``source_by_path`` maps the violation's
    path to the file's text; paths without source (runtime/IR findings)
    are never suppressible."""
    out: List[Violation] = []
    for v in violations:
        src = source_by_path.get(v.path)
        if src is not None and v.line:
            lines = src.splitlines()
            if 0 < v.line <= len(lines):
                rules = suppressed_rules(lines[v.line - 1])
                if rules is not None and (not rules or v.rule in rules):
                    continue
        out.append(v)
    return out
