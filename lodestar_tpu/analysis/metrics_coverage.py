"""Metrics-coverage core: which registered metrics are visible to operators.

Absorbed from tools/check_metrics_coverage.py (which now delegates here) so
the rule runs as a first-class checker in the lint suite
(ast_lint.MetricsCoverageChecker) while the standalone CLI keeps working.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List

# r.counter("name", ...) / r.gauge(...) / r.histogram(...) in registry.py;
# \s* spans the newline argparse-style call wrapping produces
_METRIC_RE = re.compile(r"r\.(?:counter|gauge|histogram)\(\s*\"([^\"]+)\"")


def registered_metrics(repo: str) -> List[str]:
    path = os.path.join(repo, "lodestar_tpu", "metrics", "registry.py")
    with open(path) as f:
        return _METRIC_RE.findall(f.read())


def _corpus(repo: str, subdir: str, exts: tuple) -> Dict[str, str]:
    out: Dict[str, str] = {}
    root = os.path.join(repo, subdir)
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if name.endswith(exts):
            with open(os.path.join(root, name)) as f:
                out[os.path.join(subdir, name)] = f.read()
    return out


def check(repo: str) -> Dict[str, Dict[str, List[str]]]:
    """Per-metric coverage: which dashboards and docs mention it."""
    dashboards = _corpus(repo, "dashboards", (".json",))
    docs = _corpus(repo, "docs", (".md",))
    report: Dict[str, Dict[str, List[str]]] = {}
    for metric in registered_metrics(repo):
        report[metric] = {
            "dashboards": [p for p, text in dashboards.items() if metric in text],
            "docs": [p for p, text in docs.items() if metric in text],
        }
    return report
