"""Compile-cost static auditor (analysis layer 4).

Tier-1 is XLA-compile-bound: three full runs in the PR 10 session had
ZERO failing tests yet died rc=124 at the 870s cap from ~9% box drift.
The conftest compile guard catches an over-budget test only *at runtime*,
after the wall has already been paid.  This layer makes compile cost a
statically checked property of the test suite instead: it walks the test
tree and library by AST + a one-level import graph and maps every
**program-materialization site** without executing anything —

- direct ``jax.jit`` wrapper creation + invocation (including
  ``.lower().compile()`` chains and module-level ``j_x = jax.jit(...)``
  wrappers called from tests);
- eager calls of ``@jax.jit``-decorated library functions in
  ``lodestar_tpu/`` (recorded in the map; violation only when the
  runtime ledger corroborates an expensive event, because a shared
  library wrapper compiles once per process and is often sub-threshold);
- ``TpuBlsVerifier`` constructions with *real* (non-stub) programs,
  resolved through stub factories (a helper that assigns into
  ``executor.compiled[...]`` before returning neutralizes the
  construction) and pytest fixtures, plus the drive calls
  (``verify_signature_sets*`` / ``dispatch`` / ``warmup*`` / handing the
  verifier to a ``BlsBatchPool``) that actually materialize programs;
- the per-(entry, bucket) program key each real construction implies,
  derived exactly like ``tpu_verifier._entry_name`` (``fused``/
  ``host_final_exp`` kwargs x ``buckets``).

The static map is then cross-checked against the runtime ledgers
(``.jax_cache/tier1_timings.json`` per-test compile-guard events) and
the conftest ``COMPILE_WHITELIST``, emitting four typed violations:

- ``compile-unstubbed-test``    a tier-1 (non-slow) test statically
  reaches a real verifier materialization and is not whitelisted — or
  the runtime ledger records guard events for a test the whitelist does
  not cover.
- ``compile-duplicate-program`` two tier-1 test modules materialize the
  same (entry, bucket) program key (or jit the same library target)
  instead of sharing ``_PROGRAM_MEMO``/AOT artifacts through one module.
- ``compile-whitelist-stale``   a whitelist pattern that matches no
  statically-compiling test (and no ledger-evidenced compile) — dead
  budget that hides future regressions.
- ``tier2-unmarked``            an irreducibly compile-bound test
  (direct jit of a device program) lacking both the ``slow`` marker and
  a whitelist entry.

Everything here is stdlib-only (ast/json/fnmatch): importing this module
never imports jax, so the auditor itself runs inside the tier-1 compile
guard and in bench.py's pre-flight lint stage.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .report import Violation, filter_suppressed

RULE_UNSTUBBED = "compile-unstubbed-test"
RULE_DUPLICATE = "compile-duplicate-program"
RULE_STALE = "compile-whitelist-stale"
RULE_TIER2 = "tier2-unmarked"

# mirrors tpu_verifier.DEFAULT_BUCKETS without importing jax
_DEFAULT_BUCKETS = (4, 16, 64, 128, 256)

# methods whose call on a REAL verifier materializes device programs
_DRIVE_METHODS = {
    "verify_signature_sets",
    "verify_signature_sets_async",
    "dispatch",
    "warmup",
    "warmup_sharded",
    "warmup_async",
}
# constructors that drive a verifier handed to them (the pool exists to
# dispatch batches through it)
_POOL_CTORS = {"BlsBatchPool"}


# ---------------------------------------------------------------------------
# small AST helpers (shared idiom with ast_lint, duplicated here so the
# layer stays importable without the jax-adjacent checkers)
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """Terminal Name at the base of an attribute/subscript/call chain."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return None


# ---------------------------------------------------------------------------
# per-module scan
# ---------------------------------------------------------------------------

@dataclass
class ConstructInfo:
    line: int
    ctor: str
    buckets: Tuple[int, ...]
    entry: str  # xla_split / xla_full / fused_split / fused_full
    stubbed: bool = False

    @property
    def keys(self) -> Tuple[str, ...]:
        return tuple(f"{self.entry}@{b}" for b in self.buckets)


@dataclass
class FuncScan:
    """Raw facts about one function/method body (nested defs included)."""

    name: str
    qualname: str  # Class::name for methods
    lineno: int
    is_test: bool = False
    is_fixture: bool = False
    slow: bool = False
    skipif: bool = False
    params: Tuple[str, ...] = ()
    constructs: Dict[str, ConstructInfo] = field(default_factory=dict)
    assigned_calls: Dict[str, Tuple[int, str]] = field(default_factory=dict)
    drives: List[Tuple[str, int, str]] = field(default_factory=list)  # var, line, method
    jit_sites: List[Tuple[int, Optional[str]]] = field(default_factory=list)
    lib_jit_sites: List[Tuple[int, str]] = field(default_factory=list)
    pallas_sites: List[Tuple[int, str]] = field(default_factory=list)
    trace_sites: List[Tuple[int, Optional[str]]] = field(default_factory=list)
    calls: List[Tuple[int, str]] = field(default_factory=list)  # resolved dotted refs
    returns_vars: Set[str] = field(default_factory=set)
    memo_primed: bool = False  # test primes _PROGRAM_MEMO before driving
    # resolved in phase 2:
    materializes: bool = False
    mat_sites: List[Tuple[int, str, Tuple[str, ...]]] = field(default_factory=list)
    # (line, kind, program keys) with kind in jit|verifier|helper|fixture|pallas
    returns_real_verifier: bool = False
    is_stub_factory: bool = False
    real_keys: Set[str] = field(default_factory=set)


@dataclass
class ModuleScan:
    path: str  # repo-relative, e.g. tests/test_foo.py
    dotted: str  # tests.test_foo
    module_slow: bool = False
    funcs: Dict[str, FuncScan] = field(default_factory=dict)  # qualname -> scan
    aliases: Dict[str, str] = field(default_factory=dict)
    verifier_ctors: Set[str] = field(default_factory=set)
    jit_wrappers: Dict[str, Optional[str]] = field(default_factory=dict)
    source: str = ""

    def tests(self) -> List[FuncScan]:
        return [f for f in self.funcs.values() if f.is_test]


def _decorator_names(node, aliases) -> List[str]:
    out = []
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(target)
        if name:
            out.append(_expand_alias(name, aliases))
    return out


def _expand_alias(dotted: str, aliases: Dict[str, str]) -> str:
    head, _, rest = dotted.partition(".")
    if head in aliases:
        return aliases[head] + ("." + rest if rest else "")
    return dotted


def _is_slow_mark(name: str) -> bool:
    return name.endswith("pytest.mark.slow") or name == "pytest.mark.slow"


def _pytestmark_is_slow(value: ast.AST, aliases) -> bool:
    nodes = value.elts if isinstance(value, (ast.List, ast.Tuple)) else [value]
    for n in nodes:
        name = _dotted(n.func if isinstance(n, ast.Call) else n)
        if name and _is_slow_mark(_expand_alias(name, aliases)):
            return True
    return False


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _entry_for_kwargs(kwargs: Dict[str, object]) -> str:
    """Static twin of tpu_verifier._entry_name: fused=None resolves to
    the XLA path on the CPU backend tier-1 runs on."""
    fused = bool(kwargs.get("fused") or False)
    host_final_exp = kwargs.get("host_final_exp", True)
    side = "split" if host_final_exp else "full"
    return f"{'fused' if fused else 'xla'}_{side}"


class _BodyScanner(ast.NodeVisitor):
    """Walks one callable body (nested defs inlined — the async
    ``def main()`` inside a test runs via ``asyncio.run``) and records
    raw materialization facts."""

    def __init__(self, mod: ModuleScan, fn: FuncScan,
                 jitted_lib: Dict[str, Set[str]],
                 pallas_lib: Optional[Dict[str, Set[str]]] = None):
        self.mod = mod
        self.fn = fn
        self.jitted_lib = jitted_lib
        self.pallas_lib = pallas_lib or {}
        self.alias_vars: Dict[str, str] = {}  # ex -> v (executor aliases)
        self.local_wrappers: Dict[str, Optional[str]] = {}
        self.aliases: Dict[str, str] = dict(mod.aliases)  # + in-body imports
        self.in_raises = 0  # inside `with pytest.raises(...)`

    # -- helpers ----------------------------------------------------------
    def _resolve(self, node: ast.AST) -> Optional[str]:
        name = _dotted(node)
        return _expand_alias(name, self.aliases) if name else None

    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module and node.level == 0:
            for a in node.names:
                self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def _verifier_root(self, name: Optional[str]) -> Optional[str]:
        """Follow executor aliases back to the constructed verifier var."""
        seen = set()
        while name is not None and name not in seen:
            seen.add(name)
            if name in self.fn.constructs:
                return name
            name = self.alias_vars.get(name)
        return None

    def _record_jit_creation(self, call: ast.Call) -> Optional[str]:
        if not call.args:
            return None
        return self._resolve(call.args[0])

    # -- statements -------------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        self._scan_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._scan_assign([node.target], node.value)
        self.generic_visit(node)

    def _scan_assign(self, targets, value):
        # stub injection: <chain>.compiled[...] = ...
        for t in targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Attribute)
                and t.value.attr == "compiled"
            ):
                root = self._verifier_root(_root_name(t.value))
                if root is not None:
                    self.fn.constructs[root].stubbed = True
            # kernel-builder replacement: v._kernel = <fake> means warmup
            # and dispatch build host callables, never XLA programs
            if isinstance(t, ast.Attribute) and t.attr == "_kernel":
                root = self._verifier_root(_root_name(t))
                if root is not None:
                    self.fn.constructs[root].stubbed = True
            # priming the process-level program memo before a warmup
            # serves the stub instead of compiling
            if isinstance(t, ast.Subscript) and _root_name(t) == "_PROGRAM_MEMO":
                self.fn.memo_primed = True
        if isinstance(value, ast.Call):
            resolved = self._resolve(value.func)
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if resolved in ("jax.jit", "jit"):
                target = self._record_jit_creation(value)
                for n in names:
                    self.local_wrappers[n] = target
                return
            info = self._construct_info(value, resolved)
            if info is not None:
                for n in names:
                    self.fn.constructs[n] = info
                return
            if resolved:
                for n in names:
                    self.fn.assigned_calls[n] = (value.lineno, resolved)
                return
        # plain aliasing: ex = v._executors[0]
        root = _root_name(value)
        if root is not None:
            for t in targets:
                if isinstance(t, ast.Name):
                    self.alias_vars[t.id] = root

    def visit_With(self, node):
        # a helper invoked under pytest.raises is asserted to fail before
        # it can materialize; don't propagate its compile cost
        raises = any(
            isinstance(item.context_expr, ast.Call)
            and (self._resolve(item.context_expr.func) or "").endswith(
                "pytest.raises"
            )
            for item in node.items
        )
        if raises:
            self.in_raises += 1
        self.generic_visit(node)
        if raises:
            self.in_raises -= 1

    visit_AsyncWith = visit_With

    def visit_For(self, node: ast.For):
        # for ex in v._executors: ...
        root = _root_name(node.iter)
        if isinstance(node.target, ast.Name) and root is not None:
            self.alias_vars[node.target.id] = root
        self.generic_visit(node)

    def _construct_info(self, value: ast.Call, resolved) -> Optional["ConstructInfo"]:
        """ConstructInfo when `value` is a verifier construction."""
        ctor = _dotted(value.func)
        if not (resolved and (
            resolved.endswith(".TpuBlsVerifier")
            or (ctor and ctor in self.mod.verifier_ctors)
        )):
            return None
        kwargs = {
            kw.arg: _literal(kw.value)
            for kw in value.keywords
            if kw.arg is not None
        }
        buckets = kwargs.get("buckets")
        if not isinstance(buckets, (tuple, list)):
            buckets = _DEFAULT_BUCKETS
        return ConstructInfo(
            line=value.lineno,
            ctor=ctor or "TpuBlsVerifier",
            buckets=tuple(int(b) for b in buckets),
            entry=_entry_for_kwargs(kwargs),
            # load_only verifiers serve prewarmed AOT executables
            # or degrade — they never backend-compile by contract
            stubbed=kwargs.get("load_only") is True,
        )

    def _record_returned(self, value) -> None:
        if isinstance(value, ast.Name):
            self.fn.returns_vars.add(value.id)
        elif isinstance(value, ast.Call):
            # `return TpuBlsVerifier(...)` — no Assign ever binds it, so
            # synthesize one: factories that construct inline still
            # classify as real-verifier / stub factories
            info = self._construct_info(value, self._resolve(value.func))
            if info is not None:
                var = f"<ret:{value.lineno}>"
                self.fn.constructs[var] = info
                self.fn.returns_vars.add(var)

    def visit_Return(self, node: ast.Return):
        self._record_returned(node.value)
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield):
        self._record_returned(node.value)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        resolved = self._resolve(node.func)
        # drive methods on a tracked object: v.verify_signature_sets(...)
        if isinstance(node.func, ast.Attribute):
            method = node.func.attr
            if method in _DRIVE_METHODS:
                base = _root_name(node.func.value)
                if base is not None:
                    self.fn.drives.append((base, node.lineno, method))
        if resolved in ("jax.jit", "jit"):
            parent_compiles = self._jit_chain_compiles(node)
            if parent_compiles or self._is_called_directly(node):
                self.fn.jit_sites.append(
                    (node.lineno, self._record_jit_creation(node))
                )
        elif resolved in ("jax.make_jaxpr", "make_jaxpr"):
            self.fn.trace_sites.append(
                (node.lineno, self._record_jit_creation(node))
            )
        elif resolved is not None:
            head = resolved.rsplit(".", 1)
            if resolved.rsplit(".", 1)[-1] == "pallas_call" or (
                len(head) == 2
                and head[1] in self.pallas_lib.get(head[0], ())
            ):
                self.fn.pallas_sites.append((node.lineno, resolved))
            elif len(head) == 2 and head[1] in self.jitted_lib.get(head[0], ()):
                self.fn.lib_jit_sites.append((node.lineno, resolved))
            elif isinstance(node.func, ast.Name):
                name = node.func.id
                if name in self.local_wrappers or name in self.mod.jit_wrappers:
                    target = self.local_wrappers.get(
                        name, self.mod.jit_wrappers.get(name)
                    )
                    self.fn.jit_sites.append((node.lineno, target))
                elif not self.in_raises:
                    self.fn.calls.append((node.lineno, resolved))
            elif not self.in_raises:
                self.fn.calls.append((node.lineno, resolved))
            # verifier handed to a batch pool counts as a drive
            tail = resolved.rsplit(".", 1)[-1]
            if tail in _POOL_CTORS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        self.fn.drives.append(
                            (arg.id, node.lineno, "BlsBatchPool")
                        )
        self.generic_visit(node)

    def _is_called_directly(self, node: ast.Call) -> bool:
        parent = getattr(node, "_cc_parent", None)
        return isinstance(parent, ast.Call) and parent.func is node

    def _jit_chain_compiles(self, node: ast.Call) -> bool:
        """jax.jit(f).lower(args).compile() materializes a program."""
        parent = getattr(node, "_cc_parent", None)
        chain = []
        while isinstance(parent, (ast.Attribute, ast.Call)):
            if isinstance(parent, ast.Attribute):
                chain.append(parent.attr)
            parent = getattr(parent, "_cc_parent", None)
        return "compile" in chain


def _annotate_parents(tree: ast.AST) -> None:
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._cc_parent = parent


def scan_module(path: str, repo: str,
                jitted_lib: Dict[str, Set[str]],
                pallas_lib: Optional[Dict[str, Set[str]]] = None,
                ) -> Optional[ModuleScan]:
    rel = os.path.relpath(path, repo)
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel)
    except (OSError, SyntaxError):
        return None
    _annotate_parents(tree)
    dotted = rel[:-3].replace(os.sep, ".")
    mod = ModuleScan(path=rel, dotted=dotted, source=source)
    mod.aliases = _collect_imports(tree)

    # local TpuBlsVerifier subclasses are constructors too (the stub
    # fleets subclass the verifier to override dispatch)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for base in node.bases:
                name = _dotted(base)
                if name and _expand_alias(name, mod.aliases).endswith(
                    "TpuBlsVerifier"
                ):
                    mod.verifier_ctors.add(node.name)
    mod.verifier_ctors.add("TpuBlsVerifier")

    # module-level facts: pytestmark, jit wrapper assignments
    for node in tree.body:
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "pytestmark" in names and _pytestmark_is_slow(
                node.value, mod.aliases
            ):
                mod.module_slow = True
            if isinstance(node.value, ast.Call):
                resolved = _dotted(node.value.func)
                resolved = (
                    _expand_alias(resolved, mod.aliases) if resolved else None
                )
                if resolved in ("jax.jit", "jit"):
                    target = None
                    if node.value.args:
                        t = _dotted(node.value.args[0])
                        target = _expand_alias(t, mod.aliases) if t else None
                    for n in names:
                        mod.jit_wrappers[n] = target

    def scan_callable(node, class_name=None, class_slow=False):
        qual = f"{class_name}::{node.name}" if class_name else node.name
        decos = _decorator_names(node, mod.aliases)
        fn = FuncScan(
            name=node.name,
            qualname=qual,
            lineno=node.lineno,
            is_test=node.name.startswith("test"),
            is_fixture=any(d.endswith("pytest.fixture") or d == "pytest.fixture"
                           for d in decos),
            slow=mod.module_slow or class_slow
            or any(_is_slow_mark(d) for d in decos),
            skipif=any(".mark.skipif" in d for d in decos),
            params=tuple(a.arg for a in node.args.args if a.arg != "self"),
        )
        scanner = _BodyScanner(mod, fn, jitted_lib, pallas_lib)
        for stmt in node.body:
            scanner.visit(stmt)
        mod.funcs[qual] = fn

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_callable(node)
        elif isinstance(node, ast.ClassDef):
            cdecos = _decorator_names(node, mod.aliases)
            c_slow = any(_is_slow_mark(d) for d in cdecos)
            for item in node.body:
                if isinstance(item, ast.Assign):
                    names = [
                        t.id for t in item.targets if isinstance(t, ast.Name)
                    ]
                    if "pytestmark" in names and _pytestmark_is_slow(
                        item.value, mod.aliases
                    ):
                        c_slow = True
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan_callable(item, class_name=node.name, class_slow=c_slow)
    return mod


# ---------------------------------------------------------------------------
# library scan: which lodestar_tpu functions are @jax.jit-decorated
# ---------------------------------------------------------------------------

def jitted_library_functions(repo: str) -> Dict[str, Set[str]]:
    out: Dict[str, Set[str]] = {}
    lib = os.path.join(repo, "lodestar_tpu")
    for dirpath, dirnames, filenames in os.walk(lib):
        dirnames[:] = [d for d in dirnames if not d.startswith((".", "__"))]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            dotted = rel[:-3].replace(os.sep, ".")
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            names: Set[str] = set()
            for node in tree.body:
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    d = _dotted(target)
                    if d in ("jax.jit", "jit"):
                        names.add(node.name)
                    elif d in ("partial", "functools.partial") and isinstance(
                        dec, ast.Call
                    ) and dec.args:
                        inner = _dotted(dec.args[0])
                        if inner in ("jax.jit", "jit"):
                            names.add(node.name)
            if names:
                out[dotted] = names
    return out


def pallas_library_functions(repo: str) -> Dict[str, Set[str]]:
    """Module dotted path -> top-level functions that reach a
    ``pl.pallas_call`` (directly, or through a same-module callee).
    Calling one from tier-1 materializes a Mosaic/interpret program
    exactly like a jit site — interpret=True still XLA-compiles the
    discharged kernel on CPU."""
    out: Dict[str, Set[str]] = {}
    lib = os.path.join(repo, "lodestar_tpu")
    for dirpath, dirnames, filenames in os.walk(lib):
        dirnames[:] = [d for d in dirnames if not d.startswith((".", "__"))]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo)
            dotted = rel[:-3].replace(os.sep, ".")
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            direct: Set[str] = set()
            callees: Dict[str, Set[str]] = {}
            for node in tree.body:
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                called: Set[str] = set()
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    d = _dotted(sub.func)
                    if not d:
                        continue
                    if d.rsplit(".", 1)[-1] == "pallas_call":
                        direct.add(node.name)
                    elif "." not in d:
                        called.add(d)
                callees[node.name] = called
            # same-module propagation: fq12_combine_ring_dma ->
            # ring_all_gather -> pallas_call
            changed = True
            while changed:
                changed = False
                for name, refs in callees.items():
                    if name not in direct and refs & direct:
                        direct.add(name)
                        changed = True
            if direct:
                out[dotted] = direct
    return out


# ---------------------------------------------------------------------------
# phase 2: cross-module resolution (import-graph fixpoint)
# ---------------------------------------------------------------------------

def _resolve_modules(mods: Dict[str, ModuleScan]) -> None:
    """Classify helpers (stub factory vs real-verifier factory vs
    materializing) and propagate through calls to a fixpoint, then
    resolve fixture-mediated materialization inside each module."""
    index: Dict[Tuple[str, str], FuncScan] = {}
    for mod in mods.values():
        for fn in mod.funcs.values():
            index[(mod.dotted, fn.name)] = fn
            index[(mod.dotted, fn.qualname)] = fn

    def lookup(ref: str) -> Optional[FuncScan]:
        module, _, name = ref.rpartition(".")
        return index.get((module, name))

    # local classification
    for mod in mods.values():
        for fn in mod.funcs.values():
            returned_constructs = [
                fn.constructs[v] for v in fn.returns_vars if v in fn.constructs
            ]
            if returned_constructs:
                if all(c.stubbed for c in returned_constructs):
                    fn.is_stub_factory = True
                else:
                    fn.returns_real_verifier = True
                    for c in returned_constructs:
                        if not c.stubbed:
                            fn.real_keys.update(c.keys)
            for var, line, method in fn.drives:
                info = fn.constructs.get(var)
                if info is not None and not info.stubbed and not fn.memo_primed:
                    fn.materializes = True
                    fn.mat_sites.append((line, "verifier", info.keys))
            for line, target in fn.jit_sites:
                fn.materializes = True
                fn.mat_sites.append(
                    (line, "jit", (f"jit:{target}",) if target else ())
                )
            for line, target in fn.pallas_sites:
                fn.materializes = True
                fn.mat_sites.append((line, "pallas", (f"pallas:{target}",)))

    # helper factories: v = make_real(); v.verify(...)
    for mod in mods.values():
        for fn in mod.funcs.values():
            real_vars = {}
            for var, (line, ref) in fn.assigned_calls.items():
                # dotted refs resolve cross-module; bare names fall back
                # to the same module (mirrors the fixpoint stage below)
                callee = lookup(ref) or index.get(
                    (mod.dotted, ref.rsplit(".", 1)[-1])
                )
                if callee is not None and callee.returns_real_verifier:
                    real_vars[var] = (line, callee)
            for var, line, method in fn.drives:
                if var in real_vars:
                    fn.materializes = True
                    fn.mat_sites.append(
                        (line, "verifier", tuple(sorted(real_vars[var][1].real_keys)))
                    )

    # call-graph propagation to a fixpoint (helpers calling helpers)
    changed = True
    rounds = 0
    while changed and rounds < len(index) + 2:
        changed = False
        rounds += 1
        for mod in mods.values():
            for fn in mod.funcs.values():
                for line, ref in fn.calls:
                    callee = lookup(ref) or index.get(
                        (mod.dotted, ref.rsplit(".", 1)[-1])
                    )
                    if callee is None or callee is fn:
                        continue
                    if callee.is_stub_factory:
                        continue
                    if callee.materializes and not fn.materializes:
                        fn.materializes = True
                        keys: Tuple[str, ...] = tuple(
                            sorted({k for _, _, ks in callee.mat_sites for k in ks})
                        )
                        fn.mat_sites.append((line, "helper", keys))
                        changed = True

    # fixture-mediated: a test whose param is a real-verifier fixture and
    # that drives it (or whose fixture materializes during setup)
    for mod in mods.values():
        fixtures = {f.name: f for f in mod.funcs.values() if f.is_fixture}
        for fn in mod.funcs.values():
            if not fn.is_test:
                continue
            for param in fn.params:
                fx = fixtures.get(param)
                if fx is None:
                    continue
                if fx.materializes and not fn.materializes:
                    fn.materializes = True
                    keys = tuple(
                        sorted({k for _, _, ks in fx.mat_sites for k in ks})
                    )
                    fn.mat_sites.append((fn.lineno, "fixture", keys))
                if fx.returns_real_verifier:
                    for var, line, method in fn.drives:
                        if var == param:
                            fn.materializes = True
                            fn.mat_sites.append(
                                (line, "verifier",
                                 tuple(sorted(fx.real_keys)))
                            )


# ---------------------------------------------------------------------------
# whitelist + runtime ledger
# ---------------------------------------------------------------------------

def parse_whitelist(repo: str) -> List[Tuple[str, int]]:
    """(pattern, conftest line) pairs from tests/conftest.py's
    COMPILE_WHITELIST, by AST — never imports the conftest."""
    path = os.path.join(repo, "tests", "conftest.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "COMPILE_WHITELIST"
            for t in node.targets
        ):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return [
                    (elt.value, elt.lineno)
                    for elt in node.value.elts
                    if isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)
                ]
    return []


def load_ledger_compiles(repo: str) -> Dict[str, int]:
    """nodeid -> compile-guard event count, merged over the recorded
    FULL tier-1 runs (partial -k subsets say nothing about coverage)."""
    path = os.path.join(repo, ".jax_cache", "tier1_timings.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    try:
        from lodestar_tpu.observatory.run_ledger import TIER1_FULL_RUN_MIN_TESTS
    except Exception:  # pragma: no cover - observatory always importable
        TIER1_FULL_RUN_MIN_TESTS = 400
    merged: Dict[str, int] = {}
    for run in data.get("runs", []):
        if run.get("n_tests", 0) < TIER1_FULL_RUN_MIN_TESTS:
            continue
        for nodeid, count in (run.get("test_compiles") or {}).items():
            merged[nodeid] = max(merged.get(nodeid, 0), int(count))
    return merged


def _whitelisted(nodeid: str, whitelist: Sequence[Tuple[str, int]]) -> bool:
    return any(fnmatch.fnmatch(nodeid, pat) for pat, _ in whitelist)


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

@dataclass
class CompileCostReport:
    modules: Dict[str, ModuleScan]
    whitelist: List[Tuple[str, int]]
    ledger_compiles: Dict[str, int]
    violations: List[Violation]

    def materializing_tests(self) -> Dict[str, List[Tuple[int, str, Tuple[str, ...]]]]:
        out = {}
        for mod in self.modules.values():
            for fn in mod.tests():
                if fn.materializes or fn.lib_jit_sites:
                    out[f"{mod.path}::{fn.qualname}"] = list(fn.mat_sites)
        return out


def build_map(
    repo: Optional[str] = None,
    test_paths: Optional[Sequence[str]] = None,
    whitelist: Optional[Sequence[Tuple[str, int]]] = None,
) -> CompileCostReport:
    """The static map alone (no violations yet): scan + resolve."""
    if repo is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    jitted = jitted_library_functions(repo)
    pallas = pallas_library_functions(repo)
    if test_paths is None:
        tdir = os.path.join(repo, "tests")
        test_paths = sorted(
            os.path.join(tdir, f)
            for f in os.listdir(tdir)
            if f.startswith("test_") and f.endswith(".py")
        )
        tools_dir = os.path.join(repo, "tools")
        if os.path.isdir(tools_dir):
            test_paths = list(test_paths) + sorted(
                os.path.join(tools_dir, f)
                for f in os.listdir(tools_dir)
                if f.endswith(".py")
            )
    mods: Dict[str, ModuleScan] = {}
    for path in test_paths:
        scan = scan_module(path, repo, jitted, pallas)
        if scan is not None:
            mods[scan.dotted] = scan
    _resolve_modules(mods)
    wl = list(whitelist) if whitelist is not None else parse_whitelist(repo)
    return CompileCostReport(
        modules=mods, whitelist=wl, ledger_compiles={}, violations=[]
    )


def audit_compile_cost(
    repo: Optional[str] = None,
    test_paths: Optional[Sequence[str]] = None,
    whitelist: Optional[Sequence[Tuple[str, int]]] = None,
    use_ledger: bool = True,
) -> List[Violation]:
    if repo is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    report = build_map(repo, test_paths=test_paths, whitelist=whitelist)
    report.ledger_compiles = load_ledger_compiles(repo) if use_ledger else {}
    v: List[Violation] = []

    test_mods = {
        d: m for d, m in report.modules.items()
        if os.path.basename(m.path).startswith("test_")
    }

    # -- compile-unstubbed-test + tier2-unmarked --------------------------
    for mod in test_mods.values():
        for fn in mod.tests():
            nodeid = f"{mod.path}::{fn.qualname}"
            if fn.slow or fn.skipif or _whitelisted(nodeid, report.whitelist):
                continue
            verifier_sites = [
                s for s in fn.mat_sites
                if s[1] in ("verifier", "fixture", "helper", "pallas")
            ]
            jit_only = [s for s in fn.mat_sites if s[1] == "jit"]
            for line, kind, keys in verifier_sites:
                v.append(Violation(
                    rule=RULE_UNSTUBBED,
                    path=mod.path,
                    line=line,
                    message=(
                        f"{nodeid} statically reaches a real verifier "
                        f"materialization ({kind}"
                        + (f": {', '.join(keys)}" if keys else "")
                        + ") outside the compile whitelist — inject stub "
                        "programs (executor.compiled[key] = ...), ride a "
                        "prewarmed .aot_store load, or mark it slow "
                        "(docs/static_analysis.md#tier-1-budget-discipline)"
                    ),
                ))
            for line, kind, keys in jit_only:
                v.append(Violation(
                    rule=RULE_TIER2,
                    path=mod.path,
                    line=line,
                    message=(
                        f"{nodeid} is irreducibly compile-bound (direct "
                        f"{', '.join(keys) or 'jax.jit'} materialization) but "
                        "carries no `slow` marker and no whitelist entry — "
                        "tier-1 has no compile budget for it; mark it "
                        "@pytest.mark.slow (nightly tier) or whitelist it "
                        "with a budget justification"
                    ),
                ))

    # -- runtime-ledger cross-check --------------------------------------
    static_materializing = set()
    all_tests: Dict[str, Tuple[ModuleScan, FuncScan]] = {}
    for mod in test_mods.values():
        for fn in mod.tests():
            nodeid = f"{mod.path}::{fn.qualname}"
            all_tests[nodeid] = (mod, fn)
            if fn.materializes or fn.lib_jit_sites:
                static_materializing.add(nodeid)
    for nodeid, count in sorted(report.ledger_compiles.items()):
        base = nodeid.split("[", 1)[0]
        if _whitelisted(nodeid, report.whitelist):
            continue
        if base in static_materializing:
            continue
        hit = all_tests.get(base)
        path = hit[0].path if hit else nodeid.split("::", 1)[0]
        line = hit[1].lineno if hit else 0
        v.append(Violation(
            rule=RULE_UNSTUBBED,
            path=path,
            line=line,
            message=(
                f"runtime ledger records {count} compile-guard event(s) for "
                f"{nodeid}, which is neither whitelisted nor statically "
                "mapped as materializing — it compiled under "
                "LODESTAR_TPU_COMPILE_GUARD=0 or through a path the static "
                "map cannot see; stub it or whitelist it"
            ),
        ))

    # -- compile-duplicate-program ---------------------------------------
    key_owners: Dict[str, Dict[str, int]] = {}
    for mod in test_mods.values():
        for fn in mod.tests():
            if fn.slow or fn.skipif:
                continue
            for line, kind, keys in fn.mat_sites:
                for key in keys:
                    owners = key_owners.setdefault(key, {})
                    owners.setdefault(mod.path, line)
    for key, owners in sorted(key_owners.items()):
        if len(owners) < 2:
            continue
        paths = sorted(owners)
        for path in paths[1:]:
            line = owners[path]
            v.append(Violation(
                rule=RULE_DUPLICATE,
                path=path,
                line=line,
                message=(
                    f"program key {key} is materialized by {len(paths)} "
                    f"tier-1 modules ({', '.join(paths)}) — each pays its "
                    "own trace+lower+load; share one module's programs via "
                    "_PROGRAM_MEMO / the AOT store, or stub the extra copy"
                ),
            ))

    # -- compile-whitelist-stale -----------------------------------------
    conftest_rel = os.path.join("tests", "conftest.py")
    for pat, wl_line in report.whitelist:
        alive = False
        for nodeid, (mod, fn) in all_tests.items():
            if not fnmatch.fnmatch(nodeid, pat):
                continue
            if fn.materializes or fn.lib_jit_sites or fn.trace_sites:
                alive = True
                break
        if not alive:
            for nodeid, count in report.ledger_compiles.items():
                if count and fnmatch.fnmatch(nodeid, pat):
                    alive = True
                    break
        if not alive:
            v.append(Violation(
                rule=RULE_STALE,
                path=conftest_rel,
                line=wl_line,
                message=(
                    f"COMPILE_WHITELIST entry {pat!r} matches no "
                    "statically-compiling test and no ledger-recorded "
                    "compile event — dead budget; remove it so a future "
                    "test cannot silently compile under its cover"
                ),
            ))

    source_by_path = {m.path: m.source for m in report.modules.values()}
    conftest_path = os.path.join(repo, conftest_rel)
    try:
        with open(conftest_path, encoding="utf-8") as f:
            source_by_path[conftest_rel] = f.read()
    except OSError:
        pass
    return filter_suppressed(v, source_by_path)
