"""First-party static analysis: the invariants that caused real regressions,
machine-checked in tier-1.

Every serious regression in this repo's history was an invariant violation
no existing check could see before runtime:

- BENCH_r05 rc=124: Mosaic rejected a mixed-width narrow-axis
  ``tpu.concatenate`` the fused graph emitted through ``jnp.stack`` at
  >16 lanes (fixed by ``fused_core.aligned_splice``, PR 1).
- Blocking device syncs reachable from ``async def`` paths stall the
  event loop for the whole dispatch latency.
- Shared mutable state (verifier counters, the ``PointCache`` LRU)
  mutated from ``asyncio.to_thread`` workers introduced in PR 3.

Five layers, one report format (``report.Violation``):

- ``jaxpr_audit``  — abstract-traces every public fused program in
  ``lodestar_tpu/ops/`` (``jax.make_jaxpr`` only: no backend compile, no
  device programs, so it runs inside the tier-1 conftest compile guard)
  and asserts TPU-portability invariants on the IR.  Includes
  ``limb_interval``: interval analysis proving the limb arithmetic's
  digit magnitudes stay inside the f32 exactly-representable range.
- ``ast_lint``     — pluggable AST checkers encoding the project's
  async/tracing/locking discipline over the whole ``lodestar_tpu/`` tree.
- ``lock_audit``   — instrumented lock wrappers + a deterministic
  interleaving harness over the BLS hot path
  (``BlsBatchPool._flush`` → ``TpuBlsVerifier.dispatch`` →
  ``DeviceExecutor``) that flags unguarded shared-state mutation and
  lock-order inversions at the first offending call, not by racing.
- ``compile_cost`` — stdlib-only AST + import-graph auditor proving
  which tier-1 tests materialize device programs, cross-checked against
  the runtime ledgers and the conftest compile-guard whitelist (tier-1
  died rc=124 three times in one session with ZERO failing tests; the
  compile budget is now a statically checked property).
- ``pallas_audit`` — walks every ``pallas_call`` in the traced entry
  jaxprs plus the kernel library (pallas_tower / pallas_fuse /
  pallas_ring) and proves DMA/semaphore balance, ref-race freedom,
  ring-neighbor topology, and Mosaic block tiling before any TPU cycle
  — the contract layer for ROADMAP item 3's remote-DMA pairing v2.

``tools/lint.py`` drives all five and exits nonzero on violations;
``bench.py`` runs the same suite as a pre-flight stage;
``tools/tier1_budget.py --enforce`` combines the compile-cost layer with
the wall-clock margin gate.  The rule catalogue (with the incident
behind each rule and the inline-suppression syntax) is
docs/static_analysis.md.
"""

from typing import List, Sequence

from .report import Violation, format_report  # noqa: F401


def run_all(
    repo: str = None,
    buckets: Sequence[int] = (4, 128),
    with_jaxpr: bool = True,
    with_lock_audit: bool = True,
    trace_cache: bool = True,
    with_compile_cost: bool = True,
    with_pallas: bool = True,
) -> List[Violation]:
    """Every analysis layer, one violation list — the entry point
    tools/lint.py, bench.py's pre-flight stage, and the tier-1 tests share
    (lazy imports keep `import lodestar_tpu.analysis` jax-free)."""
    import os

    if repo is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    from .ast_lint import run_ast_lint

    violations = list(run_ast_lint(repo))
    if with_compile_cost:
        from .compile_cost import audit_compile_cost

        violations += audit_compile_cost(repo=repo)
    if with_lock_audit:
        from .lock_audit import audit_bls_pipeline

        violations += audit_bls_pipeline()
    if with_jaxpr:
        from .jaxpr_audit import audit_all

        violations += audit_all(buckets=tuple(buckets), use_cache=trace_cache)
        from .limb_interval import audit_limb_overflow

        violations += audit_limb_overflow(repo=repo)
    if with_pallas:
        # the dispatch-entry graphs are swept inside audit_all via the
        # "pallas" artifact field; this adds the kernel-library entries
        # (pallas_tower / pallas_fuse / pallas_ring) audit_all can't reach
        from .pallas_audit import audit_all_pallas

        violations += audit_all_pallas(use_cache=trace_cache)
    return violations
