"""Pallas kernel verifier — the fifth static-analysis layer.

Walks every ``pallas_call`` primitive in traced entry jaxprs and audits
the kernel body for the four failure classes ROADMAP item 3 (remote-DMA
sharded pairing v2) will live or die by:

* ``pallas-dma-unbalanced`` — every ``make_async_copy`` /
  ``make_async_remote_copy`` start has a matching wait on the same
  semaphore (slot) along every control path; no wait-without-start; no
  semaphore count leaked across grid steps / loop iterations.
* ``pallas-ref-race`` — read-after-write / write-after-write /
  write-after-read on overlapping Ref slices while a DMA touching them
  is still in flight (no intervening wait) — the double-buffer
  slot-aliasing bug class.  Two in-flight DMAs sharing one semaphore
  slot are flagged directly.
* ``pallas-ring-neighbor`` — remote device ids derived from
  ``axis_index`` must be congruent mod the axis size and never
  self-send.
* ``pallas-block-misaligned`` — gridded block shapes must divide the
  operand shape on every split dim, split trailing dims must meet the
  per-dtype (sublane, 128) Mosaic tile rules (the BENCH_r05 rc=124
  class, caught here before a TPU ever sees the kernel), and
  memory-space sanity: DMA semaphore slots must be semaphore-space
  refs, semaphore refs must never be used as data.

Everything is decided from the jaxpr alone — no TPU, no interpreter
run.  The extraction distills each ``pallas_call`` into a JSON-native
record (blocks, refs, a nested region tree of DMA/access events with
slice indices evaluated per ``axis_index`` value) so the rules replay
from the jaxpr_audit artifact cache exactly like the layer-4 rules:
records ride ``.jax_cache/jaxpr_audit_artifacts.json`` under the same
ops-content fingerprint (``_CACHE_VERSION`` v4 folds this module's
source in), keyed ``pallas:<entry>`` for the kernel-library entries
below and embedded as the ``"pallas"`` artifact field for the layer-4
entry points (so the fused dispatch graphs are swept for free).

Slice arithmetic: index expressions inside kernels are evaluated by a
tiny abstract interpreter over scalar ints, tracking one value PER
axis_index (a length-n vector when the kernel sits under shard_map over
an n-way mesh).  Remote-DMA incoming writes are modelled SPMD-
symmetrically: the write landing on shard r is the one the sender s
with device_id(s) == r issued, so its destination slice is the sender's
expression evaluated at s.  Anything the interpreter cannot evaluate
degrades to "?" — treated as overlapping-everything (conservative), a
non-issue for the live tree whose only DMA kernel (ops/pallas_ring.py)
evaluates exactly.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Dict, List, Optional, Tuple

from .report import Violation

RULE_DMA = "pallas-dma-unbalanced"
RULE_RACE = "pallas-ref-race"
RULE_RING = "pallas-ring-neighbor"
RULE_TILE = "pallas-block-misaligned"

# mesh width the ring-combine entry traces at (>= 2 devices required;
# gated on jaxpr_audit.sharded_audit_available())
PALLAS_AUDIT_MESH = 2

# Mosaic vreg second-minor (sublane) tile per dtype; the minor (lane)
# tile is 128 for every dtype
_SUBLANE = {
    "float32": 8, "int32": 8, "uint32": 8,
    "bfloat16": 16, "float16": 16, "int16": 16, "uint16": 16,
    "int8": 32, "uint8": 32, "float8_e4m3fn": 32, "float8_e5m2": 32,
}
_LANE = 128

_DMA_PRIMS = frozenset({
    "dma_start", "dma_wait", "semaphore_signal", "semaphore_wait",
    "get_barrier_semaphore",
})

# scalar-int primitives the mini-interpreter evaluates (per axis_index)
_EVAL_PRIMS = frozenset({
    "add", "sub", "mul", "rem", "div", "neg", "max", "min",
    "convert_element_type", "broadcast_in_dim", "squeeze", "reshape",
    "stop_gradient", "axis_index",
})


def _site(eqn) -> List:
    from . import jaxpr_audit as ja

    f, ln = ja._eqn_site(eqn)
    return [f, ln]


# ---------------------------------------------------------------------------
# mini-interpreter values: int (uniform) | [int]*n (per axis_index) | "?"
# ---------------------------------------------------------------------------


def _lift(v, n):
    if isinstance(v, int) and n:
        return [v] * n
    return v


def _binop(op, a, b, n):
    if a == "?" or b == "?":
        return "?"
    if isinstance(a, int) and isinstance(b, int):
        return op(a, b)
    a, b = _lift(a, n), _lift(b, n)
    if not (isinstance(a, list) and isinstance(b, list) and len(a) == len(b)):
        return "?"
    return [op(x, y) for x, y in zip(a, b)]


def _trunc_rem(a, b):
    # lax.rem is the TRUNCATED remainder (sign of the dividend) — the
    # reason kernels must bias (x - k + n) % n positive before the rem
    if b == 0:
        return 0
    r = abs(a) % abs(b)
    return r if a >= 0 else -r


def _trunc_div(a, b):
    if b == 0:
        return 0
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


_BINOPS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "rem": _trunc_rem,
    "div": _trunc_div,
    "max": max,
    "min": min,
}


class _KernelExtractor:
    """One pallas_call kernel body -> JSON-native record."""

    def __init__(self, axis_sizes: Dict[str, int]):
        self.axis_sizes = dict(axis_sizes)
        # the per-axis_index vector model only makes sense for a single
        # mapped axis — the live mesh (and item 3's plan) is 1-D
        self.n: Optional[int] = (
            next(iter(self.axis_sizes.values()))
            if len(self.axis_sizes) == 1 else None
        )
        self.refs: Dict[str, dict] = {}
        self._fresh = 0

    # -- env plumbing ------------------------------------------------------

    def _reg_ref(self, var, origin: str) -> str:
        rid = f"r{self._fresh}"
        self._fresh += 1
        av = getattr(var, "aval", None)
        dt = getattr(av, "dtype", None)
        dt_name = str(getattr(dt, "name", "") or dt or "")
        space = str(getattr(av, "memory_space", None) or "")
        self.refs[rid] = {
            "shape": [int(d) for d in getattr(av, "shape", ())],
            "dtype": dt_name,
            "space": space,
            "sem": "sem" in dt_name or "sem" in space,
            "origin": origin,
        }
        return rid

    def _is_ref(self, var) -> bool:
        av = getattr(var, "aval", None)
        return "Ref" in type(av).__name__ if av is not None else False

    def _val_of(self, x, env):
        """Value of an invar/leaf: Literal, raw int, or env lookup."""
        if x is None:
            return None
        if isinstance(x, int):
            return x
        if hasattr(x, "val") and not hasattr(x, "aval"):
            try:
                return int(x.val)
            except (TypeError, ValueError):
                return "?"
        if type(x).__name__ == "Literal":
            try:
                return int(x.val)
            except (TypeError, ValueError):
                return "?"
        try:
            got = env.get(x, "?") if not isinstance(x, (list, tuple)) else "?"
        except TypeError:  # unhashable leaf (array-valued Literal)
            return "?"
        if isinstance(got, tuple) and got and got[0] == "ref":
            return "?"
        return got

    def _map_env(self, sub_invars, operands, env):
        sub = {}
        for i, v in enumerate(sub_invars):
            if i < len(operands):
                op = operands[i]
                try:
                    known = env.get(op)
                except TypeError:
                    known = None
                if known is not None:
                    sub[v] = known
                else:
                    sub[v] = self._val_of(op, env)
            elif self._is_ref(v):
                sub[v] = ("ref", self._reg_ref(v, "scoped"))
            else:
                sub[v] = "?"
        return sub

    # -- slice decoding ----------------------------------------------------

    def _decode_transforms(self, transforms, shape, env) -> List[dict]:
        """tuple of NDIndexer -> [{"start": v, "size": s}, ...] ("?" on
        anything beyond one plain strided indexer)."""
        full = [{"start": 0, "size": int(d)} for d in shape]
        try:
            if not transforms:
                return full
            if len(transforms) != 1:
                return [{"start": "?", "size": 1} for _ in shape]
            idx = transforms[0]
            out = []
            for d, el in enumerate(getattr(idx, "indices", ())):
                if isinstance(el, int):
                    out.append({"start": el, "size": 1})
                elif hasattr(el, "start") and hasattr(el, "size"):
                    if getattr(el, "stride", 1) not in (1, None):
                        out.append({"start": "?", "size": 1})
                        continue
                    out.append({
                        "start": self._val_of(el.start, env),
                        "size": int(el.size),
                    })
                else:
                    out.append({"start": self._val_of(el, env), "size": 1})
            return out or full
        except Exception:
            return [{"start": "?", "size": 1} for _ in shape]

    def _ref_slices(self, var, transforms, env) -> Optional[dict]:
        if var is None:
            return None
        tag = env.get(var)
        rid = tag[1] if isinstance(tag, tuple) and tag[0] == "ref" else "?"
        shape = getattr(getattr(var, "aval", None), "shape", ())
        return {"ref": rid,
                "slices": self._decode_transforms(transforms, shape, env)}

    # -- events ------------------------------------------------------------

    def _dma_event(self, pname, eqn, env) -> dict:
        try:
            import jax

            args = jax.tree_util.tree_unflatten(eqn.params["tree"], eqn.invars)
            (src, src_t, dst, dst_t, dst_sem, dst_sem_t,
             src_sem, src_sem_t, dev) = args
            dev_v = None if dev is None else self._val_of(dev, env)
            return {
                "op": pname, "site": _site(eqn),
                "src": self._ref_slices(src, src_t, env),
                "dst": self._ref_slices(dst, dst_t, env),
                "dst_sem": self._ref_slices(dst_sem, dst_sem_t, env),
                "src_sem": self._ref_slices(src_sem, src_sem_t, env),
                "device_id": dev_v,
            }
        except Exception:
            return {"op": pname, "site": _site(eqn), "src": None, "dst": None,
                    "dst_sem": None, "src_sem": None, "device_id": "?"}

    def _access_event(self, pname, eqn, env) -> dict:
        ref_var = eqn.invars[0] if eqn.invars else None
        try:
            import jax

            tree = eqn.params.get("tree")
            transforms = ()
            if tree is not None:
                flat = eqn.invars[1:] if pname == "get" else eqn.invars[2:]
                transforms = jax.tree_util.tree_unflatten(tree, flat)
        except Exception:
            transforms = None  # forces "?" slices below
        target = (self._ref_slices(ref_var, transforms, env)
                  if transforms is not None else
                  {"ref": "?", "slices": [{"start": "?", "size": 1}]})
        return {"op": pname, "site": _site(eqn), "target": target}

    def _sem_event(self, pname, eqn, env) -> dict:
        ref_var = eqn.invars[0] if eqn.invars else None
        tag = env.get(ref_var)
        rid = tag[1] if isinstance(tag, tuple) and tag[0] == "ref" else "?"
        return {"op": pname, "site": _site(eqn), "ref": rid}

    # -- region walk -------------------------------------------------------

    def _eval(self, eqn, env) -> None:
        p = eqn.primitive.name
        outv = eqn.outvars[0] if eqn.outvars else None
        if outv is None:
            return
        if getattr(getattr(outv, "aval", None), "shape", None) not in ((), None):
            env[outv] = "?"
            return
        if p == "axis_index":
            name = eqn.params.get("axis_name")
            if isinstance(name, (tuple, list)):
                name = name[0] if len(name) == 1 else None
            if self.n is not None and (
                name is None or str(name) in self.axis_sizes
            ):
                env[outv] = list(range(self.n))
            else:
                env[outv] = "?"
            return
        vals = [self._val_of(v, env) for v in eqn.invars]
        if p in _BINOPS and len(vals) == 2:
            env[outv] = _binop(_BINOPS[p], vals[0], vals[1], self.n)
        elif p == "neg" and vals:
            env[outv] = _binop(_BINOPS["sub"], 0, vals[0], self.n)
        elif vals:  # convert/broadcast/squeeze/reshape on a scalar
            env[outv] = vals[0]
        else:
            env[outv] = "?"

    def region(self, jaxpr, env) -> List:
        events: List = []
        for eqn in jaxpr.eqns:
            p = eqn.primitive.name
            if p in ("dma_start", "dma_wait"):
                events.append(self._dma_event(p, eqn, env))
            elif p in ("get", "swap"):
                events.append(self._access_event(p, eqn, env))
            elif p in ("semaphore_signal", "semaphore_wait"):
                events.append(self._sem_event(p, eqn, env))
            elif p == "get_barrier_semaphore":
                if eqn.outvars:
                    env[eqn.outvars[0]] = ("ref", self._reg_ref(
                        eqn.outvars[0], "barrier"))
            elif p == "scan":
                body = eqn.params["jaxpr"]
                sub = self._map_env(body.jaxpr.invars, eqn.invars, env)
                ev = self.region(body.jaxpr, sub)
                if ev:
                    events.append({"op": "loop", "site": _site(eqn),
                                   "body": ev})
            elif p == "while":
                cn = eqn.params.get("cond_nconsts", 0)
                body = eqn.params["body_jaxpr"]
                sub = self._map_env(body.jaxpr.invars, eqn.invars[cn:], env)
                ev = self.region(body.jaxpr, sub)
                cond = eqn.params.get("cond_jaxpr")
                if cond is not None:
                    cond_ops = (list(eqn.invars[:cn])
                                + list(eqn.invars[cn + eqn.params.get(
                                    "body_nconsts", 0):]))
                    ev += self.region(
                        cond.jaxpr,
                        self._map_env(cond.jaxpr.invars, cond_ops, env))
                if ev:
                    events.append({"op": "loop", "site": _site(eqn),
                                   "body": ev})
            elif p == "cond":
                branches = []
                for br in eqn.params.get("branches", ()):
                    sub = self._map_env(br.jaxpr.invars, eqn.invars[1:], env)
                    branches.append(self.region(br.jaxpr, sub))
                if any(branches):
                    events.append({"op": "cond", "site": _site(eqn),
                                   "branches": branches})
            elif p in _EVAL_PRIMS:
                self._eval(eqn, env)
            else:
                inlined = False
                for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                    sub_j = eqn.params.get(key)
                    if sub_j is None:
                        continue
                    inner = getattr(sub_j, "jaxpr", sub_j)
                    if not hasattr(inner, "eqns"):
                        continue
                    sub = self._map_env(inner.invars, eqn.invars, env)
                    for cv, c in zip(inner.constvars,
                                     getattr(sub_j, "consts", ())):
                        try:
                            sub[cv] = int(c) if getattr(
                                c, "shape", None) == () else "?"
                        except (TypeError, ValueError):
                            sub[cv] = "?"
                    events.extend(self.region(inner, sub))
                    inlined = True
                    break
                if not inlined and eqn.outvars:
                    for v in eqn.outvars:
                        env[v] = "?"
        return events


def _subtree_has_dma(jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _DMA_PRIMS:
            return True
        for v in eqn.params.values():
            cands = v if isinstance(v, (list, tuple)) else (v,)
            for c in cands:
                inner = c if hasattr(c, "eqns") else getattr(c, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns") and \
                        _subtree_has_dma(inner):
                    return True
    return False


def _pallas_record(eqn, axis_sizes: Dict[str, int]) -> dict:
    gm = eqn.params.get("grid_mapping")
    kj = eqn.params.get("jaxpr")
    name = str(eqn.params.get("name_and_src_info", "") or "pallas_call")
    name = name.split(" at ")[0]
    grid: List = []
    blocks: List = []
    if gm is not None:
        try:
            grid = [int(g) for g in gm.grid]
        except (TypeError, ValueError):
            grid = [str(g) for g in gm.grid]
        for bm in getattr(gm, "block_mappings", ()):
            try:
                sds = bm.array_shape_dtype
                blocks.append({
                    "block": [1 if b is None else int(b)
                              for b in bm.block_shape],
                    "array": [int(d) for d in sds.shape],
                    "dtype": str(getattr(sds.dtype, "name", sds.dtype)),
                    "space": str(getattr(bm.transformed_block_aval,
                                         "memory_space", None) or ""),
                    "origin": str(getattr(bm, "origin", "")),
                })
            except Exception:
                pass
    ex = _KernelExtractor(axis_sizes)
    env: Dict = {}
    for v in getattr(kj, "invars", ()):
        if ex._is_ref(v):
            env[v] = ("ref", ex._reg_ref(v, "operand"))
        else:
            env[v] = "?"
    events: List = []
    if kj is not None and _subtree_has_dma(kj):
        try:
            events = ex.region(kj, env)
        except Exception:
            events = []
    return {
        "name": name,
        "site": _site(eqn),
        "grid": grid,
        "axis_size": ex.n,
        "blocks": blocks,
        "refs": ex.refs,
        "events": events,
    }


def _walk(jaxpr, axis_sizes: Dict[str, int], out: List) -> None:
    for eqn in jaxpr.eqns:
        pname = eqn.primitive.name
        if pname == "pallas_call":
            out.append(_pallas_record(eqn, axis_sizes))
            continue
        sizes = axis_sizes
        if pname == "shard_map":
            mesh = eqn.params.get("mesh")
            try:
                sizes = dict(axis_sizes)
                sizes.update({str(k): int(v)
                              for k, v in dict(mesh.shape).items()})
            except Exception:
                sizes = axis_sizes
        for v in eqn.params.values():
            cands = v if isinstance(v, (list, tuple)) else (v,)
            for c in cands:
                inner = c if hasattr(c, "eqns") else getattr(c, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _walk(inner, sizes, out)


def extract_pallas_records(closed_jaxpr) -> List[dict]:
    """Every pallas_call in a traced graph -> JSON-native audit records
    (canonicalized through JSON so cold and cache-loaded copies compare
    equal, matching the layer-4 artifact contract)."""
    out: List = []
    _walk(closed_jaxpr.jaxpr, {}, out)
    return json.loads(json.dumps(out))


# ---------------------------------------------------------------------------
# slice / overlap helpers shared by the rules
# ---------------------------------------------------------------------------


def _slot_key(slices) -> str:
    return json.dumps(slices, sort_keys=True)


def _dim_overlap(a: dict, b: dict, n: Optional[int]) -> bool:
    sa, sb = a.get("start"), b.get("start")
    za, zb = a.get("size", 1), b.get("size", 1)
    if sa == "?" or sb == "?":
        return True
    la = _lift(sa, n) if isinstance(sa, int) else sa
    lb = _lift(sb, n) if isinstance(sb, int) else sb
    if isinstance(la, int) and isinstance(lb, int):
        la, lb = [la], [lb]
    if not (isinstance(la, list) and isinstance(lb, list)):
        return True
    if len(la) != len(lb):
        return True
    return any(x < y + zb and y < x + za for x, y in zip(la, lb))


def _slices_overlap(a: Optional[dict], b: Optional[dict],
                    n: Optional[int]) -> bool:
    """Do two {"ref", "slices"} access descriptors overlap on any device?"""
    if a is None or b is None:
        return False
    if a["ref"] != b["ref"] or a["ref"] == "?":
        return a["ref"] == "?" and b["ref"] == "?"
    xs, ys = a["slices"], b["slices"]
    if len(xs) != len(ys):
        return True
    return all(_dim_overlap(x, y, n) for x, y in zip(xs, ys))


def _incoming(dst: Optional[dict], dev, n: Optional[int]) -> Optional[dict]:
    """The SPMD-symmetric incoming remote write: shard r receives the
    write whose slice expression the sender s (device_id(s) == r)
    evaluated at s.  Unknown / non-bijective mappings degrade to "?"."""
    if dst is None:
        return None
    if n is None or dev in (None, "?"):
        return {"ref": dst["ref"],
                "slices": [{"start": "?", "size": s.get("size", 1)}
                           for s in dst["slices"]]}
    dv = _lift(dev, n) if isinstance(dev, int) else dev
    perm: Dict[int, int] = {}
    ok = isinstance(dv, list) and len(dv) == n
    if ok:
        for s, tgt in enumerate(dv):
            if not isinstance(tgt, int) or not 0 <= tgt < n or tgt in perm:
                ok = False
                break
            perm[tgt] = s
    out_slices = []
    for sl in dst["slices"]:
        st = sl.get("start")
        if not ok or st == "?":
            out_slices.append({"start": "?", "size": sl.get("size", 1)})
            continue
        vec = _lift(st, n) if isinstance(st, int) else st
        if not (isinstance(vec, list) and len(vec) == n):
            out_slices.append({"start": "?", "size": sl.get("size", 1)})
            continue
        out_slices.append({"start": [vec[perm[r]] for r in range(n)],
                           "size": sl.get("size", 1)})
    return {"ref": dst["ref"], "slices": out_slices}


def _where(rec: dict, ev: Optional[dict], fallback: str) -> Tuple[str, int]:
    site = (ev or rec).get("site") or ["", 0]
    if site[0]:
        return site[0], int(site[1])
    rsite = rec.get("site") or ["", 0]
    return (rsite[0] or fallback), int(rsite[1])


# ---------------------------------------------------------------------------
# rule (a): DMA/semaphore balance
# ---------------------------------------------------------------------------


def _sem_ledger(events, ledger: Dict[str, dict], viol: List[Violation],
                rec: dict, fallback: str) -> None:
    for ev in events:
        op = ev.get("op")
        if op == "dma_start":
            for part in ("dst_sem", "src_sem"):
                s = ev.get(part)
                if s is None:
                    continue
                k = f"{s['ref']}|{_slot_key(s['slices'])}"
                e = ledger.setdefault(
                    k, {"net": 0, "start": None, "wait": None})
                e["net"] += 1
                e["start"] = e["start"] or ev.get("site")
        elif op == "dma_wait":
            s = ev.get("dst_sem")
            if s is None:
                continue
            k = f"{s['ref']}|{_slot_key(s['slices'])}"
            e = ledger.setdefault(k, {"net": 0, "start": None, "wait": None})
            e["net"] -= 1
            e["wait"] = e["wait"] or ev.get("site")
        elif op == "loop":
            sub: Dict[str, dict] = {}
            _sem_ledger(ev["body"], sub, viol, rec, fallback)
            for k, e in sub.items():
                if e["net"] != 0:
                    f, ln = _where(rec, {"site": e["start"] or e["wait"]
                                         or ev.get("site")}, fallback)
                    viol.append(Violation(
                        RULE_DMA, f, ln,
                        f"DMA semaphore {k.split('|')[0]} nets "
                        f"{e['net']:+d} per loop iteration in kernel "
                        f"'{rec['name']}' — counts leak across iterations "
                        f"(and across grid steps)"))
        elif op == "cond":
            nets = []
            for br in ev["branches"]:
                sub = {}
                _sem_ledger(br, sub, viol, rec, fallback)
                nets.append({k: e["net"] for k, e in sub.items()
                             if e["net"] != 0})
                for k, e in sub.items():
                    ledger.setdefault(
                        k, {"net": 0, "start": None, "wait": None})
                    ledger[k]["start"] = ledger[k]["start"] or e["start"]
                    ledger[k]["wait"] = ledger[k]["wait"] or e["wait"]
            if any(nz != nets[0] for nz in nets[1:]):
                f, ln = _where(rec, ev, fallback)
                viol.append(Violation(
                    RULE_DMA, f, ln,
                    f"DMA semaphore balance differs between cond branches "
                    f"in kernel '{rec['name']}' — some control path leaves "
                    f"a start without its wait"))
            elif nets and nets[0]:
                for k, d in nets[0].items():
                    ledger.setdefault(
                        k, {"net": 0, "start": None, "wait": None})
                    ledger[k]["net"] += d


def _check_dma_balance(rec: dict, fallback: str) -> List[Violation]:
    viol: List[Violation] = []
    ledger: Dict[str, dict] = {}
    _sem_ledger(rec.get("events", ()), ledger, viol, rec, fallback)
    # collapse per-slot entries into one per-ref bucket when any slot on
    # that ref failed to decode ("?" starts) — avoids phantom imbalance
    # from a start and its wait landing in different keys
    unknown = {k.split("|")[0] for k in ledger if '"?"' in k}
    merged: Dict[str, dict] = {}
    for k, e in ledger.items():
        rid = k.split("|")[0]
        mk = rid if rid in unknown else k
        m = merged.setdefault(mk, {"net": 0, "start": None, "wait": None})
        m["net"] += e["net"]
        m["start"] = m["start"] or e["start"]
        m["wait"] = m["wait"] or e["wait"]
    for k, e in merged.items():
        if e["net"] > 0:
            f, ln = _where(rec, {"site": e["start"]}, fallback)
            viol.append(Violation(
                RULE_DMA, f, ln,
                f"{e['net']} DMA start(s) on semaphore {k.split('|')[0]} "
                f"without a matching wait in kernel '{rec['name']}' — the "
                f"semaphore count leaks across grid steps"))
        elif e["net"] < 0:
            f, ln = _where(rec, {"site": e["wait"]}, fallback)
            viol.append(Violation(
                RULE_DMA, f, ln,
                f"{-e['net']} DMA wait(s) on semaphore {k.split('|')[0]} "
                f"with no matching start in kernel '{rec['name']}' — "
                f"deadlocks at the first grid step"))
    return viol


# ---------------------------------------------------------------------------
# rule (b): ref races / double-buffer slot aliasing
# ---------------------------------------------------------------------------


def _race_replay(events, state: List[dict], rec: dict, fallback: str,
                 seen, viol: List[Violation]) -> List[dict]:
    n = rec.get("axis_size")

    def emit(ev, msg):
        f, ln = _where(rec, ev, fallback)
        key = (RULE_RACE, f, ln, msg[:40])
        if key not in seen:
            seen.add(key)
            viol.append(Violation(RULE_RACE, f, ln, msg))

    def check_access(ev, acc, is_write, what):
        if acc is None:
            return
        for rec_if in state:
            for w in rec_if["writes"]:
                if _slices_overlap(acc, w, n):
                    emit(ev, f"{what} of ref {acc['ref']} slice overlaps an "
                             f"in-flight DMA write with no intervening "
                             f"semaphore wait in kernel '{rec['name']}' "
                             f"(double-buffer slot reuse hazard)")
                    return
            if is_write:
                for r in rec_if["reads"]:
                    if _slices_overlap(acc, r, n):
                        emit(ev, f"write to ref {acc['ref']} slice still "
                                 f"being read by an in-flight DMA in kernel "
                                 f"'{rec['name']}'")
                        return

    for ev in events:
        op = ev.get("op")
        if op == "dma_start":
            for part in ("dst_sem", "src_sem"):
                s = ev.get(part)
                if s is None:
                    continue
                for rec_if in state:
                    sp = rec_if["sem"]
                    if sp and s["ref"] == sp["ref"] and s["ref"] != "?" and \
                            _slices_overlap(s, sp, n):
                        emit(ev, f"DMA started on semaphore {s['ref']} slot "
                                 f"already guarding an in-flight transfer "
                                 f"in kernel '{rec['name']}' — slot "
                                 f"aliasing, waits become ambiguous")
            src, dst = ev.get("src"), ev.get("dst")
            remote = ev.get("device_id") is not None
            check_access(ev, src, False, "DMA source read")
            wr = _incoming(dst, ev.get("device_id"), n) if remote else dst
            check_access(ev, wr, True, "DMA destination write")
            if remote:
                state.append({"sem": ev.get("src_sem"),
                              "reads": [src] if src else [], "writes": []})
                state.append({"sem": ev.get("dst_sem"), "reads": [],
                              "writes": [wr] if wr else []})
            else:
                state.append({"sem": ev.get("dst_sem"),
                              "reads": [src] if src else [],
                              "writes": [dst] if dst else []})
        elif op == "dma_wait":
            s = ev.get("dst_sem")
            if s is None:
                state.clear()
            else:
                state[:] = [r for r in state
                            if not (r["sem"] and r["sem"]["ref"] == s["ref"]
                                    and _slices_overlap(r["sem"], s, n))]
        elif op == "semaphore_wait":
            state.clear()  # generous: any explicit wait orders everything
        elif op == "get":
            check_access(ev, ev.get("target"), False, "read")
        elif op == "swap":
            check_access(ev, ev.get("target"), True, "write")
        elif op == "loop":
            # second pass catches hazards that only appear once iteration
            # k+1's accesses meet iteration k's still-in-flight DMAs
            state = _race_replay(ev["body"], state, rec, fallback, seen, viol)
            state = _race_replay(ev["body"], state, rec, fallback, seen, viol)
        elif op == "cond":
            outs: List[dict] = []
            for br in ev["branches"]:
                outs.extend(_race_replay(list(br), list(state), rec,
                                         fallback, seen, viol))
            state = outs
    return state


def _check_ref_races(rec: dict, fallback: str) -> List[Violation]:
    viol: List[Violation] = []
    _race_replay(rec.get("events", ()), [], rec, fallback, set(), viol)
    return viol


# ---------------------------------------------------------------------------
# rule (c): ring neighbor topology
# ---------------------------------------------------------------------------


def _ring_events(events):
    for ev in events:
        op = ev.get("op")
        if op == "dma_start":
            yield ev
        elif op == "loop":
            yield from _ring_events(ev["body"])
        elif op == "cond":
            for br in ev["branches"]:
                yield from _ring_events(br)


def _check_ring(rec: dict, fallback: str) -> List[Violation]:
    n = rec.get("axis_size")
    out: List[Violation] = []
    for ev in _ring_events(rec.get("events", ())):
        dev = ev.get("device_id")
        if dev is None or dev == "?" or n is None:
            continue
        vec = _lift(dev, n) if isinstance(dev, int) else dev
        if not (isinstance(vec, list) and len(vec) == n):
            continue
        bad_range = [(i, d) for i, d in enumerate(vec)
                     if not (isinstance(d, int) and 0 <= d < n)]
        self_send = [i for i, d in enumerate(vec) if d == i]
        f, ln = _where(rec, ev, fallback)
        if bad_range:
            i, d = bad_range[0]
            out.append(Violation(
                RULE_RING, f, ln,
                f"remote DMA device_id not congruent mod the axis size in "
                f"kernel '{rec['name']}': axis_index {i} targets device "
                f"{d} outside [0, {n}) — wrap with rem(x + {n}, {n})"))
        if self_send:
            out.append(Violation(
                RULE_RING, f, ln,
                f"remote DMA self-send in kernel '{rec['name']}': "
                f"axis_index {self_send[0]} targets itself — the ring "
                f"neighbor expression must never be the identity"))
    return out


# ---------------------------------------------------------------------------
# rule (d): Mosaic tiling / memory-space sanity
# ---------------------------------------------------------------------------


def _check_tiling(rec: dict, fallback: str) -> List[Violation]:
    out: List[Violation] = []
    grid = rec.get("grid") or []
    gridded = bool(grid) and all(isinstance(g, int) for g in grid)
    f, ln = _where(rec, None, fallback)
    if gridded:
        for b in rec.get("blocks", ()):
            blk, arr = b["block"], b["array"]
            if blk == arr or len(blk) != len(arr):
                continue
            if "sem" in b.get("space", ""):
                continue
            sub = _SUBLANE.get(b.get("dtype", ""), 8)
            rank = len(blk)
            for d, (bd, ad) in enumerate(zip(blk, arr)):
                if bd == ad:
                    continue
                if bd <= 0 or ad % bd != 0:
                    out.append(Violation(
                        RULE_TILE, f, ln,
                        f"block shape {blk} does not divide operand shape "
                        f"{arr} on dim {d} of '{b.get('origin', '?')}' in "
                        f"kernel '{rec['name']}' — partial edge blocks are "
                        f"the BENCH_r05 Mosaic rc=124 class"))
                    continue
                tile = sub if d == rank - 2 else (
                    _LANE if d == rank - 1 else None)
                if tile and bd % tile != 0:
                    out.append(Violation(
                        RULE_TILE, f, ln,
                        f"block dim {d} of '{b.get('origin', '?')}' splits "
                        f"a tiled axis into {bd}-wide pieces in kernel "
                        f"'{rec['name']}' — {b.get('dtype', '?')} needs "
                        f"({sub}, {_LANE}) alignment on the trailing dims"))
    refs = rec.get("refs", {})

    def ref_is_sem(acc):
        r = refs.get((acc or {}).get("ref"))
        return None if r is None else r.get("sem", False)

    for ev in _ring_events(rec.get("events", ())):
        for part in ("dst_sem", "src_sem"):
            if ev.get(part) is not None and ref_is_sem(ev[part]) is False:
                ef, eln = _where(rec, ev, fallback)
                out.append(Violation(
                    RULE_TILE, ef, eln,
                    f"DMA semaphore position holds non-semaphore ref "
                    f"{ev[part]['ref']} ({refs.get(ev[part]['ref'], {}).get('space', '?')}) "
                    f"in kernel '{rec['name']}'"))
        for part in ("src", "dst"):
            if ev.get(part) is not None and ref_is_sem(ev[part]) is True:
                ef, eln = _where(rec, ev, fallback)
                out.append(Violation(
                    RULE_TILE, ef, eln,
                    f"semaphore-space ref {ev[part]['ref']} used as DMA "
                    f"data in kernel '{rec['name']}'"))
    return out


# ---------------------------------------------------------------------------
# record -> violations driver
# ---------------------------------------------------------------------------


def check_pallas_records(where: str, records) -> List[Violation]:
    """All four rules over a list of extracted pallas_call records."""
    out: List[Violation] = []
    for rec in records or ():
        out.extend(_check_dma_balance(rec, where))
        out.extend(_check_ref_races(rec, where))
        out.extend(_check_ring(rec, where))
        out.extend(_check_tiling(rec, where))
    return out


# ---------------------------------------------------------------------------
# kernel-library entry registry (the pallas_calls NOT reachable from the
# layer-4 dispatch entries) + cache-riding driver
# ---------------------------------------------------------------------------


def pallas_entry_points() -> Dict[str, dict]:
    """name -> {fn, args}: every Pallas kernel the library exposes that
    the layer-4 entry sweep cannot reach.  All trace with interpret=True
    (lowering-only difference; tracing must not need a TPU).  The ring
    entry needs a >= PALLAS_AUDIT_MESH-device mesh and is skipped when
    unavailable (tier-1 and tools/lint.py both force 8 virtual
    devices)."""
    import jax
    import jax.numpy as jnp

    from ..ops import pallas_fuse as pf
    from ..ops import pallas_tower as pt
    from ..ops import tower as tw

    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    B = 4
    out = {
        "pallas_tower.fq2_mul": {
            "fn": lambda a, b: pt.fq2_mul(a, b, interpret=True),
            "args": (S((B, 2, 50), f32), S((B, 2, 50), f32)),
        },
        "pallas_tower.fq2_sqr": {
            "fn": lambda a: pt.fq2_sqr(a, interpret=True),
            "args": (S((B, 2, 50), f32),),
        },
        "pallas_tower.fq6_mul": {
            "fn": lambda a, b: pt.fq6_mul(a, b, interpret=True),
            "args": (S((B, 3, 2, 50), f32), S((B, 3, 2, 50), f32)),
        },
        "pallas_tower.fq12_mul": {
            "fn": lambda a, b: pt.fq12_mul(a, b, interpret=True),
            "args": (S((B, 6, 2, 50), f32), S((B, 6, 2, 50), f32)),
        },
        "pallas_fuse.fq2_mul": {
            "fn": pf.pallas_fuse(
                tw.fq2_mul, S((B, 2, 50), f32), S((B, 2, 50), f32),
                interpret=True),
            "args": (S((B, 2, 50), f32), S((B, 2, 50), f32)),
        },
    }
    from . import jaxpr_audit as ja

    if ja.sharded_audit_available():
        from ..ops import pallas_ring as pr
        from ..ops import sharded_verify as sv

        mesh = sv.make_mesh(n_devices=PALLAS_AUDIT_MESH)
        out["pallas_ring.ring_combine"] = {
            "fn": pr.ring_combine_fn(mesh, interpret=True),
            "args": (S((PALLAS_AUDIT_MESH, 6, 2, 50), f32),),
        }
    return out


@functools.lru_cache(maxsize=None)
def trace_pallas_entry(name: str):
    import jax

    meta = pallas_entry_points()[name]
    return jax.make_jaxpr(meta["fn"])(*meta["args"])


@functools.lru_cache(maxsize=None)
def pallas_entry_artifacts(name: str, use_cache: bool = True) -> dict:
    """Extracted records for one kernel-library entry — rides the
    layer-4 disk cache (same fingerprint, "pallas:"-prefixed keys)."""
    from . import jaxpr_audit as ja

    key = f"pallas:{name}"
    if use_cache:
        cached = ja._load_disk_cache().get(key)
        if cached is not None:
            return cached
    art = {"pallas": extract_pallas_records(trace_pallas_entry(name))}
    if use_cache:
        ja._store_disk_cache(key, art)
    return art


def audit_pallas_entry(name: str, use_cache: bool = True) -> List[Violation]:
    art = pallas_entry_artifacts(name, use_cache)
    return check_pallas_records(name, art.get("pallas"))


def audit_all_pallas(use_cache: bool = True) -> List[Violation]:
    """All four rules over every kernel-library entry.  The layer-4
    dispatch entries are swept separately by jaxpr_audit.audit_entry via
    the "pallas" artifact field."""
    out: List[Violation] = []
    for name in pallas_entry_points():
        out.extend(audit_pallas_entry(name, use_cache))
    return out
