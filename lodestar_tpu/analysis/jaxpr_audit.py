"""Jaxpr/IR auditor: TPU-portability invariants checked on abstract traces.

Every public fused entry point in ``lodestar_tpu/ops/`` is traced with
``jax.make_jaxpr`` on ShapeDtypeStructs — abstract values only, so the
audit runs on a CPU-only host, materializes no device programs, and stays
inside the tier-1 conftest compile guard (the backend_compile monitoring
event never fires for a trace).

Rules over the (recursively walked) equation graph:

- ``jaxpr-narrow-mixed-concat``  a ``concatenate`` whose operand extents
  along the concat dim differ while every tiled non-concat dim (the
  trailing two — Mosaic's (8, 128) vreg tile) is below the tile.  This is
  the exact shape class Mosaic rejects with "result/input offset mismatch
  on non-concat dimension" (BENCH_r05 rc=124); batch-axis splices must
  route through ``fused_core.aligned_splice`` (offset-0 pads + adds),
  which emits NO concatenate — so this rule is also the machine check
  that every splice took that route.  Scope: Mosaic-bound (fused)
  entries only — the XLA-graph twins never lower through Mosaic, and XLA
  retiles these concats fine (they are all over the portable kernels by
  design).
- ``jaxpr-f64-leak``             a 64-bit float/int abstract value
  anywhere in the graph.  The sanctioned limb format is f32 digit arrays
  (8-bit digits, 50 limbs); a float64 sneaking in silently doubles
  register pressure on TPU or — worse — gets truncated.
- ``jaxpr-host-callback``        ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` (debug_print lowers to it) in a hot-path program:
  every callback is a device->host round trip serialized into the
  dispatch.
- ``jaxpr-mxu-precision``        a ``dot_general`` anywhere in an audited
  entry that does not carry the full MXU precision contract: an explicit
  f32 ``preferred_element_type`` AND ``precision=HIGHEST`` on both
  operands.  The limb representation's exactness proofs assume f32
  accumulation; without the contract XLA may evaluate f32 dots through
  bf16 operands inside fusions (the pre-MXU-rewrite pathology that once
  banned dots from ops/limbs.py entirely) — silently rounding 16-bit
  digit products.  Every live dot must route through ``limbs._dot_f32``
  or ``fused_core._m_dot``, which both carry the contract.
- ``jaxpr-unstable-cache-key``   a Python scalar captured as a traced
  constant (rank-0 const), or a constant set that differs between bucket
  sizes.  Captured scalars make the executable hostage to a Python value
  the jit cache key cannot see (the key is (fn, avals) — a changed
  closure silently reuses the stale program); bucket-dependent constants
  multiply the per-kernel Mosaic compiles the BLK-grid design exists to
  avoid.  NOTE the per-bucket program *structure* is allowed to differ —
  the pow2-padded RLC product trees are batch-count-dependent by design
  and each bucket is its own compiled program.

Sharded-entry rule set (the round-11 mesh programs,
``ops/sharded_verify``): the concat/f64/callback/cache-key rules all
apply to the ``shard_map``-mapped body (walk_eqns recurses into the
shard_map jaxpr param like any other sub-jaxpr), plus two rules over the
body's collective structure:

- ``jaxpr-sharded-no-collective``  a sharded entry whose mapped body
  contains no cross-shard collective (all_gather/ppermute/psum/...) —
  each shard would silently verify only its local slice and the "mesh
  verdict" would be one shard's opinion.
- ``jaxpr-sharded-local-final-exp``  a final-exponentiation pow-x scan
  (length ``len(_X_WINDOWS)``, Fq12-shaped carry) appearing BEFORE the
  body's first collective: final-exp running per SHARD instead of once
  on the combined product — the serial scan the split/sharded design
  exists to pay exactly once per merged batch.

``trace_entry`` is lru-cached per (entry, bucket): the alignment contract
test, the static-analysis test, and tools/lint.py share one trace — the
trace of the full fused graph is the expensive part (~15-30 s), so it is
paid once per process.

On top of that, the audit is INCREMENTAL across processes: everything the
rules (and the alignment tests) consume is distilled into a small
JSON-able ``artifact`` per (entry, bucket) — mixed-extent concats, wide
dtypes, callback primitives, captured consts, out avals — and persisted
under ``.jax_cache/`` keyed by a content hash of ``lodestar_tpu/ops/``.
While ops/ is untouched, a tier-1 run replays artifacts in milliseconds
instead of re-spending ~100 s of abstract tracing; any edit to ops/ (or a
jax upgrade, or a rule needing new artifact fields via _CACHE_VERSION)
invalidates the whole cache and the next run re-traces.  Mutation and
fixture tests never touch this cache — they trace their own (tiny)
programs directly, so detection is always proven live.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
from typing import Dict, Iterable, List, Sequence, Tuple

from .report import Violation

# Default bucket pair: the smallest production bucket and the reference's
# MAX_SIGNATURE_SETS_PER_JOB analog — the pair the alignment tests pinned
# since PR 1, so tier-1 traces are shared, not re-spent.
AUDIT_BUCKETS: Tuple[int, int] = (4, 128)

# Sharded audit shape: global bucket 8 over a 2-device mesh — the local
# shard body is the bucket-4 graph the single-chip audit already traces,
# so the incremental trace cost is one extra bucket-4-sized walk per
# flavor, amortized by the artifact disk cache like everything else.
SHARDED_AUDIT_BUCKETS: Tuple[int, ...] = (8,)
SHARDED_AUDIT_MESH = 2

_CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback")
_WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")

#: cross-shard collective primitives a sharded body must contain
_COLLECTIVE_PRIMITIVES = (
    "all_gather", "ppermute", "pshuffle", "psum", "all_reduce",
    "reduce_scatter", "all_to_all",
)

#: pow-x window scans one final exponentiation contributes (the x-chain:
#: y0, y1, y2 and y3's double pow — fused_pairing.final_exponentiation)
FINAL_EXP_POW_SCANS = 5


# ---------------------------------------------------------------------------
# entry-point registry
# ---------------------------------------------------------------------------


def _abstract_batch(n: int):
    """ShapeDtypeStructs matching TpuBlsVerifier.pack() output — the input
    contract every batched entry point shares."""
    import jax
    import jax.numpy as jnp

    from ..ops import limbs as fl

    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    return (
        S((n, fl.NLIMBS), f32),
        S((n, fl.NLIMBS), f32),
        S((n, 2, fl.NLIMBS), f32),
        S((n, 2, fl.NLIMBS), f32),
        S((n, 2, 2, fl.NLIMBS), f32),
        S((n, 64), f32),
        S((n,), jnp.bool_),
    )


def entry_points() -> Dict[str, dict]:
    """name -> {fn, mosaic}: plain functions of the abstract batch args.

    The two fused programs cover the whole Pallas call graph
    (fused_points / fused_pairing / fused_htc / fused_ladder /
    fused_field / fused_core are all reached from them) and are the
    Mosaic-bound entries; the two XLA-graph kernels are the portable
    twins TpuBlsVerifier degrades to (``mosaic=False`` — XLA retiles
    narrow concats fine, so the concat rule does not apply to them).
    Fused entries trace with interpret=True — interpret only affects
    lowering, and tracing must not require a TPU plugin."""
    from ..ops import batch_verify as bv
    from ..ops import fused_verify as fv

    def fused_split(*a):
        f, ok = fv.miller_product_fused(*a, interpret=True)
        return f.a, ok  # digits + verdict (the static bound is not an output)

    def fused_full(*a):
        return fv.verify_signature_sets_fused(*a, interpret=True)

    return {
        "fused_verify.miller_product_fused": {"fn": fused_split, "mosaic": True},
        "fused_verify.verify_signature_sets_fused": {"fn": fused_full, "mosaic": True},
        "batch_verify.miller_product_kernel": {
            "fn": bv.miller_product_kernel, "mosaic": False,
        },
        "batch_verify.verify_signature_sets_kernel": {
            "fn": bv.verify_signature_sets_kernel, "mosaic": False,
        },
    }


def sharded_audit_available() -> bool:
    """The sharded entries need a real >= 2-device mesh at trace time
    (shard_map binds mesh devices); a 1-device host skips them — the
    8-virtual-device tier-1/conftest environment and tools/lint.py (which
    forces the host device count) both qualify."""
    try:
        import jax

        return len(jax.devices()) >= SHARDED_AUDIT_MESH
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def sharded_entry_points() -> Dict[str, dict]:
    """name -> {fn, mosaic, sharded}: the round-11 mesh entry points over
    a SHARDED_AUDIT_MESH-device mesh.  The fused flavor traces with
    interpret=True (lowering-only difference, no TPU plugin needed) and
    carries the Mosaic concat rules; the XLA full flavor carries the
    final-exp placement the full path runs on device."""
    from ..ops import sharded_verify as sv

    mesh = sv.make_mesh(n_devices=SHARDED_AUDIT_MESH)
    return {
        "sharded_verify.miller_product_sharded": {
            "fn": sv.miller_product_sharded(mesh, fused=True, interpret=True),
            "mosaic": True,
            "sharded": True,
        },
        "sharded_verify.verify_signature_sets_sharded": {
            "fn": sv.verify_signature_sets_sharded(mesh, fused=False),
            "mosaic": False,
            "sharded": True,
        },
    }


def _entry_meta(name: str) -> dict:
    eps = entry_points()
    if name in eps:
        return eps[name]
    return sharded_entry_points()[name]


@functools.lru_cache(maxsize=None)
def trace_entry(name: str, bucket: int):
    """ClosedJaxpr of one entry point at one bucket (cached per process)."""
    import jax

    fn = _entry_meta(name)["fn"]
    return jax.make_jaxpr(fn)(*_abstract_batch(bucket))


# ---------------------------------------------------------------------------
# graph walking
# ---------------------------------------------------------------------------


def walk_eqns(jaxpr, out: List) -> None:
    """Flatten every equation, recursing into sub-jaxprs (scan/while/cond
    bodies, pjit, custom_* rules, pallas_call kernels) wherever a param
    carries one."""
    for eqn in jaxpr.eqns:
        out.append(eqn)
        for v in eqn.params.values():
            if hasattr(v, "eqns"):
                walk_eqns(v, out)
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                walk_eqns(v.jaxpr, out)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if hasattr(item, "eqns"):
                        walk_eqns(item, out)
                    elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                        walk_eqns(item.jaxpr, out)


def all_eqns(closed_jaxpr) -> List:
    eqns: List = []
    walk_eqns(closed_jaxpr.jaxpr, eqns)
    return eqns


# ---------------------------------------------------------------------------
# trace artifacts: the JSON-able distillate every rule consumes
# ---------------------------------------------------------------------------

# schema tag folded into the fingerprint alongside a hash of this module's
# own source (so editing the trace inputs or extraction logic invalidates
# the cache automatically, no manual bump required)
_CACHE_VERSION = 4  # v4: pallas_call kernel records (pallas_audit layer)


def _eqn_site(eqn) -> Tuple[str, int]:
    """User-source (file, line) of an equation, '' / 0 when unavailable —
    same mapping the limb-interval findings use, so the known-bad fixture
    can pin violations to its ``# VIOLATION`` lines."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, frame.start_line
    except Exception:
        pass
    return "", 0


def _precision_is_highest(precision) -> bool:
    """True iff the dot's precision config pins HIGHEST on both operands.
    The param may be None, a single Precision, or a 2-tuple; enum names
    overlap as prefixes (HIGH vs HIGHEST) so compare full names."""
    if precision is None:
        return False
    vals = precision if isinstance(precision, (tuple, list)) else (precision,)
    names = [str(getattr(v, "name", v)).rsplit(".", 1)[-1] for v in vals]
    return bool(names) and all(n == "HIGHEST" for n in names)


def _dot_general_census(eqns: List) -> List[list]:
    """One row per distinct dot_general call site:
    [file, line, precision_is_highest, preferred_element_type_name].
    preferred name is "" when the dot carries none."""
    rows, seen = [], set()
    for eqn in eqns:
        if eqn.primitive.name != "dot_general":
            continue
        fname, line = _eqn_site(eqn)
        prec_ok = _precision_is_highest(eqn.params.get("precision"))
        pref = eqn.params.get("preferred_element_type")
        if pref is None:
            pref_name = ""
        else:
            import numpy as np

            try:
                pref_name = np.dtype(pref).name
            except TypeError:
                pref_name = str(pref)
        key = (fname, line, prec_ok, pref_name)
        if key not in seen:
            seen.add(key)
            rows.append([fname, line, prec_ok, pref_name])
    return rows


def _is_final_exp_scan(eqn) -> bool:
    """A pow-by-x window scan: length == len(_X_WINDOWS) with an
    Fq12-shaped ((6, 2, NLIMBS)-trailing) carry — 5 of these per final
    exponentiation, and nothing else in the verify graphs matches both
    the length and the carry shape."""
    if eqn.primitive.name != "scan":
        return False
    from ..ops import limbs as fl
    from ..ops.pairing import _X_WINDOWS

    if eqn.params.get("length") != len(_X_WINDOWS):
        return False
    sig = (6, 2, fl.NLIMBS)
    return any(
        tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())[-3:] == sig
        for v in eqn.outvars
    )


def _find_shard_map_bodies(jaxpr, out: List) -> None:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            body = eqn.params.get("jaxpr")
            if hasattr(body, "eqns"):
                out.append(body)
        for v in eqn.params.values():
            if hasattr(v, "eqns"):
                _find_shard_map_bodies(v, out)
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                _find_shard_map_bodies(v.jaxpr, out)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if hasattr(item, "eqns"):
                        _find_shard_map_bodies(item, out)
                    elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                        _find_shard_map_bodies(item.jaxpr, out)


def _sharded_stats(closed_jaxpr):
    """Collective/final-exp ordering stats over every shard_map body in
    the graph (None when there is none).  walk_eqns order is depth-first
    in body order, so "before the first collective" is a sound program-
    order statement for the top-level body structure."""
    bodies: List = []
    _find_shard_map_bodies(closed_jaxpr.jaxpr, bodies)
    if not bodies:
        return None
    collectives: List[str] = []
    n_final_exp = 0
    before_combine = 0
    for body in bodies:
        eqns: List = []
        walk_eqns(body, eqns)
        seen_collective = False
        for eqn in eqns:
            pname = eqn.primitive.name
            if pname in _COLLECTIVE_PRIMITIVES:
                collectives.append(pname)
                seen_collective = True
            elif _is_final_exp_scan(eqn):
                n_final_exp += 1
                if not seen_collective:
                    before_combine += 1
    return {
        "collectives": sorted(set(collectives)),
        "final_exp_scans": n_final_exp,
        "final_exp_scans_before_combine": before_combine,
    }


def extract_artifacts(closed_jaxpr) -> dict:
    """One walk over the (flattened) graph -> everything the rules and the
    alignment tests need, as plain JSON-native data (lists/strs/ints), so
    equality is stable across a serialize/deserialize round trip."""
    eqns = all_eqns(closed_jaxpr)
    wide, seen_wide = [], set()
    callbacks = []
    for eqn in eqns:
        pname = eqn.primitive.name
        if any(cb in pname for cb in _CALLBACK_PRIMITIVES):
            callbacks.append(pname)
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and dt.name in _WIDE_DTYPES:
                key = (pname, dt.name)
                if key not in seen_wide:
                    seen_wide.add(key)
                    wide.append([pname, dt.name])
    rank0 = []
    for c in closed_jaxpr.consts:
        shape = getattr(c, "shape", None)
        if shape is not None and tuple(shape) == ():
            rank0.append(repr(c)[:120])
    art = {
        "mixed_concats": [
            [d, [list(s) for s in shapes]]
            for d, shapes in narrow_mixed_concats(eqns)
        ],
        "wide_dtypes": wide,
        "callbacks": callbacks,
        "rank0_consts": rank0,
        "dot_generals": _dot_general_census(eqns),
        "const_census": _const_census(closed_jaxpr),
        "out_avals": [
            [list(a.shape), a.dtype.name] for a in closed_jaxpr.out_avals
        ],
        "sharded": _sharded_stats(closed_jaxpr),
    }
    # layer-5 sweep: every pallas_call reachable from this entry gets a
    # kernel record (lazy import — pallas_audit imports this module)
    from . import pallas_audit

    art["pallas"] = pallas_audit.extract_pallas_records(closed_jaxpr)
    # canonicalize through JSON so cold-extracted and cache-loaded
    # artifacts compare equal (tuples -> lists, np ints -> ints)
    return json.loads(json.dumps(art))


def _ops_fingerprint() -> str:
    """Content hash of everything an artifact can depend on: the traced
    package (lodestar_tpu/ops/), THIS module's source (the abstract input
    contract, entry wrappers, and extraction logic all live here), the jax
    version, and the schema tag."""
    import jax

    h = hashlib.sha256()
    h.update(f"v{_CACHE_VERSION}:jax={jax.__version__}:".encode())
    # the limb-multiply mode changes every traced graph (ladder rows vs
    # MXU dots), so a mode flip must never replay the other mode's
    # artifacts — fold the resolved mode into the fingerprint
    from ..ops.limbs import limb_mul_mode

    h.update(f"limb_mul={limb_mul_mode()}:".encode())
    here = os.path.abspath(__file__).replace(".pyc", ".py")
    # pallas_audit's extraction logic feeds the "pallas" artifact field
    # and the pallas:<entry> records — its edits must invalidate too
    for mod in (here, os.path.join(os.path.dirname(here), "pallas_audit.py")):
        with open(mod, "rb") as f:
            h.update(f.read())
    ops_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "ops")
    for dirpath, dirnames, filenames in os.walk(ops_dir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            h.update(os.path.relpath(full, ops_dir).encode())
            with open(full, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _cache_path() -> str:
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo, ".jax_cache", "jaxpr_audit_artifacts.json")


@functools.lru_cache(maxsize=1)
def _load_disk_cache() -> dict:
    try:
        with open(_cache_path()) as f:
            data = json.load(f)
        if data.get("fingerprint") == _ops_fingerprint():
            return data.get("artifacts", {})
    except (OSError, ValueError):
        pass
    return {}


def _store_disk_cache(key: str, art: dict) -> None:
    path = _cache_path()
    arts = dict(_load_disk_cache())
    arts[key] = art
    _load_disk_cache.cache_clear()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"fingerprint": _ops_fingerprint(), "artifacts": arts}, f)
        os.replace(tmp, path)  # atomic: concurrent readers never see half a file
    except OSError:
        pass  # cache is best-effort; next run just re-traces


@functools.lru_cache(maxsize=None)
def entry_artifacts(name: str, bucket: int, use_cache: bool = True) -> "dict":
    """Artifacts for one entry point at one bucket — disk-cache first
    (content-addressed on ops/), tracing only on a miss."""
    key = f"{name}@{bucket}"
    if use_cache:
        cached = _load_disk_cache().get(key)
        if cached is not None:
            return cached
    art = extract_artifacts(trace_entry(name, bucket))
    if use_cache:
        _store_disk_cache(key, art)
    return art


def entry_out_avals(name: str, bucket: int) -> List[tuple]:
    """[(shape tuple, dtype name), ...] of an entry's outputs — the shape
    oracle the alignment tests consume (cache-riding)."""
    return [
        (tuple(shape), dtype)
        for shape, dtype in entry_artifacts(name, bucket)["out_avals"]
    ]


# ---------------------------------------------------------------------------
# rules (each takes the pre-flattened eqn list — the big graphs are 100k+
# equations, walk once per trace, not once per rule)
# ---------------------------------------------------------------------------


def narrow_mixed_concats(eqns: List) -> List[tuple]:
    """Concatenate eqns that mix operand extents along the concat dim while
    every tiled non-concat dim (the trailing two, Mosaic's vreg tile) is
    below (8, 128) — the shape class Mosaic cannot retile."""
    bad = []
    for eqn in eqns:
        if eqn.primitive.name != "concatenate":
            continue
        d = eqn.params["dimension"]
        shapes = [v.aval.shape for v in eqn.invars]
        extents = {s[d] for s in shapes}
        if len(extents) == 1:
            continue  # uniform splice, retileable
        rank = len(shapes[0])
        tiled = [(ax, tile) for ax, tile in ((rank - 2, 8), (rank - 1, 128))
                 if 0 <= ax != d]
        if tiled and all(
            s[ax] < tile for s in shapes for ax, tile in tiled
        ):
            bad.append((d, shapes))
    return bad


def _check_concat(name: str, bucket: int, art: dict) -> List[Violation]:
    return [
        Violation(
            "jaxpr-narrow-mixed-concat", f"{name}@{bucket}", 0,
            f"mixed-width concatenate on dim {d} with sub-tile adjacent "
            f"dims {shapes} — Mosaic cannot retile this (BENCH_r05 class); "
            f"route the splice through fused_core.aligned_splice",
        )
        for d, shapes in art["mixed_concats"]
    ]


def _check_wide_dtypes(name: str, bucket: int, art: dict) -> List[Violation]:
    return [
        Violation(
            "jaxpr-f64-leak", f"{name}@{bucket}", 0,
            f"{prim} produces {dtype} — the sanctioned limb format is "
            f"f32 digit arrays",
        )
        for prim, dtype in art["wide_dtypes"]
    ]


def _check_callbacks(name: str, bucket: int, art: dict) -> List[Violation]:
    return [
        Violation(
            "jaxpr-host-callback", f"{name}@{bucket}", 0,
            f"host callback primitive {pname} in a hot-path program "
            f"— every callback is a device->host round trip "
            f"serialized into the dispatch",
        )
        for pname in art["callbacks"]
    ]


def _check_mxu_precision(name: str, bucket: int, art: dict) -> List[Violation]:
    """jaxpr-mxu-precision: every dot_general in the audited graph must
    carry the full precision contract (f32 preferred_element_type AND
    precision=HIGHEST).  Absence is a violation even where the default
    would happen to be exact — the contract is explicitness, so the
    exactness argument is local to the call site and a backend/flag change
    can never reintroduce the bf16-operand pass silently."""
    out: List[Violation] = []
    for fname, line, prec_ok, pref_name in art.get("dot_generals", []):
        problems = []
        if not prec_ok:
            problems.append("precision is not HIGHEST on both operands")
        if pref_name != "float32":
            problems.append(
                f"preferred_element_type is {pref_name or 'unset'}, "
                "not float32"
            )
        if problems:
            out.append(
                Violation(
                    "jaxpr-mxu-precision",
                    fname or f"{name}@{bucket}",
                    line,
                    f"{name}@{bucket}: dot_general without the MXU "
                    f"precision contract ({'; '.join(problems)}) — f32 "
                    f"dots may be evaluated through bf16 operands inside "
                    f"fusions, rounding 16-bit digit products; route the "
                    f"contraction through limbs._dot_f32 or "
                    f"fused_core._m_dot",
                )
            )
    return out


def _const_census(closed_jaxpr) -> List[list]:
    """Sorted multiset of [shape, dtype-name] over the trace's constants
    (JSON-native so cached and fresh censuses compare equal)."""
    out = []
    for c in closed_jaxpr.consts:
        shape = getattr(c, "shape", None)
        shape = [int(s) for s in shape] if shape is not None else ["?"]
        dt = getattr(getattr(c, "dtype", None), "name", type(c).__name__)
        out.append([shape, dt])
    return sorted(out)


def _check_cache_keys(
    name: str, buckets: Sequence[int], arts: Dict[int, dict]
) -> List[Violation]:
    out: List[Violation] = []
    for b in buckets:
        for const_repr in arts[b]["rank0_consts"]:
            out.append(
                Violation(
                    "jaxpr-unstable-cache-key", f"{name}@{b}", 0,
                    f"rank-0 constant {const_repr} captured into the trace "
                    f"— a closure-captured Python scalar is invisible "
                    f"to the jit cache key; pass it as an argument or "
                    f"bake it as an np array operand",
                )
            )
    base_b = buckets[0]
    base_census = arts[base_b]["const_census"]
    for b in buckets[1:]:
        census = arts[b]["const_census"]
        if census != base_census:
            out.append(
                Violation(
                    "jaxpr-unstable-cache-key", name, 0,
                    f"constant set differs between buckets {base_b} "
                    f"({len(base_census)} consts) and {b} ({len(census)}) — "
                    f"bucket-dependent constants multiply per-kernel Mosaic "
                    f"compiles (the BLK-grid design exists to avoid this)",
                )
            )
    return out


def check_sharded_rules(name: str, bucket: int, art: dict) -> List[Violation]:
    """The sharded-entry rule set over one artifact: a mesh entry must
    actually map through shard_map, its body must combine across shards,
    and the final exponentiation must follow the combine (once per
    merged batch, never once per shard)."""
    sh = art.get("sharded")
    where = f"{name}@{bucket}"
    if not sh:
        return [
            Violation(
                "jaxpr-sharded-no-collective", where, 0,
                "sharded entry traced to a graph with NO shard_map body — "
                "the mesh wrapper is gone, so the 'sharded' program is a "
                "single-chip program wearing the mesh's ledger key",
            )
        ]
    out: List[Violation] = []
    if not sh["collectives"]:
        out.append(
            Violation(
                "jaxpr-sharded-no-collective", where, 0,
                "shard_map body contains no cross-shard collective "
                f"({'/'.join(_COLLECTIVE_PRIMITIVES)}) — each shard would "
                "verify only its local slice and the mesh verdict would "
                "be one shard's opinion",
            )
        )
    if sh["final_exp_scans_before_combine"]:
        out.append(
            Violation(
                "jaxpr-sharded-local-final-exp", where, 0,
                f"{sh['final_exp_scans_before_combine']} final-exp pow-x "
                f"scan(s) run BEFORE the body's first collective — the "
                f"final exponentiation must run once on the combined "
                f"product, not once per shard (the serial scan the "
                f"split/sharded design pays exactly once per batch)",
            )
        )
    if sh["final_exp_scans"] > FINAL_EXP_POW_SCANS:
        out.append(
            Violation(
                "jaxpr-sharded-local-final-exp", where, 0,
                f"{sh['final_exp_scans']} final-exp pow-x scans in the "
                f"mapped body (one final exponentiation contributes "
                f"{FINAL_EXP_POW_SCANS}) — final-exp is running more than "
                f"once per merged batch",
            )
        )
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def audit_entry(
    name: str, buckets: Sequence[int] = AUDIT_BUCKETS, use_cache: bool = True
) -> List[Violation]:
    """All IR rules for one entry point at every bucket in ``buckets``."""
    meta = _entry_meta(name)
    arts = {b: entry_artifacts(name, b, use_cache) for b in buckets}
    out: List[Violation] = []
    for b in buckets:
        if meta["mosaic"]:
            out.extend(_check_concat(name, b, arts[b]))
        out.extend(_check_wide_dtypes(name, b, arts[b]))
        out.extend(_check_callbacks(name, b, arts[b]))
        out.extend(_check_mxu_precision(name, b, arts[b]))
        from . import pallas_audit

        out.extend(pallas_audit.check_pallas_records(
            f"{name}@{b}", arts[b].get("pallas")))
        if meta.get("sharded"):
            out.extend(check_sharded_rules(name, b, arts[b]))
    out.extend(_check_cache_keys(name, buckets, arts))
    return out


def audit_all(
    buckets: Sequence[int] = AUDIT_BUCKETS,
    entries: Iterable[str] = None,
    use_cache: bool = True,
    include_sharded: bool = True,
) -> List[Violation]:
    names = list(entries) if entries is not None else list(entry_points())
    out: List[Violation] = []
    for name in names:
        out.extend(audit_entry(name, buckets, use_cache))
    # the mesh entries audit at their own (global-bucket, mesh) shape —
    # the caller's single-chip bucket pair does not apply to them
    if include_sharded and entries is None and sharded_audit_available():
        for name in sharded_entry_points():
            out.extend(audit_entry(name, SHARDED_AUDIT_BUCKETS, use_cache))
    return out
