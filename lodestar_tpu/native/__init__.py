"""Native (C) runtime components.

Reference parity: the reference's runtime leans on native deps for its
host hot paths (as-sha256/hashtree for SSZ merkleization, snappy, blst —
SURVEY.md §2.9).  The TPU framework keeps device compute in XLA and puts
the host-side hot loops in small C libraries built on demand with the
system compiler and bound via ctypes (no pybind11 in this image).
"""

from .hashtree import hash_layer, have_native  # noqa: F401
