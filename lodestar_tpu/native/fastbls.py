"""ctypes binding for csrc/fastbls.c (native BLS12-381) with
build-on-demand and self-test gating.

Three roles (see fastbls.c header):
- honest CPU baseline for bench.py (portable-C blst counterpart),
- host-side final exponentiation for the split TPU dispatch,
- fast CPU fallback verifier (FastBlsVerifier in crypto/bls/native_verifier).

Mirrors native/hashtree.py: compile once into build/, atomic rename so
concurrent importers never dlopen a half-written .so, fb_selftest() must
pass before the lib is trusted, and every caller has a pure-Python oracle
fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_LOCK = threading.Lock()

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
    "fastbls.c",
)
_SO = os.path.abspath(os.path.join(os.path.dirname(_SRC), "..", "build", "libfastbls.so"))


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            hdr = os.path.join(os.path.dirname(_SRC), "fastbls_consts.h")
            newest_src = max(os.path.getmtime(_SRC), os.path.getmtime(hdr))
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < newest_src:
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                tmp = f"{_SO}.{os.getpid()}.tmp"
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
            lib.fb_selftest.restype = ctypes.c_int
            lib.fb_batch_verify.restype = ctypes.c_int
            lib.fb_batch_verify.argtypes = [
                ctypes.c_size_t,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.fb_verify_one.restype = ctypes.c_int
            lib.fb_verify_one.argtypes = [ctypes.c_char_p] * 3
            lib.fb_final_exp_is_one.restype = ctypes.c_int
            lib.fb_final_exp_is_one.argtypes = [ctypes.c_char_p]
            lib.fb_hash_to_g2.restype = ctypes.c_int
            lib.fb_hash_to_g2.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
            lib.fb_sign.restype = ctypes.c_int
            lib.fb_sign.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.fb_sign_ct.restype = ctypes.c_int
            lib.fb_sign_ct.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.fb_sk_to_pk.restype = ctypes.c_int
            lib.fb_sk_to_pk.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            lib.fb_sign_aggregate.restype = ctypes.c_int
            lib.fb_sign_aggregate.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.fb_aggregate_sigs.restype = ctypes.c_int
            lib.fb_aggregate_sigs.argtypes = [
                ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
            ]
            lib.fb_aggregate_pubkeys_c.restype = ctypes.c_int
            lib.fb_aggregate_pubkeys_c.argtypes = [
                ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
            ]
            if lib.fb_selftest() != 1:
                return None
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def have_native() -> bool:
    return _load() is not None


def batch_verify(
    sets: Sequence[Tuple[List[bytes], bytes, bytes]], coeffs: Sequence[int]
) -> Optional[bool]:
    """sets: (pubkeys_compressed[], signing_root32, signature_compressed96).
    coeffs: odd 64-bit RLC coefficients, one per set.  Returns None when the
    native lib is unavailable (caller falls back to the oracle); False on
    malformed inputs or failed verification."""
    lib = _load()
    if lib is None:
        return None
    n = len(sets)
    if n == 0:
        return False
    pk_blob = b"".join(pk for pks, _, _ in sets for pk in pks)
    counts = (ctypes.c_uint32 * n)(*[len(pks) for pks, _, _ in sets])
    msgs = b"".join(m for _, m, _ in sets)
    sigs = b"".join(s for _, _, s in sets)
    if len(msgs) != 32 * n or len(sigs) != 96 * n:
        return False
    c_arr = (ctypes.c_uint64 * n)(*[c & 0xFFFFFFFFFFFFFFFF for c in coeffs])
    return lib.fb_batch_verify(n, pk_blob, counts, msgs, sigs, c_arr) == 1


def final_exp_is_one(f_bytes: bytes) -> Optional[bool]:
    """Host tail of the split TPU dispatch: f_bytes = 12 x 48-byte BE fp
    components in tower order (fastbls.c fb_final_exp_is_one)."""
    lib = _load()
    if lib is None:
        return None
    if len(f_bytes) != 576:
        return False
    return lib.fb_final_exp_is_one(f_bytes) == 1


def sign(sk32: bytes, msg: bytes) -> Optional[bytes]:
    """sk * H(msg) as a compressed 96-byte G2 signature — VARIABLE TIME
    (fb_sign, sliding double-and-add: the branch pattern encodes the
    secret key).  Dev/interop fixtures only; production signing uses
    ``sign_ct``.  None without the native lib or for an invalid scalar."""
    lib = _load()
    if lib is None or len(sk32) != 32:
        return None
    out = ctypes.create_string_buffer(96)
    if lib.fb_sign(out, sk32, msg, len(msg)) != 1:
        return None
    return out.raw


def sign_ct(sk32: bytes, msg: bytes) -> Optional[bytes]:
    """Constant-time-safe signing (fb_sign_ct): identical bytes to
    ``sign`` via a fixed-length double-and-always-add ladder — uniform
    operation sequence regardless of the key, ~2x the variable-time
    cost (measured; every bit pays the add).  The ValidatorStore default.  None without the native lib or
    for an invalid scalar."""
    lib = _load()
    if lib is None or len(sk32) != 32:
        return None
    out = ctypes.create_string_buffer(96)
    if lib.fb_sign_ct(out, sk32, msg, len(msg)) != 1:
        return None
    return out.raw


def sign_aggregate(sks: Sequence[bytes], msg: bytes) -> Optional[bytes]:
    """One aggregate signature by n secret keys over one message — equals
    aggregating n individual signatures but pays one hash + one scalar mult
    (fb_sign_aggregate)."""
    lib = _load()
    if lib is None or not sks:
        return None
    blob = b"".join(sks)
    if len(blob) != 32 * len(sks):
        return None
    out = ctypes.create_string_buffer(96)
    if lib.fb_sign_aggregate(out, blob, len(sks), msg, len(msg)) != 1:
        return None
    return out.raw


def sk_to_pk(sk32: bytes) -> Optional[bytes]:
    """sk * g1 as a compressed 48-byte pubkey (fb_sk_to_pk)."""
    lib = _load()
    if lib is None or len(sk32) != 32:
        return None
    out = ctypes.create_string_buffer(48)
    if lib.fb_sk_to_pk(out, sk32) != 1:
        return None
    return out.raw


def aggregate_sigs(sigs: Sequence[bytes]) -> Optional[bytes]:
    """Sum of compressed signatures, compressed out (fb_aggregate_sigs)."""
    lib = _load()
    if lib is None:
        return None
    blob = b"".join(sigs)
    if len(blob) != 96 * len(sigs):
        return None
    out = ctypes.create_string_buffer(96)
    if lib.fb_aggregate_sigs(len(sigs), blob, out) != 1:
        return None
    return out.raw


def aggregate_pks(pks: Sequence[bytes]) -> Optional[bytes]:
    """Sum of compressed pubkeys, compressed out (fb_aggregate_pubkeys_c)."""
    lib = _load()
    if lib is None:
        return None
    blob = b"".join(pks)
    if len(blob) != 48 * len(pks):
        return None
    out = ctypes.create_string_buffer(48)
    if lib.fb_aggregate_pubkeys_c(len(pks), blob, out) != 1:
        return None
    return out.raw


def hash_to_g2_affine(msg: bytes) -> Optional[Tuple[int, int, int, int]]:
    """(x.c0, x.c1, y.c0, y.c1) ints, or None without the native lib."""
    lib = _load()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(192)
    if lib.fb_hash_to_g2(out, msg, len(msg)) != 1:
        return None
    raw = out.raw
    return tuple(int.from_bytes(raw[48 * i : 48 * (i + 1)], "big") for i in range(4))
