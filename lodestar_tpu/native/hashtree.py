"""ctypes binding for csrc/hashtree.c with build-on-demand + fallback.

The shared library is compiled once into the repo's build/ directory with
the system compiler; every call after that is one FFI hop per merkle
LAYER (not per pair).  If no compiler is available the module falls back
to hashlib transparently — callers never notice beyond speed.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_LOCK = threading.Lock()

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc", "hashtree.c")
_BUILD_DIR = os.path.join(os.path.dirname(_SRC), "..", "build")
_SO = os.path.abspath(os.path.join(_BUILD_DIR, "libhashtree.so"))


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
                    check=True, capture_output=True, timeout=60,
                )
            lib = ctypes.CDLL(_SO)
            lib.hashtree_hash_layer.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
            ]
            lib.hashtree_sha256.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
            ]
            # self-check against hashlib before trusting it
            probe = bytes(range(64))
            out = ctypes.create_string_buffer(32)
            lib.hashtree_hash_layer(probe, 1, out)
            if out.raw != hashlib.sha256(probe).digest():
                return None
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def have_native() -> bool:
    return _load() is not None


def hash_layer(data: bytes) -> bytes:
    """Hash consecutive 64-byte blocks into 32-byte digests (one merkle
    layer step)."""
    lib = _load()
    n = len(data) // 64
    if lib is None:
        out = bytearray(n * 32)
        for i in range(0, len(data), 64):
            out[i // 2 : i // 2 + 32] = hashlib.sha256(data[i : i + 64]).digest()
        return bytes(out)
    buf = ctypes.create_string_buffer(n * 32)
    lib.hashtree_hash_layer(data, n, buf)
    return buf.raw


def sha256(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return hashlib.sha256(data).digest()
    out = ctypes.create_string_buffer(32)
    lib.hashtree_sha256(data, len(data), out)
    return out.raw
