"""ctypes binding for csrc/hashtree.c with build-on-demand + fallback.

The shared library is compiled once into the repo's build/ directory with
the system compiler; every call after that is one FFI hop per merkle
LAYER (not per pair).  If no compiler is available the module falls back
to hashlib transparently — callers never notice beyond speed.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_LOCK = threading.Lock()

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc", "hashtree.c")
_BUILD_DIR = os.path.join(os.path.dirname(_SRC), "..", "build")
_SO = os.path.abspath(os.path.join(_BUILD_DIR, "libhashtree.so"))


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                # build to a process-unique temp path and rename into place:
                # concurrent importers must never dlopen a half-written .so
                tmp = f"{_SO}.{os.getpid()}.tmp"
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    check=True, capture_output=True, timeout=60,
                )
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
            lib.hashtree_hash_layer.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
            ]
            lib.hashtree_sha256.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
            ]
            # self-check against hashlib before trusting it
            probe = bytes(range(64))
            out = ctypes.create_string_buffer(32)
            lib.hashtree_hash_layer(probe, 1, out)
            if out.raw != hashlib.sha256(probe).digest():
                return None
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def have_native() -> bool:
    return _load() is not None


def hash_layer(data: bytes) -> bytes:
    """Hash consecutive 64-byte blocks into 32-byte digests (one merkle
    layer step).  Callers gate on have_native(); without the lib this
    falls back to the caller's own hashlib path via ssz.core."""
    lib = _load()
    if lib is None:  # pragma: no cover - callers check have_native() first
        from ..ssz.core import _hashlib_hash_layer

        return _hashlib_hash_layer(data)
    n = len(data) // 64
    buf = ctypes.create_string_buffer(n * 32)
    lib.hashtree_hash_layer(data, n, buf)
    return buf.raw
