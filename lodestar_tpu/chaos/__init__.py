"""Chaos engineering: deterministic fault injection + the campaign that
proves every induced failure is diagnosable AND self-healing
(docs/chaos.md; ROADMAP item 5's fault-injection half).

Public surface:

- ``CHAOS``            process-wide controller; ``CHAOS.armed`` is the
                       constant-time disarmed gate every seam reads
- ``FaultPlan`` / ``FaultSpec``   the seeded, deterministic plan
- ``install_from_env`` spawn-child activation (``LODESTAR_TPU_CHAOS_PLAN``)
- ``corrupt_file``     deterministic byte-flipper for cache-corruption runs
- ``DeviceLostError`` / ``InjectedCompileError`` / ``InjectedIOError`` /
  ``FaultInjected``    the typed injected failures

``tools/chaos_campaign.py`` drives the full campaign; ``bench.py``'s
``chaos`` stage publishes ``time_to_quarantine_s`` / ``time_to_recover_s``
/ ``verdicts_lost``.
"""

from .plan import (  # noqa: F401
    CHAOS,
    KNOWN_SEAMS,
    PLAN_ENV,
    ChaosController,
    DeviceLostError,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    InjectedCompileError,
    InjectedIOError,
    corrupt_file,
    install_from_env,
)
