"""Deterministic fault injection: the plan, the controller, the seams.

The chaos plane is the *active* half of the robustness stack: PR 5's
forensics can diagnose a failure and PR 6's overload policy survives too
much load, but nothing before this package could *induce* the failures
the north star's traffic levels will eventually deliver for free — a
wedged chip, a lost device mid-flight, a Mosaic compile that starts
failing after a driver update, a corrupted persistent-cache entry, a
bench child killed mid-stage, a full disk under the bundle writer.

Design constraints, in order (mirroring ``tracing.SpanTracer``):

1. **Zero overhead disarmed.**  Every seam site gates on the single
   attribute read ``CHAOS.armed`` (a plain bool, False unless a plan is
   installed) before building any context or touching any lock.  A
   production node that never arms a plan pays one attribute read per
   seam crossing — nothing else.
2. **Deterministic.**  A ``FaultPlan`` is (seed, fault specs); whether a
   given seam crossing fires is a pure function of the seed, the spec's
   ``after``/``count`` window, and the (deterministic) crossing order —
   so a campaign failure reproduces from its seed alone.  No wall clock,
   no global RNG.
3. **Every injection leaves evidence.**  Each fired fault lands in the
   forensics journal (``chaos.inject``: seed, seam, context) and in the
   controller's ``injected`` log, which rides into every diagnostic
   bundle — the campaign's "zero undiagnosable deaths" guarantee starts
   with the injector itself confessing.

Seams (each named site asks the controller at the moment the real
failure would occur; docs/chaos.md carries the full taxonomy):

========================  ===================================================
``bls.compile``           raised inside ``TpuBlsVerifier.warmup()`` /
                          ``dispatch()`` where the program call happens —
                          models a Mosaic/XLA compile failure; drives the
                          fused→XLA→native degradation ladder
``device.loss``           ``PendingVerdict`` sync raises ``DeviceLostError``
                          — models a chip dropping out mid-flight; drives
                          requeue + quarantine
``device.wedge``          ``PendingVerdict`` sync blocks ``wedge_s`` seconds
                          (the watchdog window) and THEN raises — models a
                          hung device tunnel; drives watchdog + requeue
``cache.corrupt``         no hook: ``corrupt_file`` deterministically
                          flips bytes in a persistent-cache / ledger /
                          AOT-store file (the campaign applies it between
                          processes)
``aot.midwrite``          ``maybe_kill`` inside ``aot/store.save`` between
                          the temp-file write and the rename — models a
                          prewarmer dying mid-write; the loader must
                          ignore the orphan and the manifest stays
                          consistent (manifest-written-last)
``bench.kill``            ``maybe_kill`` SIGKILLs the calling process —
                          models the rc=124 stage-child death; drives
                          salvage-heartbeat bundle recovery
``forensics.io``          raised inside ``forensics/bundle.write_bundle``
                          section producers — models a full/broken scratch
                          disk under the bundle writer itself
========================  ===================================================

This module imports nothing from the rest of the package at module
scope (journal access is lazy, at fire time) so low-level modules —
``forensics/bundle`` included — can import ``CHAOS`` without cycles.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import threading
from typing import Any, Dict, List, Optional

#: env var carrying a JSON FaultPlan into spawn children (bench stages,
#: the campaign's kill child) — see install_from_env()
PLAN_ENV = "LODESTAR_TPU_CHAOS_PLAN"

KNOWN_SEAMS = (
    "bls.compile",
    "device.loss",
    "device.wedge",
    "cache.corrupt",
    "aot.midwrite",
    "bench.kill",
    "forensics.io",
)


class FaultInjected(Exception):
    """Base class of every injected failure — a campaign assertion can
    tell an induced fault from an organic bug by type."""


class DeviceLostError(FaultInjected):
    """The device behind an in-flight batch is gone (injected analog of a
    chip dropping its tunnel: ``result()`` raises instead of returning)."""


class InjectedCompileError(FaultInjected):
    """A compile/program-call failure injected at the ``bls.compile`` seam."""


class InjectedIOError(FaultInjected, OSError):
    """An IO failure injected at the ``forensics.io`` seam (an OSError so
    the bundle writer's per-section isolation sees its usual class)."""


@dataclasses.dataclass
class FaultSpec:
    """One fault: fire at ``seam`` on crossings matching ``match``,
    skipping the first ``after`` matches, then firing on the next
    ``count`` (0 = every match from then on).

    ``match`` compares context keys by equality (e.g. ``{"device":
    "cpu:1", "fused": True}``); keys absent from the crossing context
    never match.  ``probability`` < 1 draws from the plan's seeded RNG —
    still deterministic for a fixed seed and crossing order."""

    seam: str
    match: Optional[Dict[str, Any]] = None
    after: int = 0
    count: int = 1
    probability: float = 1.0
    wedge_s: float = 0.0
    error: str = ""
    # runtime state (not part of the plan identity)
    seen: int = 0
    fired: int = 0

    def matches(self, ctx: Dict[str, Any]) -> bool:
        if not self.match:
            return True
        return all(ctx.get(k) == v for k, v in self.match.items())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seam": self.seam, "match": self.match, "after": self.after,
            "count": self.count, "probability": self.probability,
            "wedge_s": self.wedge_s, "error": self.error,
        }


class FaultPlan:
    """A seeded list of fault specs — the unit a campaign installs."""

    def __init__(self, seed: int = 0, faults: Optional[List[FaultSpec]] = None):
        self.seed = int(seed)
        self.faults: List[FaultSpec] = list(faults or [])
        self._rng = random.Random(self.seed)

    def add(self, seam: str, **kw: Any) -> "FaultPlan":
        self.faults.append(FaultSpec(seam=seam, **kw))
        return self

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "faults": [f.to_dict() for f in self.faults]}
        )

    @classmethod
    def from_json(cls, blob: str) -> "FaultPlan":
        doc = json.loads(blob)
        if not isinstance(doc, dict):
            # valid JSON that is not a plan object (e.g. a bare faults
            # list) must fail as a *bad plan*, not an AttributeError that
            # bypasses install_from_env's evidence trail
            raise ValueError(f"fault plan must be a JSON object, got {type(doc).__name__}")
        return cls(
            seed=doc.get("seed", 0),
            faults=[FaultSpec(**f) for f in doc.get("faults", [])],
        )


class ChaosController:
    """Process-wide injection point.  ``armed`` is the constant-time
    disarmed gate every seam site reads first; all other state is only
    touched once a plan is installed."""

    def __init__(self):
        self.armed = False  # the ONLY attribute the disarmed hot path reads
        self._lock = threading.Lock()
        self._plan: Optional[FaultPlan] = None
        #: fired-fault log (newest last) — bundles and inspect_bundle's
        #: chaos triage section read this
        self.injected: List[Dict[str, Any]] = []

    # -- arming ---------------------------------------------------------------

    def install(self, plan: FaultPlan) -> "ChaosController":
        with self._lock:
            self._plan = plan
            self.injected = []
            self.armed = True
        self._journal(
            "chaos.install", level="WARNING", seed=plan.seed,
            seams=sorted({f.seam for f in plan.faults}),
            faults=len(plan.faults),
        )
        return self

    def disarm(self) -> None:
        with self._lock:
            self.armed = False
            self._plan = None
        self._journal("chaos.disarm")

    # -- the seam API ---------------------------------------------------------

    def fire(self, seam: str, **ctx: Any) -> Optional[FaultSpec]:
        """One seam crossing: returns the matching FaultSpec when the
        plan says this crossing fails, else None.  Callers gate on
        ``CHAOS.armed`` first; this method re-checks under the lock so a
        concurrent disarm is safe."""
        with self._lock:
            plan = self._plan
            if not self.armed or plan is None:
                return None
            for spec in plan.faults:
                if spec.seam != seam or not spec.matches(ctx):
                    continue
                spec.seen += 1
                if spec.seen <= spec.after:
                    continue
                if spec.count and spec.fired >= spec.count:
                    continue
                if spec.probability < 1.0 and plan._rng.random() >= spec.probability:
                    continue
                spec.fired += 1
                record = {
                    "seam": seam, "seed": plan.seed, "ctx": dict(ctx),
                    "fired": spec.fired,
                }
                self.injected.append(record)
                break
            else:
                return None
        # journal outside the lock (the journal has its own)
        self._journal("chaos.inject", level="WARNING", seam=seam,
                      seed=plan.seed, **ctx)
        return spec

    def maybe_raise(self, seam: str, **ctx: Any) -> None:
        """Raise the seam's injected exception type when the plan fires."""
        spec = self.fire(seam, **ctx)
        if spec is None:
            return
        msg = spec.error or f"injected fault at {seam} (seed {self._seed()})"
        if seam == "forensics.io":
            raise InjectedIOError(msg)
        if seam == "bls.compile":
            raise InjectedCompileError(msg)
        raise FaultInjected(msg)

    def maybe_kill(self, seam: str = "bench.kill", **ctx: Any) -> None:
        """SIGKILL the calling process when the plan fires (the bench
        stage-child death class — nothing downstream of this returns)."""
        if self.fire(seam, **ctx) is not None:
            os.kill(os.getpid(), signal.SIGKILL)

    # -- introspection --------------------------------------------------------

    def _seed(self) -> Optional[int]:
        plan = self._plan
        return plan.seed if plan is not None else None

    def state(self) -> Dict[str, Any]:
        """Snapshot for diagnostic bundles (forensics/bundle)."""
        with self._lock:
            plan = self._plan
            return {
                "armed": self.armed,
                "seed": plan.seed if plan else None,
                "faults": [
                    dict(f.to_dict(), seen=f.seen, fired=f.fired)
                    for f in plan.faults
                ] if plan else [],
                "injected": [dict(r) for r in self.injected],
            }

    def _journal(self, kind: str, **fields: Any) -> None:
        # lazy: keeps this module import-cycle-free (bundle.py imports us)
        try:
            from ..forensics.journal import JOURNAL

            JOURNAL.record(kind, **fields)
        except Exception:
            pass  # evidence is best-effort; injection must still work


#: process-wide singleton every seam site reads
CHAOS = ChaosController()


def install_from_env(env: Optional[Dict[str, str]] = None) -> bool:
    """Arm CHAOS from the ``LODESTAR_TPU_CHAOS_PLAN`` JSON env var (the
    spawn-child activation path: bench stage children and the campaign's
    kill child call this first).  Returns True when a plan was armed."""
    blob = (env or os.environ).get(PLAN_ENV)
    if not blob:
        return False
    try:
        CHAOS.install(FaultPlan.from_json(blob))
        return True
    except Exception as e:  # noqa: BLE001 — ANY malformed plan must leave
        # evidence rather than silently never arming (the whole point of
        # the injector is that nothing about it is invisible)
        CHAOS._journal("chaos.bad_plan", level="ERROR", error=str(e)[:200])
        return False


def corrupt_file(path: str, seed: int = 0, flips: int = 16) -> List[int]:
    """Deterministically flip ``flips`` bytes of ``path`` in place (the
    ``cache.corrupt`` seam: persistent-cache / ledger entries don't have
    an in-process hook — real corruption happens to the file between
    processes).  Returns the flipped offsets so a campaign can log them."""
    rng = random.Random(seed)
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        if not data:
            data = bytearray(b"\x00")
        # sample WITHOUT replacement: a duplicate offset would XOR the
        # same byte twice and cancel, making the "corruption" a no-op
        offsets = sorted(rng.sample(range(len(data)), min(flips, len(data))))
        for off in offsets:
            data[off] ^= 0xFF
        f.seek(0)
        f.write(bytes(data))
        f.truncate()
    return offsets
