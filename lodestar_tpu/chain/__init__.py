"""Chain orchestration layer: verifier pool, block pipeline, clock, caches.

Reference: packages/beacon-node/src/chain (SURVEY §2.4).
"""

from .bls_pool import BlsBatchPool  # noqa: F401
from .clock import LocalClock  # noqa: F401
from .emitter import ChainEvent, ChainEventEmitter  # noqa: F401
