"""Light-client server: bootstrap + best-update production per sync period.

Reference: packages/beacon-node/src/chain/lightClient/index.ts:151
(LightClientServer: onImportBlockHead tracks attested/finalized data and
keeps the best LightClientUpdate per sync-committee period, served over
the API; getBootstrap serves header + current committee + proof).

Shape here: the server subscribes to block imports; every altair block
whose sync_aggregate attests its parent yields a candidate update for the
parent's period, scored by participation (isBetterUpdate reduced to the
participation ordering, which dominates in practice).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..params import Preset
from ..ssz import Fields
from ..state_transition import compute_epoch_at_slot
from ..types import get_types
from ..utils.logger import get_logger

logger = get_logger("light-client-server")


def sync_period_at_slot(p: Preset, slot: int) -> int:
    return compute_epoch_at_slot(p, slot) // p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD


def block_to_header(p: Preset, block, body_root: Optional[bytes] = None) -> Fields:
    from ..state_transition.upgrade import block_types

    t = block_types(p, block)
    return Fields(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body_root=body_root or t.BeaconBlockBody.hash_tree_root(block.body),
    )


class LightClientServer:
    def __init__(self, preset: Preset, chain):
        self.p = preset
        self.chain = chain
        self.t = get_types(preset)
        self.best_update_by_period: Dict[int, object] = {}
        # latest head/finality updates (reference lightClient/index.ts:198
        # onImportBlockHead keeps latestHeadUpdate + finalized variant and
        # emits lightClientOptimisticUpdate / lightClientFinalityUpdate)
        self.latest_optimistic_update = None
        self.latest_finality_update = None
        from .emitter import ChainEvent

        chain.emitter.on(ChainEvent.BLOCK, self._on_block)

    # -- bootstrap (getBootstrap) ---------------------------------------------

    def get_bootstrap(self, block_root: bytes):
        """Header + current sync committee + proof for a trusted root."""
        from ..state_transition.upgrade import state_types

        block = self.chain.get_block_by_root(block_root)
        state = self.chain.get_state_by_block_root(block_root)
        if block is None or state is None:
            return None
        st = state_types(self.p, state).BeaconState
        committee_root, branch = st.get_field_proof(state, "current_sync_committee")
        return Fields(
            header=block_to_header(self.p, block.message),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=[bytes(b) for b in branch],
        )

    # -- update production (onImportBlock) ------------------------------------

    def _on_block(self, signed_block, block_root: bytes) -> None:
        block = signed_block.message
        body = block.body
        if "sync_aggregate" not in body.keys():
            return
        agg = body.sync_aggregate
        participation = sum(agg.sync_committee_bits)
        if participation == 0:
            return
        attested_root = bytes(block.parent_root)
        attested_block = self.chain.get_block_by_root(attested_root)
        attested_state = self.chain.get_state_by_block_root(attested_root)
        if attested_block is None or attested_state is None:
            return
        self._track_head_updates(block, attested_block, attested_state, agg)
        period = sync_period_at_slot(self.p, attested_block.message.slot)
        # "relevant": signed within the attested header's own period, so a
        # store whose next committee is still unknown can verify it (spec
        # is_better_update's sync-committee-relevance criterion) — an update
        # attesting the LAST slot of a period is signed by the NEXT period's
        # committee and must lose to any same-period-signed candidate
        new_rel = sync_period_at_slot(self.p, block.slot) == period
        cur = self.best_update_by_period.get(period)
        if cur is not None:
            cur_rel = sync_period_at_slot(self.p, cur.signature_slot) == period
            if cur_rel and not new_rel:
                return
            cur_part = sum(cur.sync_aggregate.sync_committee_bits)
            # same relevance class: more participation wins; on a tie
            # prefer the newer attested header (fresher finality info)
            if cur_rel == new_rel and (
                cur_part > participation
                or (
                    cur_part == participation
                    and cur.attested_header.slot >= attested_block.message.slot
                )
            ):
                return
        update = self._build_update(attested_block, attested_state, agg,
                                    signature_slot=block.slot)
        if update is not None:
            self.best_update_by_period[period] = update

    def _track_head_updates(self, block, attested_block, attested_state, agg) -> None:
        """Maintain latest optimistic + finality updates and emit events
        (reference lightClient/index.ts:198 onImportBlockHead; routes
        lightclient.ts:60 getLightClientOptimisticUpdate /
        getLightClientFinalityUpdate)."""
        from .emitter import ChainEvent

        attested_slot = attested_block.message.slot
        participation = sum(agg.sync_committee_bits)
        cur = self.latest_optimistic_update
        # newer attested header wins; same header needs more participation
        if cur is None or attested_slot > cur.attested_header.slot or (
            attested_slot == cur.attested_header.slot
            and participation > sum(cur.sync_aggregate.sync_committee_bits)
        ):
            ou = Fields(
                attested_header=block_to_header(self.p, attested_block.message),
                sync_aggregate=agg,
                signature_slot=block.slot,
            )
            self.latest_optimistic_update = ou
            self.chain.emitter.emit(ChainEvent.LIGHT_CLIENT_OPTIMISTIC_UPDATE, ou)

        fin_cp = attested_state.finalized_checkpoint
        if bytes(fin_cp.root) == b"\x00" * 32:
            return
        fin_block = self.chain.get_block_by_root(bytes(fin_cp.root))
        if fin_block is None:
            return
        cur = self.latest_finality_update
        if cur is not None and not (
            attested_slot > cur.attested_header.slot or (
                attested_slot == cur.attested_header.slot
                and participation > sum(cur.sync_aggregate.sync_committee_bits)
            )
        ):
            return
        from ..state_transition.upgrade import state_types
        from ..ssz import uint64 as u64t

        st = state_types(self.p, attested_state).BeaconState
        _, state_branch = st.get_field_proof(attested_state, "finalized_checkpoint")
        finality_branch = [u64t.hash_tree_root(fin_cp.epoch)] + [
            bytes(b) for b in state_branch
        ]
        fu = Fields(
            attested_header=block_to_header(self.p, attested_block.message),
            finalized_header=block_to_header(self.p, fin_block.message),
            finality_branch=finality_branch,
            sync_aggregate=agg,
            signature_slot=block.slot,
        )
        self.latest_finality_update = fu
        self.chain.emitter.emit(ChainEvent.LIGHT_CLIENT_FINALITY_UPDATE, fu)

    def _build_update(self, attested_block, attested_state, sync_aggregate,
                      signature_slot: int = 0):
        from ..state_transition.upgrade import state_types

        st = state_types(self.p, attested_state).BeaconState
        try:
            _, nsc_branch = st.get_field_proof(attested_state, "next_sync_committee")
        except StopIteration:
            return None  # pre-altair attested state: no update possible
        fin_cp = attested_state.finalized_checkpoint
        finalized_header = None
        if bytes(fin_cp.root) != b"\x00" * 32:
            fin_block = self.chain.get_block_by_root(bytes(fin_cp.root))
            if fin_block is not None:
                finalized_header = block_to_header(self.p, fin_block.message)
        # finality branch: checkpoint root within Checkpoint (epoch sibling)
        # then finalized_checkpoint within the state
        _, state_branch = st.get_field_proof(attested_state, "finalized_checkpoint")
        t0 = self.t.phase0
        epoch_leaf = t0.Epoch.hash_tree_root(fin_cp.epoch) if hasattr(t0, "Epoch") else None
        from ..ssz import uint64 as u64t

        epoch_leaf = u64t.hash_tree_root(fin_cp.epoch)
        finality_branch = [epoch_leaf] + [bytes(b) for b in state_branch]
        empty_header = Fields(
            slot=0, proposer_index=0, parent_root=b"\x00" * 32,
            state_root=b"\x00" * 32, body_root=b"\x00" * 32,
        )
        return Fields(
            attested_header=block_to_header(self.p, attested_block.message),
            next_sync_committee=attested_state.next_sync_committee,
            next_sync_committee_branch=[bytes(b) for b in nsc_branch],
            finalized_header=finalized_header or empty_header,
            finality_branch=finality_branch,
            sync_aggregate=sync_aggregate,
            signature_slot=signature_slot or (attested_block.message.slot + 1),
            fork_version=bytes(attested_state.fork.current_version),
        )

    def get_update(self, period: int):
        return self.best_update_by_period.get(period)

    def get_latest_update(self):
        if not self.best_update_by_period:
            return None
        return self.best_update_by_period[max(self.best_update_by_period)]

    def get_finality_update(self):
        return self.latest_finality_update

    def get_optimistic_update(self):
        return self.latest_optimistic_update
