"""Light-client server: bootstrap + best-update production per sync period.

Reference: packages/beacon-node/src/chain/lightClient/index.ts:151
(LightClientServer: onImportBlockHead tracks attested/finalized data and
keeps the best LightClientUpdate per sync-committee period, served over
the API; getBootstrap serves header + current committee + proof).

Shape here: the server subscribes to block imports; every altair block
whose sync_aggregate attests its parent yields a candidate update for the
parent's period, scored by participation (isBetterUpdate reduced to the
participation ordering, which dominates in practice).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..params import Preset
from ..ssz import Fields
from ..state_transition import compute_epoch_at_slot
from ..types import get_types
from ..utils.logger import get_logger

logger = get_logger("light-client-server")


def sync_period_at_slot(p: Preset, slot: int) -> int:
    return compute_epoch_at_slot(p, slot) // p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD


def block_to_header(p: Preset, block, body_root: Optional[bytes] = None) -> Fields:
    from ..state_transition.upgrade import block_types

    t = block_types(p, block)
    return Fields(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body_root=body_root or t.BeaconBlockBody.hash_tree_root(block.body),
    )


class LightClientServer:
    def __init__(self, preset: Preset, chain):
        self.p = preset
        self.chain = chain
        self.t = get_types(preset)
        self.best_update_by_period: Dict[int, object] = {}
        # latest head/finality updates (reference lightClient/index.ts:198
        # onImportBlockHead keeps latestHeadUpdate + finalized variant and
        # emits lightClientOptimisticUpdate / lightClientFinalityUpdate)
        self.latest_optimistic_update = None
        self.latest_finality_update = None
        # parked (attested_block, attested_state, agg, signature_slot) whose
        # finality proof hasn't been materialised yet (see
        # _track_head_updates on why this is lazy)
        self._pending_finality = None
        from .emitter import ChainEvent

        chain.emitter.on(ChainEvent.BLOCK, self._on_block)

    # -- bootstrap (getBootstrap) ---------------------------------------------

    def get_bootstrap(self, block_root: bytes):
        """Header + current sync committee + proof for a trusted root."""
        from ..state_transition.upgrade import state_types

        block = self.chain.get_block_by_root(block_root)
        state = self.chain.get_state_by_block_root(block_root)
        if block is None or state is None:
            return None
        st = state_types(self.p, state).BeaconState
        committee_root, branch = st.get_field_proof(state, "current_sync_committee")
        return Fields(
            header=block_to_header(self.p, block.message),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=[bytes(b) for b in branch],
        )

    # -- update production (onImportBlock) ------------------------------------

    def _on_block(self, signed_block, block_root: bytes) -> None:
        block = signed_block.message
        body = block.body
        if "sync_aggregate" not in body.keys():
            return
        agg = body.sync_aggregate
        participation = sum(agg.sync_committee_bits)
        if participation == 0:
            return
        attested_root = bytes(block.parent_root)
        attested_block = self.chain.get_block_by_root(attested_root)
        attested_state = self.chain.get_state_by_block_root(attested_root)
        if attested_block is None or attested_state is None:
            return
        self._track_head_updates(block, attested_block, attested_state, agg)
        period = sync_period_at_slot(self.p, attested_block.message.slot)
        # spec is_better_update cascade, computed without building the
        # update: supermajority, then participation below it, then
        # relevance ("relevant" = signed within the attested header's own
        # period, so a store whose next committee is still unknown can
        # verify it — an update attesting the LAST slot of a period is
        # signed by the NEXT period's committee), then finality presence,
        # then participation.  Final tie-break deviates from the spec's
        # older-attested preference (a client-side stability heuristic):
        # a SERVER serves the update ladder, and the fresher attested
        # header carries the freshest finalized header — an early-period
        # update's finality can predate a client's bootstrap entirely
        new_rel = sync_period_at_slot(self.p, block.slot) == period
        # finality-bearing only when the finalized BLOCK is present in the
        # store (ADVICE r5): _build_update serves an empty finality_branch
        # when it cannot materialize the finalized header, and an
        # empty-branch candidate must not win the is_better_update cascade
        # on the finality tiebreak
        fin_cp = attested_state.finalized_checkpoint
        new_fin = (
            bytes(fin_cp.root) != b"\x00" * 32
            and self.chain.get_block_by_root(bytes(fin_cp.root)) is not None
        )
        cur = self.best_update_by_period.get(period)
        if cur is not None:
            max_bits = len(agg.sync_committee_bits)
            cur_part = sum(cur.sync_aggregate.sync_committee_bits)
            cur_rel = sync_period_at_slot(self.p, cur.signature_slot) == period
            cur_fin = cur.finalized_header.slot != 0 or (
                bytes(cur.finalized_header.state_root) != b"\x00" * 32
            )
            new_sup = participation * 3 >= max_bits * 2
            cur_sup = cur_part * 3 >= max_bits * 2
            if new_sup != cur_sup:
                better = new_sup
            elif not new_sup and participation != cur_part:
                better = participation > cur_part
            elif new_rel != cur_rel:
                better = new_rel
            elif new_fin != cur_fin:
                better = new_fin
            elif participation != cur_part:
                better = participation > cur_part
            else:
                better = attested_block.message.slot > cur.attested_header.slot
            if not better:
                return
        update = self._build_update(attested_block, attested_state, agg,
                                    signature_slot=block.slot)
        if update is not None:
            self.best_update_by_period[period] = update

    def _finality_proof(self, attested_state):
        """(finalized_header, finality_branch) for an attested state, or
        (None, None) when it has no finality — the ONE implementation of
        the Checkpoint generalized-index layout ([htr(epoch)] + the
        finalized_checkpoint state branch) that both the per-period updates
        and the head finality updates serve, mirrored by the client's
        idx = 1 + 2*field_index('finalized_checkpoint') verification."""
        from ..state_transition.upgrade import state_types
        from ..ssz import uint64 as u64t

        fin_cp = attested_state.finalized_checkpoint
        if bytes(fin_cp.root) == b"\x00" * 32:
            return None, None
        fin_block = self.chain.get_block_by_root(bytes(fin_cp.root))
        if fin_block is None:
            return None, None
        st = state_types(self.p, attested_state).BeaconState
        _, state_branch = st.get_field_proof(attested_state, "finalized_checkpoint")
        finality_branch = [u64t.hash_tree_root(fin_cp.epoch)] + [
            bytes(b) for b in state_branch
        ]
        return block_to_header(self.p, fin_block.message), finality_branch

    def _track_head_updates(self, block, attested_block, attested_state, agg) -> None:
        """Maintain latest optimistic + finality updates and emit events
        (reference lightClient/index.ts:198 onImportBlockHead; routes
        lightclient.ts:60 getLightClientOptimisticUpdate /
        getLightClientFinalityUpdate).

        The finality update's merkle proof costs a partial state
        re-merkleization (~300 ms at 250k validators on a fresh state), so
        it is built LAZILY: the candidate block/state are parked and the
        proof is materialised on first demand (REST route or SSE
        subscriber) — block import never pays for it."""
        from .emitter import ChainEvent

        attested_slot = attested_block.message.slot
        participation = sum(agg.sync_committee_bits)
        cur = self.latest_optimistic_update
        # newer attested header wins; same header needs more participation
        if cur is None or attested_slot > cur.attested_header.slot or (
            attested_slot == cur.attested_header.slot
            and participation > sum(cur.sync_aggregate.sync_committee_bits)
        ):
            ou = Fields(
                attested_header=block_to_header(self.p, attested_block.message),
                sync_aggregate=agg,
                signature_slot=block.slot,
            )
            self.latest_optimistic_update = ou
            self.chain.emitter.emit(ChainEvent.LIGHT_CLIENT_OPTIMISTIC_UPDATE, ou)

        if bytes(attested_state.finalized_checkpoint.root) == b"\x00" * 32:
            return
        # participation only competes between SAME-slot candidates — an
        # older update's high participation must not block a newer header
        cur = self.latest_finality_update
        cur_slot = cur.attested_header.slot if cur is not None else -1
        cur_part = -1
        if cur is not None and cur_slot == attested_slot:
            cur_part = sum(cur.sync_aggregate.sync_committee_bits)
        if self._pending_finality is not None:
            pend_block, _, pend_agg, _sig = self._pending_finality
            cur_slot = max(cur_slot, pend_block.message.slot)
            if pend_block.message.slot == attested_slot:
                cur_part = max(cur_part, sum(pend_agg.sync_committee_bits))
        if not (attested_slot > cur_slot
                or (attested_slot == cur_slot and participation > cur_part)):
            return
        self._pending_finality = (attested_block, attested_state, agg, block.slot)
        # only materialise eagerly when someone is listening for the event,
        # and only emit a FRESHLY built update — a failed materialisation
        # (finalized block missing from the store) must not re-emit stale
        # state every import
        if self.chain.emitter.has_listeners(ChainEvent.LIGHT_CLIENT_FINALITY_UPDATE):
            fu = self._materialize_pending()
            if fu is not None:
                self.chain.emitter.emit(ChainEvent.LIGHT_CLIENT_FINALITY_UPDATE, fu)

    def _build_update(self, attested_block, attested_state, sync_aggregate,
                      signature_slot: int = 0):
        from ..state_transition.upgrade import state_types

        st = state_types(self.p, attested_state).BeaconState
        try:
            _, nsc_branch = st.get_field_proof(attested_state, "next_sync_committee")
        except StopIteration:
            return None  # pre-altair attested state: no update possible
        finalized_header, finality_branch = self._finality_proof(attested_state)
        if finality_branch is None:
            finality_branch = []
        empty_header = Fields(
            slot=0, proposer_index=0, parent_root=b"\x00" * 32,
            state_root=b"\x00" * 32, body_root=b"\x00" * 32,
        )
        return Fields(
            attested_header=block_to_header(self.p, attested_block.message),
            next_sync_committee=attested_state.next_sync_committee,
            next_sync_committee_branch=[bytes(b) for b in nsc_branch],
            finalized_header=finalized_header or empty_header,
            finality_branch=finality_branch,
            sync_aggregate=sync_aggregate,
            # spec LightClientUpdate field; clients derive the signing
            # domain from their own fork schedule at this slot (an
            # update-supplied fork version is never trusted)
            signature_slot=signature_slot or (attested_block.message.slot + 1),
        )

    def get_update(self, period: int):
        return self.best_update_by_period.get(period)

    def get_latest_update(self):
        if not self.best_update_by_period:
            return None
        return self.best_update_by_period[max(self.best_update_by_period)]

    def _materialize_pending(self):
        """Build the parked finality update; returns it only when freshly
        built (None on no pending candidate or a missing finalized block)."""
        if self._pending_finality is None:
            return None
        attested_block, attested_state, agg, sig_slot = self._pending_finality
        self._pending_finality = None
        finalized_header, finality_branch = self._finality_proof(attested_state)
        if finalized_header is None:
            return None
        fu = Fields(
            attested_header=block_to_header(self.p, attested_block.message),
            finalized_header=finalized_header,
            finality_branch=finality_branch,
            sync_aggregate=agg,
            signature_slot=sig_slot,
        )
        self.latest_finality_update = fu
        return fu

    def get_finality_update(self):
        self._materialize_pending()
        return self.latest_finality_update

    def get_optimistic_update(self):
        return self.latest_optimistic_update
