"""Fee-recipient registrations from prepareBeaconProposer.

Reference: packages/beacon-node/src/chain/beaconProposerCache.ts — VCs
re-send their proposer preparations every epoch; entries expire after
PROPOSER_PRESERVE_EPOCHS so a disconnected VC's fee recipient stops
overriding the node default.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

PROPOSER_PRESERVE_EPOCHS = 2


class BeaconProposerCache:
    def __init__(self, default_fee_recipient: bytes = b"\x00" * 20):
        self.default_fee_recipient = default_fee_recipient
        self._entries: Dict[int, Tuple[int, bytes]] = {}  # index -> (epoch, recipient)

    def add(self, epoch: int, validator_index: int, fee_recipient: bytes) -> None:
        self._entries[int(validator_index)] = (int(epoch), bytes(fee_recipient))

    def prune(self, current_epoch: int) -> None:
        cutoff = current_epoch - PROPOSER_PRESERVE_EPOCHS
        self._entries = {
            i: (e, r) for i, (e, r) in self._entries.items() if e >= cutoff
        }

    def get(self, proposer_index: int) -> bytes:
        entry = self._entries.get(int(proposer_index))
        return entry[1] if entry is not None else self.default_fee_recipient

    def get_or_none(self, proposer_index: int) -> Optional[bytes]:
        entry = self._entries.get(int(proposer_index))
        return entry[1] if entry is not None else None
