"""Typed chain event bus.

Reference: packages/beacon-node/src/chain/emitter.ts (ChainEventEmitter —
clockSlot/clockEpoch/block/checkpoint/justified/finalized/head/reorg).
"""

from __future__ import annotations

import enum
from collections import defaultdict
from typing import Callable, DefaultDict, List


class ChainEvent(str, enum.Enum):
    CLOCK_SLOT = "clock:slot"
    CLOCK_EPOCH = "clock:epoch"
    BLOCK = "block"
    CHECKPOINT = "checkpoint"
    JUSTIFIED = "justified"
    FINALIZED = "finalized"
    HEAD = "forkChoice:head"
    REORG = "forkChoice:reorg"
    LIGHT_CLIENT_FINALITY_UPDATE = "lightClient:finalityUpdate"
    LIGHT_CLIENT_OPTIMISTIC_UPDATE = "lightClient:optimisticUpdate"


class ChainEventEmitter:
    def __init__(self):
        self._handlers: DefaultDict[ChainEvent, List[Callable]] = defaultdict(list)

    def on(self, event: ChainEvent, handler: Callable) -> None:
        self._handlers[event].append(handler)

    def off(self, event: ChainEvent, handler: Callable) -> None:
        if handler in self._handlers[event]:
            self._handlers[event].remove(handler)

    def has_listeners(self, event: ChainEvent) -> bool:
        return bool(self._handlers.get(event))

    def emit(self, event: ChainEvent, *args) -> None:
        for handler in list(self._handlers[event]):
            handler(*args)
