"""Operation pools: gossip-received ops buffered for block inclusion.

Reference: packages/beacon-node/src/chain/opPools/ (SURVEY §2.4):
- AttestationPool            unaggregated atts, per-slot groups, naive agg
- AggregatedAttestationPool  aggregates for block packing, scored
- OpPool                     slashings/exits (persisted across restarts)

Aggregation here happens on SERIALIZED signatures lazily: pools store
bytes; BLS point math runs only when an aggregate is actually consumed
(the reference aggregates eagerly because blst is cheap per-op; batching
the math suits the device model better).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..crypto.bls.api import Signature, aggregate_signatures
from ..params import Preset
from ..types import get_types


class OpPoolError(Exception):
    pass


@dataclasses.dataclass
class _AttGroup:
    data: object
    bits_and_sigs: List[Tuple[List[bool], bytes]]


class AttestationPool:
    """Unaggregated attestation pool (attestationPool.ts): keyed by slot ->
    data root -> list of (bits, sig); retention SLOTS_RETAINED=3."""

    SLOTS_RETAINED = 3
    MAX_PER_SLOT = 16384

    def __init__(self, preset: Preset):
        self.p = preset
        self.t = get_types(preset).phase0
        self._by_slot: Dict[int, Dict[bytes, _AttGroup]] = {}

    def add(self, attestation) -> str:
        slot = attestation.data.slot
        data_root = self.t.AttestationData.hash_tree_root(attestation.data)
        groups = self._by_slot.setdefault(slot, {})
        if sum(len(g.bits_and_sigs) for g in groups.values()) >= self.MAX_PER_SLOT:
            raise OpPoolError("attestation pool slot full")
        group = groups.get(data_root)
        if group is None:
            group = groups[data_root] = _AttGroup(data=attestation.data, bits_and_sigs=[])
        bits = list(attestation.aggregation_bits)
        for existing_bits, _ in group.bits_and_sigs:
            if all(not b or e for b, e in zip(bits, existing_bits)):
                return "already_known"
        group.bits_and_sigs.append((bits, bytes(attestation.signature)))
        return "added"

    def get_aggregate(self, slot: int, data_root: bytes):
        """Naive aggregation of all entries for (slot, data_root) — what an
        aggregator duty publishes (attestationPool.ts getAggregate)."""
        group = self._by_slot.get(slot, {}).get(data_root)
        if group is None:
            return None
        n = len(group.bits_and_sigs[0][0])
        bits = [False] * n
        sigs = []
        for b, sig in group.bits_and_sigs:
            if any(x and y for x, y in zip(bits, b)):
                continue  # overlapping: naive agg skips
            bits = [x or y for x, y in zip(bits, b)]
            sigs.append(Signature.from_bytes(sig))
        from ..ssz import Fields

        return Fields(
            aggregation_bits=bits,
            data=group.data,
            signature=aggregate_signatures(sigs).to_bytes(),
        )

    def __len__(self) -> int:
        # entries, not data-root groups — the pool-pressure number the
        # MAX_PER_SLOT bound also counts
        return sum(
            len(g.bits_and_sigs)
            for groups in self._by_slot.values()
            for g in groups.values()
        )

    def prune(self, clock_slot: int) -> None:
        for slot in list(self._by_slot):
            if slot < clock_slot - self.SLOTS_RETAINED:
                del self._by_slot[slot]


class AggregatedAttestationPool:
    """Aggregates for block packing (aggregatedAttestationPool.ts:40).

    Scoring: not-yet-seen attester count / inclusion age — the reference's
    packing heuristic (:103-174), kept; MAX_ATTESTATIONS_PER_GROUP=2.
    """

    SLOTS_RETAINED = 32
    MAX_PER_GROUP = 2

    def __init__(self, preset: Preset):
        self.p = preset
        self.t = get_types(preset).phase0
        self._by_slot: Dict[int, Dict[bytes, List[object]]] = {}

    def add(self, attestation) -> None:
        slot = attestation.data.slot
        data_root = self.t.AttestationData.hash_tree_root(attestation.data)
        group = self._by_slot.setdefault(slot, {}).setdefault(data_root, [])
        bits = list(attestation.aggregation_bits)
        for existing in group:
            if all(not b or e for b, e in zip(bits, existing.aggregation_bits)):
                return  # subset of an existing aggregate
        group.append(attestation)
        # keep the most participated aggregates
        group.sort(key=lambda a: -sum(a.aggregation_bits))
        del group[self.MAX_PER_GROUP :]

    def get_attestations_for_block(self, state, seen_attesters=None) -> List[object]:
        """Pick up to MAX_ATTESTATIONS, prev/current epoch valid, scored by
        fresh-attester count per age."""
        out: List[Tuple[float, object]] = []
        state_slot = state.slot
        min_slot = max(0, state_slot - self.p.SLOTS_PER_EPOCH)
        for slot in sorted(self._by_slot, reverse=True):
            if not (min_slot <= slot <= state_slot - self.p.MIN_ATTESTATION_INCLUSION_DELAY):
                continue
            age = state_slot - slot
            for group in self._by_slot[slot].values():
                for att in group:
                    fresh = sum(att.aggregation_bits)
                    score = fresh / (age + 1)
                    out.append((score, att))
        out.sort(key=lambda x: -x[0])
        return [att for _, att in out[: self.p.MAX_ATTESTATIONS]]

    def __len__(self) -> int:
        return sum(
            len(aggs)
            for groups in self._by_slot.values()
            for aggs in groups.values()
        )

    def prune(self, clock_slot: int) -> None:
        for slot in list(self._by_slot):
            if slot < clock_slot - self.SLOTS_RETAINED:
                del self._by_slot[slot]


class OpPool:
    """Slashings + exits awaiting inclusion (opPool.ts), persistable via
    BeaconDb repositories (chain.ts:272-280 persist-on-close)."""

    def __init__(self, preset: Preset):
        self.p = preset
        self.t = get_types(preset).phase0
        self.attester_slashings: Dict[bytes, object] = {}
        self.proposer_slashings: Dict[int, object] = {}
        self.voluntary_exits: Dict[int, object] = {}

    def add_attester_slashing(self, slashing) -> None:
        root = self.t.AttesterSlashing.hash_tree_root(slashing)
        self.attester_slashings[root] = slashing

    def add_proposer_slashing(self, slashing) -> None:
        self.proposer_slashings[slashing.signed_header_1.message.proposer_index] = slashing

    def add_voluntary_exit(self, signed_exit) -> None:
        self.voluntary_exits[signed_exit.message.validator_index] = signed_exit

    def get_slashings_and_exits(self, state) -> Tuple[List, List, List]:
        """Ops valid against `state` for a new block (opPool.ts
        getSlashingsAndExits — validity re-checked at packing)."""
        from ..params import FAR_FUTURE_EPOCH
        from ..state_transition.misc import compute_epoch_at_slot, is_active_validator

        epoch = compute_epoch_at_slot(self.p, state.slot)
        proposer = [
            s
            for i, s in self.proposer_slashings.items()
            if not state.validators[i].slashed
        ][: self.p.MAX_PROPOSER_SLASHINGS]
        attester = list(self.attester_slashings.values())[: self.p.MAX_ATTESTER_SLASHINGS]
        exits = [
            e
            for i, e in self.voluntary_exits.items()
            if is_active_validator(state.validators[i], epoch)
            and state.validators[i].exit_epoch == FAR_FUTURE_EPOCH
        ][: self.p.MAX_VOLUNTARY_EXITS]
        return proposer, attester, exits

    # -- persistence (toPersisted/fromPersisted) -----------------------------

    def to_db(self, beacon_db) -> None:
        from ..db.schema import uint_key

        for root, s in self.attester_slashings.items():
            beacon_db.attester_slashing.put(root, s)
        for i, s in self.proposer_slashings.items():
            beacon_db.proposer_slashing.put(uint_key(i), s)
        for i, e in self.voluntary_exits.items():
            beacon_db.voluntary_exit.put(uint_key(i), e)

    def from_db(self, beacon_db) -> None:
        from ..db.schema import decode_uint_key

        for root, s in beacon_db.attester_slashing.entries():
            self.attester_slashings[root] = s
        for k, s in beacon_db.proposer_slashing.entries():
            self.proposer_slashings[decode_uint_key(k)] = s
        for k, e in beacon_db.voluntary_exit.entries():
            self.voluntary_exits[decode_uint_key(k)] = e
