"""BlsBatchPool: async accumulation of signature sets into single device
dispatches — the scheduling layer of the north-star path.

Reference: BlsMultiThreadWorkerPool (chain/bls/multithread/index.ts:98).
The redesign: instead of N worker threads each running blst, ONE device
kernel verifies the whole merged batch, so the pool's job is purely
temporal: merge concurrent small jobs (gossip validation pushes 1-3 sets
each, attestation.ts:138) into dispatch-sized batches.

Mechanics kept from the reference, retuned for a TPU dispatch:
- buffer up to ``max_buffer_wait`` seconds or ``flush_threshold`` sets,
  then flush (MAX_BUFFER_WAIT_MS=100 / MAX_BUFFERED_SIGS=32 analog,
  multithread/index.ts:41-57; both configurable because the optimal values
  are dispatch-latency dependent, not core-count dependent).
- a failed merged batch is retried per job so one bad gossip message
  cannot poison its batchmates (worker.ts:78-88 retry-individually).
- accumulation happens through JobItemQueue.drain_batch — the queue seam
  built for exactly this (utils/queue.py:99).

Round-6 pipelining: the flusher keeps up to ``pipeline_depth`` merged
batches IN FLIGHT.  Against a stage-split verifier
(TpuBlsVerifier.verify_signature_sets_async), batch N+1 is packed and
its device program enqueued while batch N is still computing and batch
N-1's host final exponentiation runs — the pack/compute overlap the
reference's BlsMultiThreadWorkerPool gets from N worker threads, rebuilt
around ONE asynchronous device queue.  Verifiers without the async API
get the same window via thread-pool concurrency.

Round-8 multi-chip: ``pipeline_depth`` is PER DEVICE — the flush window
is ``pipeline_depth * verifier.n_devices`` merged batches, so an 8-chip
executor pool at depth 2 keeps 16 batches in flight and the verifier's
least-loaded scheduler spreads them across the chips.  Single-device
verifiers (n_devices absent or 1) behave exactly as before.
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import List, Optional, Sequence

from .. import tracing
from ..crypto.bls.verifier import IBlsVerifier, SignatureSet
from ..forensics.journal import JOURNAL
from ..tracing import TRACER
from ..utils.queue import JobItemQueue, QueueType
from ..utils.logger import get_logger

logger = get_logger("bls-pool")


class BlsBatchPool:
    """IBlsVerifier-compatible async facade over a device verifier."""

    def __init__(
        self,
        verifier: IBlsVerifier,
        *,
        max_buffer_wait: float = 0.02,
        flush_threshold: int = 128,
        max_queue_length: int = 8192,
        pipeline_depth: int = 2,
        metrics=None,
    ):
        self.verifier = verifier
        self.max_buffer_wait = max_buffer_wait
        self.flush_threshold = flush_threshold
        self.pipeline_depth = max(1, pipeline_depth)
        self.metrics = metrics
        # stage-split verifiers observe their pack/final-exp histograms on
        # the same registry
        if metrics is not None and getattr(verifier, "metrics", "no") is None:
            verifier.metrics = metrics
        self.batch_retries = 0
        self.batch_sets_success = 0
        self.inflight_peak = 0
        self._next_batch_id = 0  # correlation id shared by a batch's spans
        # max_concurrency=0: jobs are never auto-scheduled; the flusher is
        # the only consumer, via drain_batch.
        self._queue: JobItemQueue[List[SignatureSet], bool] = JobItemQueue(
            self._verify_job, max_length=max_queue_length, max_concurrency=0, queue_type=QueueType.FIFO
        )
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._flushing = False
        self._closed = False

    async def _verify_job(self, sets: List[SignatureSet]) -> bool:
        """Fallback single-job path (unused in normal operation: the queue
        has max_concurrency=0 and the flusher drains batches)."""
        return await asyncio.to_thread(self.verifier.verify_signature_sets, sets)

    # -- public API (chain.bls.verifySignatureSets analog) -------------------

    async def verify_signature_sets(self, sets: Sequence[SignatureSet], batchable: bool = True) -> bool:
        """Verify a job of sets; batchable jobs may wait up to
        max_buffer_wait to share a dispatch with concurrent jobs.

        An empty job raises (reference: multithread/index.ts throws on
        empty) — this is the one seam through which an empty drain could
        reach the verifier, and a silent False verdict here would read as
        'invalid signature' to gossip validation."""
        if self._closed:
            raise RuntimeError("pool closed")
        sets = list(sets)
        if not sets:
            raise ValueError("verify_signature_sets: empty batch of signature sets")
        if not batchable:
            return await asyncio.to_thread(self.verifier.verify_signature_sets, sets)
        loop = asyncio.get_running_loop()
        fut_result = loop.create_task(self._queue.push(sets))
        # the push task enqueues on its first step; check buffer state after
        loop.call_soon(self._buffered_sets_changed)
        return await fut_result

    def pending_sets(self) -> int:
        return sum(len(item) for item, _, _ in self._queue._items)

    def close(self) -> None:
        self._closed = True
        if self._flush_handle:
            self._flush_handle.cancel()
        self._queue.abort()

    # -- flushing -------------------------------------------------------------

    def _buffered_sets_changed(self) -> None:
        if self.metrics:
            self.metrics.bls_pool_queue_length.set(self.pending_sets())
        if self.pending_sets() >= self.flush_threshold:
            self._schedule_flush(0.0)
        elif self._flush_handle is None:
            self._schedule_flush(self.max_buffer_wait)

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._spawn_flush)

    def _spawn_flush(self) -> None:
        self._flush_handle = None
        if not self._flushing:
            asyncio.get_running_loop().create_task(self._flush())

    async def _flush(self) -> None:
        """Pipelined drain: keep up to ``pipeline_depth * n_devices``
        merged batches in flight.  The fill half packs + enqueues batch
        N+1 (host CPU work on a worker thread; the device dispatch itself
        is async) while the drain half reads back the OLDEST in-flight
        batch's verdict — so the host final exponentiation of batch N runs
        concurrently with the device compute of batch N+1, and a
        multi-device verifier's scheduler sees enough batches to feed
        every chip."""
        self._flushing = True
        use_async = hasattr(self.verifier, "verify_signature_sets_async")
        inflight: collections.deque = collections.deque()
        flush_t0 = time.monotonic()
        busy = 0.0  # sum of per-batch pack-start->verdict wall (overlap ratio)
        sets_done = 0  # sets resolved this flush (per-chip throughput gauge)
        # pipeline_depth is per device: a multi-chip executor pool wants
        # enough batches in flight to keep every chip busy
        window = self.pipeline_depth * max(1, getattr(self.verifier, "n_devices", 1))
        try:
            while len(self._queue) or inflight:
                # fill the window
                while len(self._queue) and len(inflight) < window:
                    drained = self._queue.drain_batch(
                        max_items=1024, with_enqueue_time=True
                    )
                    if not drained:
                        break
                    cid = self._next_batch_id
                    self._next_batch_id += 1
                    now = time.monotonic()
                    jobs: List = []
                    merged: List[SignatureSet] = []
                    for item, fut, t_enq in drained:
                        jobs.append((item, fut))
                        merged.extend(item)
                        if self.metrics:
                            self.metrics.bls_pool_queue_wait_seconds.observe(
                                now - t_enq
                            )
                        if TRACER.enabled:
                            TRACER.add_span(
                                "bls.queue_wait", "queue",
                                int(t_enq * 1e9), int(now * 1e9),
                                cid=cid, sets=len(item),
                            )
                    if self.metrics:
                        self.metrics.bls_pool_dispatches_total.inc()
                        self.metrics.bls_pool_batch_size.observe(len(merged))
                    # black box: the coalescing decision (how many jobs
                    # merged into this batch, window occupancy at the time)
                    if JOURNAL.enabled:
                        JOURNAL.record(
                            "pool.flush", cid=cid, jobs=len(jobs),
                            sets=len(merged), inflight=len(inflight),
                            window=window,
                        )
                    # correlation id rides the contextvar into to_thread and
                    # create_task (both copy the current context), so the
                    # verifier's pack/dispatch/final-exp spans pick it up
                    # without widening the IBlsVerifier API
                    t_fill = time.monotonic()  # batch busy starts at pack
                    device = None
                    token = tracing.set_batch(cid)
                    try:
                        if use_async:
                            # pack on a worker thread; returns once the
                            # device program is ENQUEUED, not finished
                            pending = await asyncio.to_thread(
                                self.verifier.verify_signature_sets_async, merged
                            )
                            # executor name the scheduler picked (None for a
                            # chunked batch spread over several devices)
                            device = getattr(pending, "device", None)
                            verdict = asyncio.create_task(
                                asyncio.to_thread(pending.result)
                            )
                        else:
                            verdict = asyncio.create_task(
                                asyncio.to_thread(
                                    self.verifier.verify_signature_sets, merged
                                )
                            )
                    except Exception as e:  # noqa: BLE001
                        # a pack/enqueue failure must NOT strand the drained
                        # jobs' futures: feed a failed verdict through the
                        # normal drain half so the per-job retry resolves
                        # every caller
                        logger.warning(
                            "dispatch enqueue failed: %s; will retry per job", e
                        )
                        verdict = asyncio.get_running_loop().create_future()
                        verdict.set_result(False)
                    finally:
                        tracing.reset_batch(token)
                    inflight.append(
                        (jobs, merged, verdict, t_fill, time.monotonic(), cid, device)
                    )
                    self.inflight_peak = max(self.inflight_peak, len(inflight))
                    if self.metrics:
                        self.metrics.bls_pool_inflight_depth.set(len(inflight))
                if not inflight:
                    return
                # drain the oldest batch
                jobs, merged, verdict, t_fill, t0, cid, device = inflight.popleft()
                try:
                    ok = await verdict
                except Exception as e:  # noqa: BLE001
                    logger.warning("merged dispatch raised: %s; retrying per job", e)
                    ok = False
                t_done = time.monotonic()
                # busy counts from pack start so a fully serial pipeline
                # reads ~1.0 (the documented baseline), overlap reads >1
                busy += t_done - t_fill
                sets_done += len(merged)
                if TRACER.enabled:
                    TRACER.add_span(
                        "pool.batch", "pool", int(t_fill * 1e9), int(t_done * 1e9),
                        cid=cid, sets=len(merged), jobs=len(jobs), ok=bool(ok),
                        inflight_left=len(inflight), device=device,
                    )
                if self.metrics:
                    self.metrics.bls_pool_dispatch_seconds.observe(t_done - t0)
                    self.metrics.bls_pool_inflight_depth.set(len(inflight))
                if ok:
                    self.batch_sets_success += len(merged)
                    for _item, fut in jobs:
                        if not fut.done():
                            fut.set_result(True)
                    continue
                # merged batch failed: re-verify each job individually so
                # innocent jobs still succeed (worker.ts:78-88)
                self.batch_retries += 1
                logger.debug("merged batch of %d jobs failed; retrying individually", len(jobs))
                for item, fut in jobs:
                    if fut.done():
                        continue
                    try:
                        one = await asyncio.to_thread(self.verifier.verify_signature_sets, item)
                    except Exception as e:  # noqa: BLE001
                        fut.set_exception(e)
                        continue
                    fut.set_result(one)
        finally:
            self._flushing = False
            self._publish_flush_metrics(busy, time.monotonic() - flush_t0, sets_done)
            if len(self._queue):
                self._buffered_sets_changed()

    def _publish_flush_metrics(self, busy: float, wall: float, sets_done: int = 0) -> None:
        """End-of-flush snapshots: the overlap ratio this flush achieved,
        the previously-orphaned verifier stage_seconds / pool
        inflight_peak counters (ISSUE 2 satellite 1), and the north-star
        per-chip throughput of this flush (sets resolved / wall /
        n_devices)."""
        if not self.metrics:
            return
        self.metrics.bls_pool_inflight_depth.set(0)
        self.metrics.bls_pool_inflight_peak.set(self.inflight_peak)
        if busy > 0 and wall > 0:
            self.metrics.bls_pool_overlap_ratio.set(busy / wall)
        if sets_done and wall > 0:
            n_dev = max(1, getattr(self.verifier, "n_devices", 1))
            self.metrics.bls_sets_per_sec_per_chip.set(sets_done / wall / n_dev)
        stage_seconds = getattr(self.verifier, "stage_seconds", None)
        if stage_seconds:
            for stage, secs in stage_seconds.items():
                self.metrics.bls_verifier_stage_seconds.labels(stage=stage).set(secs)
        # drop visibility: ring-buffer evictions would otherwise be the
        # one thing the observability stack is silent about
        self.metrics.tracing_spans_dropped_total.set(TRACER.dropped)
        self.metrics.forensics_journal_dropped_total.set(JOURNAL.dropped)
