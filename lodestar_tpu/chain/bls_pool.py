"""BlsBatchPool: async accumulation of signature sets into single device
dispatches — the scheduling layer of the north-star path.

Reference: BlsMultiThreadWorkerPool (chain/bls/multithread/index.ts:98).
The redesign: instead of N worker threads each running blst, ONE device
kernel verifies the whole merged batch, so the pool's job is purely
temporal: merge concurrent small jobs (gossip validation pushes 1-3 sets
each, attestation.ts:138) into dispatch-sized batches.

Mechanics kept from the reference, retuned for a TPU dispatch:
- buffer up to ``max_buffer_wait`` seconds or ``flush_threshold`` sets,
  then flush (MAX_BUFFER_WAIT_MS=100 / MAX_BUFFERED_SIGS=32 analog,
  multithread/index.ts:41-57; both configurable because the optimal values
  are dispatch-latency dependent, not core-count dependent).
- a failed merged batch is retried per job so one bad gossip message
  cannot poison its batchmates (worker.ts:78-88 retry-individually).
- accumulation happens through JobItemQueue.drain_batch — the queue seam
  built for exactly this (utils/queue.py).

Round-6 pipelining: the flusher keeps up to ``pipeline_depth`` merged
batches IN FLIGHT.  Against a stage-split verifier
(TpuBlsVerifier.verify_signature_sets_async), batch N+1 is packed and
its device program enqueued while batch N is still computing and batch
N-1's host final exponentiation runs — the pack/compute overlap the
reference's BlsMultiThreadWorkerPool gets from N worker threads, rebuilt
around ONE asynchronous device queue.  Verifiers without the async API
get the same window via thread-pool concurrency.

Round-8 multi-chip: ``pipeline_depth`` is PER DEVICE — the flush window
is ``pipeline_depth * verifier.n_devices`` merged batches, so an 8-chip
executor pool at depth 2 keeps 16 batches in flight and the verifier's
least-loaded scheduler spreads them across the chips.  Single-device
verifiers (n_devices absent or 1) behave exactly as before.

Round-10 overload survival (docs/overload.md): the pool now SCHEDULES,
not just merges.

- **Priority lanes**: every job carries a ``SignatureSetPriority``
  (block_proposal > aggregate > unaggregated > sync_committee; untagged
  callers share the default lane).  The queue drains lane-ordered, so a
  block proposal arriving during an attestation storm rides the very
  next merged batch instead of queueing behind thousands of stale sets.
- **Deadline shedding**: a job may carry an absolute ``time.monotonic()``
  deadline; the flusher sheds expired jobs BEFORE packing, resolving
  their futures with a typed ``VerificationDroppedError`` (never a
  silent False — a drop is an admission decision, not a verdict).
- **Overflow eviction**: queue overflow evicts the oldest job of the
  lowest lane (``overflow="evict_low"``) instead of raising
  QUEUE_MAX_LENGTH into gossip validation.
- **Backpressure**: ``overloaded`` toggles at a pending-set high-water
  mark (released at half) so intake (gossip router) can slow down
  instead of OOMing.
- Every drop lands in ``bls_pool_dropped_total{reason,lane}`` (counted
  in SETS) plus a journal event, and a shed-rate spike across
  ``overload_shed_threshold`` sets within ``overload_window_s`` writes
  one rate-limited "overload" diagnostic bundle with per-lane shed
  counts and queue depth at trigger (tools/inspect_bundle.py triages
  it).
"""

from __future__ import annotations

import asyncio
import collections
import inspect
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from .. import tracing
from ..crypto.bls.verifier import (
    DEFAULT_PRIORITY,
    IBlsVerifier,
    SignatureSet,
    SignatureSetPriority,
    VerificationDroppedError,
)
from ..forensics.journal import JOURNAL
from ..observatory.xprof import notify_flush as _xprof_notify_flush
from ..tracing import TRACER
from ..utils.queue import JobItemQueue, QueueError, QueueType
from ..utils.logger import get_logger

logger = get_logger("bls-pool")


def _lane_name(lane) -> str:
    try:
        return SignatureSetPriority(lane).name.lower()
    except ValueError:  # lint: disable=bls-silent-except
        # label-formatting fallback for out-of-enum lanes, not a fault path
        return str(lane)


class BlsBatchPool:
    """IBlsVerifier-compatible async facade over a device verifier."""

    def __init__(
        self,
        verifier: IBlsVerifier,
        *,
        max_buffer_wait: float = 0.02,
        flush_threshold: int = 128,
        max_queue_length: int = 8192,
        pipeline_depth: int = 2,
        high_water: Optional[int] = None,
        overload_shed_threshold: int = 256,
        overload_window_s: float = 10.0,
        overload_cooldown_s: float = 60.0,
        metrics=None,
    ):
        self.verifier = verifier
        self.max_buffer_wait = max_buffer_wait
        self.flush_threshold = flush_threshold
        self.pipeline_depth = max(1, pipeline_depth)
        self.metrics = metrics
        # stage-split verifiers observe their pack/final-exp histograms on
        # the same registry
        if metrics is not None and getattr(verifier, "metrics", "no") is None:
            verifier.metrics = metrics
        self.batch_retries = 0
        self.batch_sets_success = 0
        self.inflight_peak = 0
        self._next_batch_id = 0  # correlation id shared by a batch's spans
        # -- overload policy (docs/overload.md) --------------------------------
        # high-water in pending SETS; hysteresis releases at half so a
        # queue oscillating around the mark doesn't flap the signal
        self.high_water = high_water if high_water else max_queue_length // 2
        self.low_water = max(1, self.high_water // 2)
        self.overloaded = False
        self.overload_shed_threshold = overload_shed_threshold
        self.overload_window_s = overload_window_s
        self.overload_cooldown_s = overload_cooldown_s
        self._last_overload_bundle = -1e18
        self._shed_window: Deque[Tuple[float, int]] = collections.deque()
        self._shed_window_sum = 0  # running sum: O(1) per drop, not O(window)
        self._overload_task: Optional[asyncio.Task] = None
        #: cumulative dropped sets by (reason, lane-name) — the accounting
        #: the firehose harness and diagnostic bundles read back
        self.dropped_sets: Dict[Tuple[str, str], int] = {}
        # max_concurrency=0: jobs are never auto-scheduled; the flusher is
        # the only consumer, via drain_batch.  overflow="evict_low": a full
        # queue sheds the oldest job of the lowest lane instead of raising
        # QUEUE_MAX_LENGTH into validation; size_fn=len keeps pending_sets
        # O(1) (one job = a list of signature sets).
        self._queue: JobItemQueue[List[SignatureSet], bool] = JobItemQueue(
            self._verify_job, max_length=max_queue_length, max_concurrency=0,
            queue_type=QueueType.FIFO, overflow="evict_low", size_fn=len,
        )
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._flushing = False
        self._closed = False
        # verifier capabilities are fixed at construction: probe once, not
        # per flush (inspect.signature on the hot scheduling path)
        self._use_async = hasattr(verifier, "verify_signature_sets_async")
        self._accepts_deadline = False
        if self._use_async:
            try:
                self._accepts_deadline = "deadline" in inspect.signature(
                    verifier.verify_signature_sets_async
                ).parameters
            except (TypeError, ValueError):  # lint: disable=bls-silent-except
                # construction-time capability probe, not a fault path
                self._accepts_deadline = False

    async def _verify_job(self, sets: List[SignatureSet]) -> bool:
        """Fallback single-job path (unused in normal operation: the queue
        has max_concurrency=0 and the flusher drains batches)."""
        return await asyncio.to_thread(self.verifier.verify_signature_sets, sets)

    # -- public API (chain.bls.verifySignatureSets analog) -------------------

    async def verify_signature_sets(
        self,
        sets: Sequence[SignatureSet],
        batchable: bool = True,
        priority: Optional[SignatureSetPriority] = None,
        deadline: Optional[float] = None,
    ) -> bool:
        """Verify a job of sets; batchable jobs may wait up to
        max_buffer_wait to share a dispatch with concurrent jobs.

        ``priority`` selects the QoS lane (default: the untagged lane, so
        existing callers behave exactly as before).  ``deadline`` is an
        absolute ``time.monotonic()`` instant; a job still buffered past
        it is shed with ``VerificationDroppedError`` instead of verified
        — an attestation is worthless after its inclusion window, and
        burning device time on it during a storm starves live traffic.

        An empty job raises (reference: multithread/index.ts throws on
        empty) — this is the one seam through which an empty drain could
        reach the verifier, and a silent False verdict here would read as
        'invalid signature' to gossip validation."""
        if self._closed:
            raise RuntimeError("pool closed")
        sets = list(sets)
        if not sets:
            raise ValueError("verify_signature_sets: empty batch of signature sets")
        lane = DEFAULT_PRIORITY if priority is None else SignatureSetPriority(priority)
        if not batchable:
            return await asyncio.to_thread(self.verifier.verify_signature_sets, sets)
        loop = asyncio.get_running_loop()
        fut_result = loop.create_task(
            self._queue.push(sets, priority=int(lane), deadline=deadline)
        )
        # the push task enqueues on its first step; check buffer state after
        loop.call_soon(self._buffered_sets_changed)
        try:
            return await fut_result
        except QueueError as e:
            if e.code == "QUEUE_MAX_LENGTH":
                # this job was the overflow victim: either it was evicted
                # from the lowest lane, or everything buffered outranked it
                self._count_drop("overflow", lane, len(sets))
                raise VerificationDroppedError("overflow", lane) from e
            if e.code == "QUEUE_ABORTED":
                # close() aborted the queue while this job was buffered:
                # same typed contract as shutdown-mid-retry — callers are
                # written around VerificationDroppedError, never QueueError
                self._count_drop("shutdown", lane, len(sets))
                raise VerificationDroppedError("shutdown", lane) from e
            raise

    def pending_sets(self) -> int:
        """Buffered signature sets — O(1) (the queue maintains the sum;
        the pre-round-10 deque walk here was O(n²) intake under storm
        load, once per push via _buffered_sets_changed)."""
        return self._queue.pending_size

    def close(self) -> None:
        self._closed = True
        if self._flush_handle:
            self._flush_handle.cancel()
        self._queue.abort()

    # -- drop accounting ------------------------------------------------------

    def _count_drop(self, reason: str, lane, n_sets: int) -> None:
        """One bookkeeping seam for EVERY shed/evicted/shutdown set:
        Prometheus counter, journal aggregate, firehose-readable dict, and
        the overload-bundle rate window."""
        name = _lane_name(lane)
        key = (reason, name)
        self.dropped_sets[key] = self.dropped_sets.get(key, 0) + n_sets
        if self.metrics:
            self.metrics.bls_pool_dropped_total.labels(
                reason=reason, lane=name
            ).inc(n_sets)
        # every drop leaves journal evidence: deadline sheds are batched
        # into one pool.shed event by _shed_expired; the push-time reasons
        # (overflow eviction, shutdown) are recorded here per drop
        if reason != "deadline" and JOURNAL.enabled:
            JOURNAL.record("pool.drop", reason=reason, lane=name, sets=n_sets)
        if not self.overload_shed_threshold:
            return  # bundles disabled: don't grow the rate window either
        now = time.monotonic()
        self._shed_window.append((now, n_sets))
        self._shed_window_sum += n_sets
        self._maybe_overload_bundle(now)

    def _maybe_overload_bundle(self, now: float) -> None:
        """Cross the shed-rate threshold -> ONE diagnostic bundle (rate
        limited by ``overload_cooldown_s``) so a storm leaves triageable
        evidence: per-lane shed counts and the queue depth at trigger."""
        if not self.overload_shed_threshold:
            return
        window = self._shed_window
        while window and now - window[0][0] > self.overload_window_s:
            self._shed_window_sum -= window.popleft()[1]
        shed = self._shed_window_sum
        if shed < self.overload_shed_threshold:
            return
        if now - self._last_overload_bundle < self.overload_cooldown_s:
            return
        if self._overload_task is not None and not self._overload_task.done():
            return  # one dump at a time, whatever the cooldown says
        self._last_overload_bundle = now
        extra = {
            "overload": {
                "shed_window_sets": shed,
                "window_s": self.overload_window_s,
                "dropped_by_lane": self._dropped_by("lane"),
                "dropped_by_reason": self._dropped_by("reason"),
                "queue_depth_jobs": len(self._queue),
                "pending_sets": self.pending_sets(),
                "backpressure": self.overloaded,
            }
        }
        JOURNAL.record(
            "pool.overload", level="ERROR", shed_window_sets=shed,
            pending_sets=self.pending_sets(),
        )

        def _dump() -> None:
            from ..forensics.recorder import RECORDER

            try:
                RECORDER.dump("overload", extra=extra, metric_reason="overload")
            except Exception:  # a broken dump path must never hit the flusher
                logger.exception("overload bundle failed")

        # bundle writing is file I/O: keep it off the event loop; strong
        # ref so the task survives (the loop holds tasks weakly)
        self._overload_task = asyncio.get_running_loop().create_task(
            asyncio.to_thread(_dump)
        )

    def _dropped_by(self, axis: str) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for (reason, lane), n in self.dropped_sets.items():
            k = reason if axis == "reason" else lane
            out[k] = out.get(k, 0) + n
        return out

    # -- backpressure ----------------------------------------------------------

    def _update_backpressure(self) -> None:
        pending = self.pending_sets()
        if not self.overloaded and pending >= self.high_water:
            self.overloaded = True
            if self.metrics:
                self.metrics.bls_pool_backpressure.set(1)
            JOURNAL.record(
                "pool.backpressure", level="WARNING", on=True,
                pending_sets=pending, high_water=self.high_water,
            )
            logger.warning(
                "bls pool backpressure ON: %d pending sets (high water %d)",
                pending, self.high_water,
            )
        elif self.overloaded and pending <= self.low_water:
            self.overloaded = False
            if self.metrics:
                self.metrics.bls_pool_backpressure.set(0)
            JOURNAL.record(
                "pool.backpressure", on=False, pending_sets=pending,
                low_water=self.low_water,
            )
            logger.info(
                "bls pool backpressure off: %d pending sets (low water %d)",
                pending, self.low_water,
            )

    def _publish_lane_gauges(self) -> None:
        if not self.metrics:
            return
        lengths = self._queue.lane_lengths()
        for lane in SignatureSetPriority:
            self.metrics.bls_pool_lane_pending.labels(
                lane=lane.name.lower()
            ).set(lengths.get(int(lane), 0))

    # -- flushing -------------------------------------------------------------

    def _flush_window(self) -> Tuple[int, int]:
        """(pipeline window, per-batch merge cap) for the current flush
        pass.  Per-device placement wants ``pipeline_depth`` batches PER
        chip, each near ``flush_threshold``.  An active sharded tier
        (docs/multichip.md) grows the MERGE CAP by ``n_devices`` — under
        storm load one mesh-wide merged batch then absorbs what would
        otherwise fan out as ``n_devices`` separate placements — while
        the window stays ``pipeline_depth × n_devices``: light traffic
        still drains into small sub-mesh batches that ride the
        per-device pool tier, and shrinking the window for THOSE would
        idle n-1 chips (the pool cannot know a batch's tier before it is
        drained and packed).  Re-read every loop iteration — a sharded
        tier that degrades mid-storm drops the cap back on the next
        fill."""
        n_dev = max(1, getattr(self.verifier, "n_devices", 1))
        max_size = max(self.flush_threshold, 1)
        if getattr(self.verifier, "sharded_active", False):
            max_size *= n_dev
        return self.pipeline_depth * n_dev, max_size

    def _buffered_sets_changed(self) -> None:
        if self.metrics:
            self.metrics.bls_pool_queue_length.set(self.pending_sets())
        self._update_backpressure()
        if self.pending_sets() >= self.flush_threshold:
            self._schedule_flush(0.0)
        elif self._flush_handle is None:
            self._schedule_flush(self.max_buffer_wait)

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._spawn_flush)

    def _spawn_flush(self) -> None:
        self._flush_handle = None
        if not self._flushing:
            asyncio.get_running_loop().create_task(self._flush())

    def _shed_expired(self, drained: List[Tuple], cid: int) -> List[Tuple]:
        """Drop drained jobs whose deadline already passed, BEFORE any
        pack work is spent on them.  Each shed future resolves with the
        typed ``VerificationDroppedError`` (IGNORE upstream, never a
        False 'invalid signature'); the survivors are returned."""
        now = time.monotonic()
        live: List[Tuple] = []
        shed_by_lane: Dict[str, int] = {}
        for item, fut, t_enq, lane, deadline in drained:
            if deadline is None or now <= deadline:
                live.append((item, fut, t_enq, lane, deadline))
                continue
            lane_p = SignatureSetPriority(lane)
            self._count_drop("deadline", lane_p, len(item))
            shed_by_lane[_lane_name(lane)] = (
                shed_by_lane.get(_lane_name(lane), 0) + len(item)
            )
            if TRACER.enabled:
                TRACER.add_span(
                    "bls.shed", "pool", int(t_enq * 1e9), int(now * 1e9),
                    cid=cid, lane=_lane_name(lane), reason="deadline",
                    sets=len(item),
                )
            if not fut.done():
                fut.set_exception(VerificationDroppedError("deadline", lane_p))
        if shed_by_lane and JOURNAL.enabled:
            JOURNAL.record(
                "pool.shed", level="WARNING", cid=cid, reason="deadline",
                sets=sum(shed_by_lane.values()), by_lane=shed_by_lane,
            )
        return live

    async def _flush(self) -> None:
        """Pipelined drain: keep up to ``pipeline_depth * n_devices``
        merged batches in flight.  The fill half sheds expired jobs, then
        packs + enqueues batch N+1 (host CPU work on a worker thread; the
        device dispatch itself is async) while the drain half reads back
        the OLDEST in-flight batch's verdict — so the host final
        exponentiation of batch N runs concurrently with the device
        compute of batch N+1, and a multi-device verifier's scheduler
        sees enough batches to feed every chip.  Batches drain
        lane-ordered: the queue hands back block proposals first."""
        self._flushing = True
        use_async = self._use_async
        accepts_deadline = self._accepts_deadline
        inflight: collections.deque = collections.deque()
        flush_t0 = time.monotonic()
        busy = 0.0  # sum of per-batch pack-start->verdict wall (overlap ratio)
        sets_done = 0  # sets resolved this flush (per-chip throughput gauge)
        # pipeline_depth is per device: a multi-chip executor pool wants
        # enough batches in flight to keep every chip busy.  With the
        # sharded tier active the merge cap grows so storm backlogs form
        # mesh-wide batches — see _flush_window.
        window, max_size = self._flush_window()
        try:
            while len(self._queue) or inflight:
                window, max_size = self._flush_window()
                # fill the window.  max_size keeps each merged batch near
                # the dispatch-sized flush_threshold even when a storm
                # backlog sits in the queue — lane priority is only real
                # if the block lane rides the NEXT batch, not the middle
                # of one mega-batch (a single oversized job still drains
                # alone and chunks verifier-side).
                while len(self._queue) and len(inflight) < window:
                    drained = self._queue.drain_batch(
                        max_items=1024, with_meta=True,
                        max_size=max_size,
                    )
                    if not drained:
                        break
                    cid = self._next_batch_id
                    self._next_batch_id += 1
                    drained = self._shed_expired(drained, cid)
                    if not drained:
                        self._update_backpressure()
                        continue  # the whole drain was expired backlog
                    now = time.monotonic()
                    jobs: List = []
                    merged: List[SignatureSet] = []
                    batch_deadline: Optional[float] = None
                    for item, fut, t_enq, lane, deadline in drained:
                        jobs.append((item, fut, lane, t_enq))
                        merged.extend(item)
                        if deadline is not None:
                            batch_deadline = (
                                deadline if batch_deadline is None
                                else min(batch_deadline, deadline)
                            )
                        if self.metrics:
                            # deprecated laneless alias kept one release
                            self.metrics.bls_pool_queue_wait_seconds.observe(
                                now - t_enq
                            )
                            self.metrics.bls_queue_wait_seconds.labels(
                                lane=_lane_name(lane)
                            ).observe(now - t_enq)
                        if TRACER.enabled:
                            TRACER.add_span(
                                "bls.queue_wait", "queue",
                                int(t_enq * 1e9), int(now * 1e9),
                                cid=cid, sets=len(item), lane=_lane_name(lane),
                            )
                    self._update_backpressure()
                    self._publish_lane_gauges()
                    if self.metrics:
                        self.metrics.bls_pool_dispatches_total.inc()
                        self.metrics.bls_pool_batch_size.observe(len(merged))
                    # black box: the coalescing decision (how many jobs
                    # merged into this batch, window occupancy at the time)
                    if JOURNAL.enabled:
                        JOURNAL.record(
                            "pool.flush", cid=cid, jobs=len(jobs),
                            sets=len(merged), inflight=len(inflight),
                            window=window,
                        )
                    # correlation id rides the contextvar into to_thread and
                    # create_task (both copy the current context), so the
                    # verifier's pack/dispatch/final-exp spans pick it up
                    # without widening the IBlsVerifier API
                    t_fill = time.monotonic()  # batch busy starts at pack
                    device = None
                    token = tracing.set_batch(cid)
                    try:
                        if use_async:
                            # pack on a worker thread; returns once the
                            # device program is ENQUEUED, not finished.  The
                            # batch's tightest job deadline rides along so
                            # dispatch placement / the in-flight table see it.
                            if accepts_deadline:
                                pending = await asyncio.to_thread(
                                    self.verifier.verify_signature_sets_async,
                                    merged, deadline=batch_deadline,
                                )
                            else:
                                pending = await asyncio.to_thread(
                                    self.verifier.verify_signature_sets_async,
                                    merged,
                                )
                            # executor name the scheduler picked (None for a
                            # chunked batch spread over several devices)
                            device = getattr(pending, "device", None)
                            verdict = asyncio.create_task(
                                asyncio.to_thread(pending.result)
                            )
                        else:
                            verdict = asyncio.create_task(
                                asyncio.to_thread(
                                    self.verifier.verify_signature_sets, merged
                                )
                            )
                    except Exception as e:  # noqa: BLE001
                        # a pack/enqueue failure must NOT strand the drained
                        # jobs' futures: feed a failed verdict through the
                        # normal drain half so the per-job retry resolves
                        # every caller
                        logger.warning(
                            "dispatch enqueue failed: %s; will retry per job", e
                        )
                        verdict = asyncio.get_running_loop().create_future()
                        verdict.set_result(False)
                    finally:
                        tracing.reset_batch(token)
                    inflight.append(
                        (jobs, merged, verdict, t_fill, time.monotonic(), cid, device)
                    )
                    self.inflight_peak = max(self.inflight_peak, len(inflight))
                    if self.metrics:
                        self.metrics.bls_pool_inflight_depth.set(len(inflight))
                if not inflight:
                    return
                # drain the oldest batch
                jobs, merged, verdict, t_fill, t0, cid, device = inflight.popleft()
                try:
                    ok = await verdict
                except Exception as e:  # noqa: BLE001
                    logger.warning("merged dispatch raised: %s; retrying per job", e)
                    ok = False
                t_done = time.monotonic()
                # busy counts from pack start so a fully serial pipeline
                # reads ~1.0 (the documented baseline), overlap reads >1
                busy += t_done - t_fill
                sets_done += len(merged)
                if TRACER.enabled:
                    TRACER.add_span(
                        "pool.batch", "pool", int(t_fill * 1e9), int(t_done * 1e9),
                        cid=cid, sets=len(merged), jobs=len(jobs), ok=bool(ok),
                        inflight_left=len(inflight), device=device,
                    )
                if self.metrics:
                    self.metrics.bls_pool_dispatch_seconds.observe(t_done - t0)
                    self.metrics.bls_pool_inflight_depth.set(len(inflight))
                if ok:
                    self.batch_sets_success += len(merged)
                    for item, fut, lane, t_enq in jobs:
                        # e2e observes DELIVERED verdicts only: a pusher
                        # cancelled mid-flight (fut already done) never
                        # received one, and the retry path below skips
                        # those too — the histogram must agree
                        if not fut.done():
                            fut.set_result(True)
                            self._observe_e2e(lane, t_done - t_enq)
                    continue
                # merged batch failed: re-verify each job individually so
                # innocent jobs still succeed (worker.ts:78-88)
                self.batch_retries += 1
                logger.debug("merged batch of %d jobs failed; retrying individually", len(jobs))
                for item, fut, lane, t_enq in jobs:
                    if fut.done():
                        continue
                    if self._closed:
                        # shutdown mid-retry: resolve (typed), never strand —
                        # an awaiting validator task must not hang forever
                        # on a pool that no longer has a verifier behind it
                        lane_p = SignatureSetPriority(lane)
                        self._count_drop("shutdown", lane_p, len(item))
                        fut.set_exception(
                            VerificationDroppedError("shutdown", lane_p)
                        )
                        continue
                    try:
                        one = await asyncio.to_thread(self.verifier.verify_signature_sets, item)
                    except Exception as e:  # noqa: BLE001
                        if not fut.done():  # pusher cancelled during the await
                            fut.set_exception(e)
                        continue
                    if not fut.done():  # ditto — set on a cancelled future
                        fut.set_result(one)  # raises and would kill the flusher
                        self._observe_e2e(lane, time.monotonic() - t_enq)
        finally:
            self._flushing = False
            self._update_backpressure()
            self._publish_lane_gauges()
            self._publish_flush_metrics(busy, time.monotonic() - flush_t0, sets_done)
            # profile-window flush boundary (observatory/xprof.py): a
            # constant-time no-op until a capture is configured, and
            # guaranteed non-raising — deliberately OUTSIDE the metrics
            # guard so a metrics-less pool still drives windows
            _xprof_notify_flush()
            if len(self._queue):
                self._buffered_sets_changed()

    def _observe_e2e(self, lane, seconds: float) -> None:
        """Histogram-grade end-to-end verify latency (enqueue -> verdict
        resolved) per QoS lane — the /metrics twin of the firehose
        report's e2e percentiles (same SLO bucket ladder)."""
        if self.metrics:
            self.metrics.bls_e2e_verify_seconds.labels(
                lane=_lane_name(lane)
            ).observe(seconds)

    def _publish_flush_metrics(self, busy: float, wall: float, sets_done: int = 0) -> None:
        """End-of-flush snapshots: the overlap ratio this flush achieved,
        the previously-orphaned verifier stage_seconds / pool
        inflight_peak counters (ISSUE 2 satellite 1), and the north-star
        throughput of this flush — per chip AND whole-mesh (ISSUE 7
        satellite 2: roadmap item 1's success metric needs the mesh
        headline to exist before the sharded kernel lands)."""
        if not self.metrics:
            return
        self.metrics.bls_pool_inflight_depth.set(0)
        self.metrics.bls_pool_inflight_peak.set(self.inflight_peak)
        if busy > 0 and wall > 0:
            self.metrics.bls_pool_overlap_ratio.set(busy / wall)
        if sets_done and wall > 0:
            n_dev = max(1, getattr(self.verifier, "n_devices", 1))
            self.metrics.bls_sets_per_sec_per_chip.set(sets_done / wall / n_dev)
            self.metrics.bls_sets_per_sec_mesh.set(sets_done / wall)
        stage_seconds = getattr(self.verifier, "stage_seconds", None)
        if stage_seconds:
            for stage, secs in stage_seconds.items():
                self.metrics.bls_verifier_stage_seconds.labels(stage=stage).set(secs)
        # drop visibility: ring-buffer evictions would otherwise be the
        # one thing the observability stack is silent about
        self.metrics.tracing_spans_dropped_total.set(TRACER.dropped)
        self.metrics.forensics_journal_dropped_total.set(JOURNAL.dropped)
