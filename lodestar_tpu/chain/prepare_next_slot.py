"""PrepareNextSlotScheduler + ReprocessController.

Reference: packages/beacon-node/src/chain/prepareNextSlot.ts:30 (at 2/3 of
every slot, advance the head state to slot+1 so proposals/attestations at
the next slot start from a warm state) and chain/reprocess.ts:51
(attestations referencing an unknown head block wait — bounded — for that
block to arrive instead of being dropped).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from ..params import Preset
from ..state_transition import clone_state, process_slots
from ..utils.logger import get_logger
from .emitter import ChainEvent

logger = get_logger("prepare-next-slot")

REPROCESS_MAX_WAIT = 2.0  # seconds (reprocess.ts WAIT_TIME_BEFORE_REJECT)
REPROCESS_MAX_PENDING = 16_384


class PrepareNextSlotScheduler:
    """Precomputes (head_root, next_slot) -> advanced state; BeaconChain's
    produce_block and the gossip handlers consult the cache via
    get_prepared_state."""

    def __init__(self, preset: Preset, chain):
        self.p = preset
        self.chain = chain
        self._prepared: Optional[Tuple[bytes, int, object, object]] = None

    async def prepare(self, next_slot: int) -> None:
        import time

        head_root = self.chain.head_root
        state = clone_state(self.p, self.chain.head_state())
        if state.slot >= next_slot:
            return
        crosses_epoch = next_slot % self.p.SLOTS_PER_EPOCH == 0
        t0 = time.monotonic()
        ctx = process_slots(self.p, self.chain.cfg, state, next_slot)
        if crosses_epoch and self.chain.metrics:
            # the precomputed epoch transition — the cost the 2/3-slot tick
            # absorbs off the import path (lodestar.ts stfnEpochTransition)
            self.chain.metrics.epoch_transition_seconds.observe(
                time.monotonic() - t0
            )
        self._prepared = (head_root, next_slot, state, ctx)
        logger.debug("prepared state for slot %d on head %s", next_slot, head_root.hex()[:8])

    def get_prepared_state(self, head_root: bytes, slot: int):
        """(state, ctx) if the precomputation matches, else None."""
        if self._prepared is None:
            return None
        r, s, state, ctx = self._prepared
        if r == head_root and s == slot:
            return state, ctx
        return None


class ReprocessController:
    """awaitBlockOfAttestation: parks objects keyed by the missing block
    root; resolves them when the block is imported, rejects on timeout."""

    def __init__(self, chain):
        self.chain = chain
        self._waiting: Dict[bytes, List[asyncio.Future]] = {}
        chain.emitter.on(ChainEvent.BLOCK, self._on_block)

    def _on_block(self, signed_block, block_root: bytes) -> None:
        futs = self._waiting.pop(block_root, [])
        for f in futs:
            if not f.done():
                f.set_result(True)

    async def wait_for_block(self, root: bytes, timeout: float = REPROCESS_MAX_WAIT) -> bool:
        """True if the block arrived within the window."""
        if self.chain.fork_choice.has_block(root):
            return True
        total = sum(len(v) for v in self._waiting.values())
        if total >= REPROCESS_MAX_PENDING:
            return False
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiting.setdefault(root, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            lst = self._waiting.get(root)
            if lst and fut in lst:
                lst.remove(fut)
                if not lst:
                    del self._waiting[root]
