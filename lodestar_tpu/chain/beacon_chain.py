"""BeaconChain: the orchestrator tying STF + fork choice + the batched
verifier boundary together.

Reference: packages/beacon-node/src/chain/chain.ts:58 (BeaconChain),
blocks/verifyBlock.ts:45 (verify flow: sanity -> STF with deferred sigs ->
one batched signature-set verification) and blocks/importBlock.ts:76
(fork-choice import + head update).

This is the minimum end-to-end core (SURVEY §7 step 6): network/sync/api
attach on top; regen here is a simple block-root -> post-state cache (the
queued regenerator with db replay is a later layer).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config.chain_config import ChainConfig
from ..fork_choice import Checkpoint, ForkChoice, ForkChoiceStore, ProtoNode
from ..params import Preset
from ..ssz import Fields
from ..state_transition import (
    EpochContext,
    clone_state,
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_block_signature_sets,
    process_slots,
    state_transition,
)
from ..types import get_types
from .bls_pool import BlsBatchPool
from .emitter import ChainEvent, ChainEventEmitter
from ..utils.logger import get_logger

logger = get_logger("chain")


class BlockError(Exception):
    pass


class BeaconChain:
    def __init__(
        self,
        preset: Preset,
        cfg: ChainConfig,
        genesis_state,
        bls_pool: BlsBatchPool,
        metrics=None,
        clock=None,
    ):
        self.p = preset
        self.cfg = cfg
        self.bls = bls_pool
        self.metrics = metrics
        self.clock = clock
        self.emitter = ChainEventEmitter()
        self.t = get_types(preset).phase0
        from ..config.fork_config import ForkConfig

        self.fork_config = ForkConfig(cfg)

        # anchor: genesis (or checkpoint) state + implied block header
        self.genesis_state = genesis_state
        header = Fields(**{k: genesis_state.latest_block_header[k] for k in genesis_state.latest_block_header.keys()})
        if header.state_root == b"\x00" * 32:
            from ..state_transition.upgrade import state_types

            header.state_root = state_types(preset, genesis_state).BeaconState.hash_tree_root(
                genesis_state
            )
        anchor_root = self.t.BeaconBlockHeader.hash_tree_root(header)

        balances = np.array(
            [v.effective_balance for v in genesis_state.validators], dtype=np.int64
        )
        anchor_epoch = compute_epoch_at_slot(preset, genesis_state.slot)
        cp = Checkpoint(anchor_epoch, anchor_root)
        store = ForkChoiceStore(
            current_slot=genesis_state.slot,
            justified_checkpoint=cp,
            finalized_checkpoint=cp,
            justified_balances=balances,
        )
        self.fork_choice = ForkChoice(
            store,
            ProtoNode(
                slot=genesis_state.slot,
                block_root=anchor_root,
                parent_root=None,
                state_root=header.state_root,
                target_root=anchor_root,
                justified_epoch=anchor_epoch,
                finalized_epoch=anchor_epoch,
            ),
            proposer_boost_pct=cfg.PROPOSER_SCORE_BOOST,
        )
        # state caches (stateCache/stateContextCache.ts analog, simple dict v1)
        self.states_by_block_root: Dict[bytes, object] = {anchor_root: genesis_state}
        self.ctx_by_block_root: Dict[bytes, EpochContext] = {}
        self.head_root = anchor_root
        self.blocks: Dict[bytes, object] = {}

    # -- queries --------------------------------------------------------------

    def head_state(self):
        return self.states_by_block_root[self.head_root]

    def get_state_by_block_root(self, root: bytes):
        return self.states_by_block_root.get(root)

    # -- block import (verifyBlock + importBlock) ------------------------------

    async def process_block(self, signed_block, *, proposer_sig_verified: bool = False) -> bytes:
        from ..state_transition.upgrade import block_types

        t0 = time.monotonic()
        block = signed_block.message
        block_root = block_types(self.p, block).BeaconBlock.hash_tree_root(block)

        # sanity (verifyBlockSanityChecks, verifyBlock.ts:80-121)
        if self.fork_choice.has_block(block_root):
            return block_root  # duplicate import is a no-op
        parent_root = bytes(block.parent_root)
        if not self.fork_choice.has_block(parent_root):
            raise BlockError(f"unknown parent {parent_root.hex()}")
        pre_state = self.states_by_block_root.get(parent_root)
        if pre_state is None:
            raise BlockError("missing pre-state for parent (regen not available)")

        # STF with all signature checks deferred (verifyBlock.ts:152)
        post, ctx = state_transition(
            self.p,
            self.cfg,
            pre_state,
            signed_block,
            verify_proposer_signature=False,
            verify_signatures=False,
            verify_state_root=True,
        )

        # one batched signature verification (verifyBlock.ts:177-190)
        pre_at_slot = clone_state(self.p, pre_state)
        pre_ctx = process_slots(self.p, self.cfg, pre_at_slot, block.slot)
        sets = get_block_signature_sets(
            self.p, self.cfg, pre_ctx, pre_at_slot, signed_block,
            include_proposer=not proposer_sig_verified,
        )
        if sets and not await self.bls.verify_signature_sets(sets):
            raise BlockError("block signature sets failed batch verification")

        # import (importBlock.ts:76)
        target_epoch = compute_epoch_at_slot(self.p, block.slot)
        target_root = self._target_root(post, block_root, target_epoch)
        justified = Checkpoint(
            post.current_justified_checkpoint.epoch, bytes(post.current_justified_checkpoint.root)
        )
        finalized = Checkpoint(
            post.finalized_checkpoint.epoch, bytes(post.finalized_checkpoint.root)
        )
        balances = np.array([v.effective_balance for v in post.validators], dtype=np.int64)
        old_finalized = self.fork_choice.store.finalized_checkpoint.epoch
        self.fork_choice.on_block(
            block.slot,
            block_root,
            parent_root,
            bytes(block.state_root),
            target_root,
            justified,
            finalized,
            justified_balances=balances,
            is_timely_proposal=self._is_timely_proposal(block.slot),
        )
        # per-attestation fork-choice votes (importBlock.ts:144)
        for att in block.body.attestations:
            try:
                indices = pre_ctx.get_attesting_indices(att.data, att.aggregation_bits)
            except ValueError:
                continue
            if self.fork_choice.has_block(bytes(att.data.beacon_block_root)):
                self.fork_choice.on_attestation(
                    indices, bytes(att.data.beacon_block_root), att.data.target.epoch
                )

        self.states_by_block_root[block_root] = post
        self.ctx_by_block_root[block_root] = ctx
        self.blocks[block_root] = signed_block

        old_head = self.head_root
        self.head_root = self.fork_choice.update_head()
        self.emitter.emit(ChainEvent.BLOCK, signed_block, block_root)
        if self.head_root != old_head:
            self.emitter.emit(ChainEvent.HEAD, self.head_root)
        if finalized.epoch > old_finalized:
            self.emitter.emit(ChainEvent.FINALIZED, finalized)
        if self.metrics:
            self.metrics.block_processing_seconds.observe(time.monotonic() - t0)
            self.metrics.head_slot.set(block.slot)
            self.metrics.finalized_epoch.set(finalized.epoch)
        return block_root

    def _is_timely_proposal(self, block_slot: int) -> bool:
        """Proposer boost gate (forkChoice onBlock): only a block for the
        CURRENT clock slot arriving before the attestation deadline
        (SECONDS_PER_SLOT / INTERVALS_PER_SLOT into the slot) earns the
        boost.  Late blocks and replayed old blocks (sync) must not — the
        ~40% committee-weight boost would otherwise be reorg-exploitable."""
        from ..params import INTERVALS_PER_SLOT

        if self.clock is None:
            return False
        if block_slot != self.clock.current_slot:
            return False
        return self.clock.seconds_into_slot() < self.cfg.SECONDS_PER_SLOT / INTERVALS_PER_SLOT

    def _target_root(self, post, block_root: bytes, target_epoch: int) -> bytes:
        boundary_slot = compute_start_slot_at_epoch(self.p, target_epoch)
        if boundary_slot >= post.slot:
            return block_root
        return bytes(post.block_roots[boundary_slot % self.p.SLOTS_PER_HISTORICAL_ROOT])

    # -- block production (chain/factory/block/index.ts:21) --------------------

    G2_INFINITY_SIG = b"\xc0" + b"\x00" * 95

    def produce_block_body(self, fork, attestations: Sequence = (), sync_aggregate=None) -> object:
        from ..config.fork_config import ForkName
        from ..types import get_types

        t = getattr(get_types(self.p), fork.value)
        body = t.BeaconBlockBody.default()
        body.attestations = list(attestations)
        if fork != ForkName.phase0:
            body.sync_aggregate = sync_aggregate or Fields(
                sync_committee_bits=[False] * self.p.SYNC_COMMITTEE_SIZE,
                sync_committee_signature=self.G2_INFINITY_SIG,
            )
        return body

    def produce_block(
        self, slot: int, randao_reveal: bytes, attestations: Sequence = (), sync_aggregate=None
    ):
        """Assemble an unsigned block on top of the current head, using the
        body shape of the fork active at `slot`."""
        from ..state_transition.upgrade import state_types

        head_state = self.head_state()
        pre = clone_state(self.p, head_state)
        ctx = process_slots(self.p, self.cfg, pre, slot)
        proposer = ctx.get_beacon_proposer(slot)
        fork = self.fork_config.get_fork_info_at_epoch(
            compute_epoch_at_slot(self.p, slot)
        ).name
        body = self.produce_block_body(fork, attestations, sync_aggregate)
        body.randao_reveal = randao_reveal
        body.eth1_data = pre.eth1_data
        block = Fields(
            slot=slot,
            proposer_index=proposer,
            parent_root=self.t.BeaconBlockHeader.hash_tree_root(pre.latest_block_header),
            state_root=b"\x00" * 32,
            body=body,
        )
        unsigned = Fields(message=block, signature=b"\x00" * 96)
        post, _ = state_transition(
            self.p, self.cfg, head_state, unsigned,
            verify_proposer_signature=False, verify_signatures=False, verify_state_root=False,
        )
        block.state_root = state_types(self.p, post).BeaconState.hash_tree_root(post)
        return block, proposer
