"""Seen caches: first-seen dedup for gossip objects.

Reference: packages/beacon-node/src/chain/seenCache/ (SURVEY §2.4):
SeenAttesters / SeenAggregators (per-epoch validator sets),
SeenBlockProposers (per-slot), SeenAggregatedAttestations (superset dedup),
SeenSyncCommitteeMessages, SeenBlockAttesters (liveness tracking).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple


class SeenEpochValidators:
    """Epoch -> set of validator indices (seenAttesters.ts base).  Prunes
    epochs older than `retention` behind the latest seen."""

    def __init__(self, retention: int = 2):
        self.retention = retention
        self._by_epoch: Dict[int, Set[int]] = {}
        self._max_epoch = 0

    def is_known(self, epoch: int, index: int) -> bool:
        return index in self._by_epoch.get(epoch, ())

    def add(self, epoch: int, index: int) -> None:
        self._by_epoch.setdefault(epoch, set()).add(index)
        if epoch > self._max_epoch:
            self._max_epoch = epoch
            self.prune()

    def prune(self) -> None:
        low = self._max_epoch - self.retention
        for e in list(self._by_epoch):
            if e < low:
                del self._by_epoch[e]


SeenAttesters = SeenEpochValidators
SeenAggregators = SeenEpochValidators


class SeenBlockProposers:
    """Slot -> proposer indices that already proposed (seenBlockProposers.ts);
    equivocation guard for gossip blocks."""

    def __init__(self, retention_slots: int = 64):
        self.retention = retention_slots
        self._by_slot: Dict[int, Set[int]] = {}
        self._max_slot = 0

    def is_known(self, slot: int, proposer: int) -> bool:
        return proposer in self._by_slot.get(slot, ())

    def add(self, slot: int, proposer: int) -> None:
        self._by_slot.setdefault(slot, set()).add(proposer)
        if slot > self._max_slot:
            self._max_slot = slot
            for s in list(self._by_slot):
                if s < self._max_slot - self.retention:
                    del self._by_slot[s]


class SeenAggregatedAttestations:
    """data-root -> list of seen aggregation-bit sets; an incoming aggregate
    is redundant iff its bits are a NON-STRICT SUBSET of one already seen
    (seenAggregateAndProof.ts non-strict-superset dedup)."""

    MAX_PER_ROOT = 8

    def __init__(self, retention_epochs: int = 2):
        self._by_epoch: Dict[int, Dict[bytes, List[Tuple[bool, ...]]]] = {}
        self._max_epoch = 0
        self.retention = retention_epochs

    def is_known(self, target_epoch: int, data_root: bytes, bits) -> bool:
        seen = self._by_epoch.get(target_epoch, {}).get(data_root, [])
        bits = tuple(bits)
        for s in seen:
            if len(s) == len(bits) and all(not b or e for b, e in zip(bits, s)):
                return True
        return False

    def add(self, target_epoch: int, data_root: bytes, bits) -> None:
        lst = self._by_epoch.setdefault(target_epoch, {}).setdefault(data_root, [])
        bits = tuple(bits)
        # drop subsets of the new bits
        lst[:] = [s for s in lst if not all(not e or b for e, b in zip(s, bits))]
        lst.append(bits)
        del lst[: max(0, len(lst) - self.MAX_PER_ROOT)]
        if target_epoch > self._max_epoch:
            self._max_epoch = target_epoch
            for e in list(self._by_epoch):
                if e < self._max_epoch - self.retention:
                    del self._by_epoch[e]


class SeenSyncCommitteeMessages:
    """(slot, subnet, validator) first-seen (seenCommittee.ts)."""

    def __init__(self, retention_slots: int = 8):
        self._by_slot: Dict[int, Set[Tuple[int, int]]] = {}
        self._max_slot = 0
        self.retention = retention_slots

    def is_known(self, slot: int, subnet: int, index: int) -> bool:
        return (subnet, index) in self._by_slot.get(slot, ())

    def add(self, slot: int, subnet: int, index: int) -> None:
        self._by_slot.setdefault(slot, set()).add((subnet, index))
        if slot > self._max_slot:
            self._max_slot = slot
            for s in list(self._by_slot):
                if s < self._max_slot - self.retention:
                    del self._by_slot[s]


class SeenContributions:
    """Slot-keyed first-seen set for (slot, aggregator, subcommittee)
    contribution keys (seenContributionAndProof.ts) with the same bounded
    retention as SeenSyncCommitteeMessages — an unbounded set would leak
    one entry per contribution for the node's whole uptime."""

    def __init__(self, retention_slots: int = 8):
        self._by_slot: Dict[int, Set[tuple]] = {}
        self._max_slot = 0
        self.retention = retention_slots

    def __contains__(self, key: tuple) -> bool:
        return key in self._by_slot.get(int(key[0]), ())

    def add(self, key: tuple) -> None:
        slot = int(key[0])
        self._by_slot.setdefault(slot, set()).add(key)
        if slot > self._max_slot:
            self._max_slot = slot
            for s in list(self._by_slot):
                if s < self._max_slot - self.retention:
                    del self._by_slot[s]


class SeenBlockAttesters(SeenEpochValidators):
    """Validators whose attestations appeared in blocks — liveness data for
    the doppelganger check (seenBlockAttesters.ts)."""
