"""Gossip handler map: topic object -> validate -> route into chain state.

Reference: packages/beacon-node/src/network/processor/gossipHandlers.ts
(:72-291): each handler runs the pure validation function from
chain/validation, then applies the accepted object — attestations into the
naive pool + fork-choice votes, aggregates into the aggregated pool,
blocks into BeaconChain.process_block, slashings/exits into the op pool.

The transport (network/gossip) delivers raw objects here; the handlers are
transport-agnostic so in-process tests and the wire path share them.
"""

from __future__ import annotations

from typing import List, Optional

from ..config.chain_config import ChainConfig
from ..params import Preset
from ..state_transition import clone_state, process_slots
from ..utils.logger import get_logger
from .beacon_chain import BeaconChain
from .seen_cache import (
    SeenAggregatedAttestations,
    SeenAggregators,
    SeenAttesters,
    SeenBlockProposers,
)
from .validation import (
    GossipAction,
    GossipValidationError,
    validate_gossip_aggregate_and_proof,
    validate_gossip_attestation,
    validate_gossip_attester_slashing,
    validate_gossip_block,
    validate_gossip_proposer_slashing,
    validate_gossip_voluntary_exit,
)

logger = get_logger("gossip-handlers")


class GossipHandlers:
    """Validated-object router bound to one BeaconChain."""

    def __init__(self, chain: BeaconChain):
        self.chain = chain
        self.p: Preset = chain.p
        self.cfg: ChainConfig = chain.cfg
        self.seen_attesters = SeenAttesters()
        self.seen_aggregators = SeenAggregators()
        self.seen_aggregates = SeenAggregatedAttestations()
        self.seen_proposers = SeenBlockProposers()
        from .seen_cache import SeenContributions, SeenSyncCommitteeMessages

        self.seen_sync_msgs = SeenSyncCommitteeMessages()
        self.seen_contributions = SeenContributions()

    # -- helpers ---------------------------------------------------------------

    def _head_ctx_state(self, slot: int):
        """Head state advanced to `slot` for committee lookups (the
        reference uses the wall-clock state via regen; head-at-slot is the
        same state for canonical gossip).

        Memoized per (head_root, slot): gossip bursts validate hundreds of
        objects against the same dial state, and a full state clone +
        slot advance per message is the exact DoS shape ADVICE r2 flagged
        for the exit validator."""
        key = (self.chain.head_root, slot)
        if getattr(self, "_ctx_memo_key", None) == key:
            return self._ctx_memo_val
        state = clone_state(self.p, self.chain.head_state())
        if state.slot < slot:
            ctx = process_slots(self.p, self.cfg, state, slot)
        else:
            ctx = self.chain.ctx_by_block_root.get(self.chain.head_root)
            if ctx is None:
                from ..state_transition import EpochContext

                ctx = EpochContext.create_from_state(self.p, state)
        self._ctx_memo_key = key
        self._ctx_memo_val = (ctx, state)
        return ctx, state

    def _clock_slot(self) -> int:
        return self.chain.clock.current_slot if self.chain.clock else self.chain.head_state().slot

    # -- handlers (gossipHandlers.ts:72) ---------------------------------------

    async def on_attestation(self, attestation, subnet: Optional[int] = None) -> List[int]:
        data = attestation.data
        ctx, state = self._head_ctx_state(data.slot)
        indices = await validate_gossip_attestation(
            self.p,
            self.cfg,
            attestation=attestation,
            subnet=subnet,
            clock_slot=self._clock_slot(),
            fork_choice=self.chain.fork_choice,
            seen_attesters=self.seen_attesters,
            ctx=ctx,
            state=state,
            pool=self.chain.bls,
        )
        self.chain.att_pool.add(attestation)
        if self.chain.fork_choice.has_block(bytes(data.beacon_block_root)):
            self.chain.fork_choice.on_attestation(
                indices, bytes(data.beacon_block_root), data.target.epoch
            )
        return indices

    async def on_aggregate_and_proof(self, signed_aggregate) -> List[int]:
        aggregate = signed_aggregate.message.aggregate
        ctx, state = self._head_ctx_state(aggregate.data.slot)
        indices = await validate_gossip_aggregate_and_proof(
            self.p,
            self.cfg,
            signed_aggregate=signed_aggregate,
            clock_slot=self._clock_slot(),
            fork_choice=self.chain.fork_choice,
            seen_aggregators=self.seen_aggregators,
            seen_aggregates=self.seen_aggregates,
            ctx=ctx,
            state=state,
            pool=self.chain.bls,
        )
        self.chain.agg_pool.add(aggregate)
        if self.chain.fork_choice.has_block(bytes(aggregate.data.beacon_block_root)):
            self.chain.fork_choice.on_attestation(
                indices, bytes(aggregate.data.beacon_block_root), aggregate.data.target.epoch
            )
        return indices

    async def on_block(self, signed_block) -> bytes:
        block = signed_block.message
        ctx, state = self._head_ctx_state(block.slot)
        await validate_gossip_block(
            self.p,
            self.cfg,
            signed_block=signed_block,
            clock_slot=self._clock_slot(),
            fork_choice=self.chain.fork_choice,
            seen_block_proposers=self.seen_proposers,
            ctx=ctx,
            state=state,
            pool=self.chain.bls,
            clock=self.chain.clock,
        )
        return await self.chain.process_block(signed_block, proposer_sig_verified=True)

    async def on_voluntary_exit(self, signed_exit) -> None:
        ctx, state = self._head_ctx_state(self.chain.head_state().slot)
        await validate_gossip_voluntary_exit(
            self.p, self.cfg, signed_exit=signed_exit, ctx=ctx, state=state,
            pool=self.chain.bls, op_pool=self.chain.op_pool,
        )
        self.chain.op_pool.add_voluntary_exit(signed_exit)

    async def on_proposer_slashing(self, slashing) -> None:
        ctx, state = self._head_ctx_state(self.chain.head_state().slot)
        await validate_gossip_proposer_slashing(
            self.p, self.cfg, slashing=slashing, ctx=ctx, state=state,
            pool=self.chain.bls, op_pool=self.chain.op_pool,
        )
        self.chain.op_pool.add_proposer_slashing(slashing)

    async def on_attester_slashing(self, slashing) -> None:
        ctx, state = self._head_ctx_state(self.chain.head_state().slot)
        await validate_gossip_attester_slashing(
            self.p, self.cfg, slashing=slashing, ctx=ctx, state=state,
            pool=self.chain.bls, op_pool=self.chain.op_pool,
        )
        self.chain.op_pool.add_attester_slashing(slashing)

    # -- altair sync-committee traffic (gossipHandlers.ts syncCommittee*) ------

    async def on_sync_committee_message(self, message, subnet: int) -> None:
        from .sync_committee_pools import validate_sync_committee_message

        ctx, state = self._head_ctx_state(self._clock_slot())
        index_in_sub = await validate_sync_committee_message(
            self.p, self.cfg, message=message, subnet=subnet,
            clock_slot=self._clock_slot(), state=state, ctx=ctx,
            seen_sync_msgs=self.seen_sync_msgs, pool=self.chain.bls,
        )
        self.chain.sync_msg_pool.add(
            message.slot, bytes(message.beacon_block_root), subnet,
            index_in_sub, bytes(message.signature),
        )

    async def on_sync_contribution(self, signed_contribution) -> None:
        from .sync_committee_pools import validate_sync_committee_contribution

        ctx, state = self._head_ctx_state(self._clock_slot())
        await validate_sync_committee_contribution(
            self.p, self.cfg, signed_contribution=signed_contribution,
            clock_slot=self._clock_slot(), state=state, ctx=ctx,
            seen_contributions=self.seen_contributions, pool=self.chain.bls,
        )
        self.chain.contribution_pool.add(signed_contribution.message.contribution)
