"""State regeneration + state caches.

Reference: packages/beacon-node/src/chain/regen/ (QueuedStateRegenerator:27 /
StateRegenerator) and chain/stateCache/ (StateContextCache LRU max 96,
CheckpointStateCache).

Regen answers "give me the state at X" from caches first, else by replaying
blocks from the nearest cached ancestor state (regen.ts getState flow).
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Tuple

from ..config.chain_config import ChainConfig
from ..params import Preset
from ..state_transition import clone_state, process_slots, state_transition
from ..types import get_types


class RegenError(Exception):
    pass


class StateContextCache:
    """block-root -> post-state LRU (stateContextCache.ts, MAX_STATES=96)."""

    MAX_STATES = 96

    def __init__(self, max_states: int = MAX_STATES):
        self.max_states = max_states
        self._map: "collections.OrderedDict[bytes, object]" = collections.OrderedDict()

    def get(self, block_root: bytes):
        state = self._map.get(block_root)
        if state is not None:
            self._map.move_to_end(block_root)
        return state

    def add(self, block_root: bytes, state) -> None:
        self._map[block_root] = state
        self._map.move_to_end(block_root)
        while len(self._map) > self.max_states:
            self._map.popitem(last=False)

    def delete(self, block_root: bytes) -> None:
        self._map.pop(block_root, None)

    def __len__(self):
        return len(self._map)


class CheckpointStateCache:
    """(epoch, root) -> epoch-boundary state (stateContextCheckpointsCache.ts)."""

    MAX = 64

    def __init__(self):
        self._map: "collections.OrderedDict[Tuple[int, bytes], object]" = collections.OrderedDict()

    def get(self, epoch: int, root: bytes):
        return self._map.get((epoch, root))

    def add(self, epoch: int, root: bytes, state) -> None:
        self._map[(epoch, root)] = state
        while len(self._map) > self.MAX:
            self._map.popitem(last=False)

    def prune_finalized(self, finalized_epoch: int) -> None:
        for k in list(self._map):
            if k[0] < finalized_epoch:
                del self._map[k]


class StateRegenerator:
    """getPreState / getBlockSlotState / getState (regen.ts), replaying from
    block storage when the cache misses."""

    def __init__(self, preset: Preset, cfg: ChainConfig, block_source, state_cache: StateContextCache, metrics=None):
        self.p = preset
        self.cfg = cfg
        self.blocks = block_source  # mapping block_root -> SignedBeaconBlock
        self.cache = state_cache
        self.metrics = metrics
        self.t = get_types(preset).phase0

    def get_state_by_block_root(self, block_root: bytes, max_replay: int = 32):
        """State after applying the block at `block_root` (getState)."""
        import time

        cached = self.cache.get(block_root)
        if cached is not None:
            if self.metrics:
                self.metrics.state_cache_hits_total.inc()
            return cached
        if self.metrics:
            self.metrics.state_cache_misses_total.inc()
        t0 = time.monotonic()
        # walk back to a cached ancestor, replaying forward
        chain: List[object] = []
        root = block_root
        while True:
            block = self.blocks.get(root)
            if block is None:
                raise RegenError(f"block {root.hex()[:12]} not available for replay")
            chain.append(block)
            if len(chain) > max_replay:
                raise RegenError("replay distance exceeded")
            parent = bytes(block.message.parent_root)
            state = self.cache.get(parent)
            if state is not None:
                break
            root = parent
        if self.metrics:
            self.metrics.regen_replays_total.inc(len(chain))
        for block in reversed(chain):
            state, _ = state_transition(
                self.p, self.cfg, state, block,
                verify_proposer_signature=False,
                verify_signatures=False,
                verify_state_root=True,
            )
            broot = self.t.BeaconBlock.hash_tree_root(block.message)
            self.cache.add(broot, state)
        if self.metrics:
            self.metrics.regen_seconds.observe(time.monotonic() - t0)
        return state

    def get_pre_state(self, block) -> object:
        """Pre-state for importing `block` (getPreState): parent post-state
        advanced to the block's slot is the caller's job (STF does it)."""
        return self.get_state_by_block_root(bytes(block.message.parent_root))

    def get_block_slot_state(self, block_root: bytes, slot: int):
        state = self.get_state_by_block_root(block_root)
        if state.slot > slot:
            raise RegenError("requested slot is before the block's state")
        if state.slot == slot:
            return state
        out = clone_state(self.p, state)
        process_slots(self.p, self.cfg, out, slot)
        return out
