"""Gossip validation: per-type spec checks -> signature sets -> batched
verification verdicts.

Reference: packages/beacon-node/src/chain/validation/ (attestation.ts:15,
aggregateAndProof.ts, voluntaryExit.ts, proposerSlashing.ts,
attesterSlashing.ts) and the gossip-block checks in
network/gossip/handlers/index.ts:90.  Typed IGNORE/REJECT outcomes mirror
GossipAction; every accepted object has flowed through
``pool.verify_signature_sets`` (chain.bls.verifySignatureSets analog,
{batchable: true} for small jobs — attestation.ts:138).

Dependencies are explicit (clock/fork_choice/seen caches/ctx/pool) so unit
tests can drive them without a full node (the reference mocks IBeaconChain
the same way, test/utils/mocks/chain.ts).
"""

from __future__ import annotations

import enum
import time
from typing import List, Optional, Sequence

from ..config.chain_config import ChainConfig
from ..params import DOMAIN_AGGREGATE_AND_PROOF, DOMAIN_SELECTION_PROOF, Preset
from ..ssz import Fields, uint64
from ..state_transition import compute_epoch_at_slot, compute_signing_root, get_domain
from ..state_transition.block import is_slashable_attestation_data, is_slashable_validator
from ..state_transition.signature_sets import (
    attester_slashing_signature_sets,
    indexed_attestation_signature_set,
    proposer_slashing_signature_sets,
    voluntary_exit_signature_set,
)
from ..crypto.bls.verifier import (
    SignatureSetPriority,
    SingleSignatureSet,
    VerificationDroppedError,
)
from ..types import get_types


class GossipAction(str, enum.Enum):
    IGNORE = "IGNORE"
    REJECT = "REJECT"


class GossipValidationError(Exception):
    def __init__(self, action: GossipAction, code: str):
        super().__init__(f"{action.value}: {code}")
        self.action = action
        self.code = code


def _reject(code: str):
    raise GossipValidationError(GossipAction.REJECT, code)


def _ignore(code: str):
    raise GossipValidationError(GossipAction.IGNORE, code)


async def _pool_verify(pool, sets, *, batchable=True, priority=None, deadline=None):
    """pool.verify_signature_sets with the QoS lane + deadline threaded
    through and the overload contract applied: a job the pool SHED
    (deadline expiry, overflow eviction — VerificationDroppedError) maps
    to IGNORE, never REJECT — the node's own admission decision must not
    downscore the relaying peer or mark the message invalid.

    Plain verifiers that predate the ``priority`` kwarg (test doubles,
    IBlsVerifier facades) are driven through the legacy signature."""
    try:
        coro = pool.verify_signature_sets(
            sets, batchable=batchable, priority=priority, deadline=deadline
        )
    except TypeError:  # pool without QoS lanes: legacy signature
        coro = pool.verify_signature_sets(sets, batchable=batchable)
    try:
        return await coro
    except VerificationDroppedError:
        _ignore("VERIFICATION_DROPPED")


def _storm_deadline(cfg: ChainConfig) -> float:
    """Deadline stamped on storm-lane gossip jobs (single attestations,
    per-subnet sync-committee messages): one slot from intake.  Their
    propagation value decays within the slot — a job still buffered a
    full slot later is stale backlog the flusher sheds instead of burning
    device time on (docs/overload.md §Deadline shedding)."""
    return time.monotonic() + cfg.SECONDS_PER_SLOT


async def validate_gossip_attestation(
    p: Preset,
    cfg: ChainConfig,
    *,
    attestation,
    subnet: Optional[int],
    clock_slot: int,
    fork_choice,
    seen_attesters,
    ctx,
    state,
    pool,
) -> List[int]:
    """Returns the attesting indices on acceptance (attestation.ts:15).

    Reference checks in order: slot window, single-bit, known block root,
    committee lookup, first-seen dedup, signature (batchable single set).
    """
    data = attestation.data
    target_epoch = data.target.epoch
    att_slot = data.slot
    if target_epoch != compute_epoch_at_slot(p, att_slot):
        _reject("BAD_TARGET_EPOCH")
    # ATTESTATION_PROPAGATION_SLOT_RANGE = 32 with clock disparity
    if not (att_slot <= clock_slot <= att_slot + 32):
        _ignore("INVALID_SLOT_TIME")
    bits = list(attestation.aggregation_bits)
    if sum(bits) != 1:
        _reject("NOT_EXACTLY_ONE_BIT_SET")
    if not fork_choice.has_block(bytes(data.beacon_block_root)):
        _ignore("UNKNOWN_BEACON_BLOCK_ROOT")
    _verify_head_block_and_target_root(p, fork_choice, data)
    if data.index >= ctx.get_committee_count_per_slot(target_epoch):
        _reject("COMMITTEE_INDEX_OUT_OF_RANGE")
    committee = ctx.get_beacon_committee(att_slot, data.index)
    if len(bits) != len(committee):
        _reject("WRONG_NUMBER_OF_AGGREGATION_BITS")
    attester = int(committee[bits.index(True)])
    if seen_attesters.is_known(target_epoch, attester):
        _ignore("ATTESTATION_ALREADY_KNOWN")

    indexed = ctx.get_indexed_attestation(attestation)
    sig_set = indexed_attestation_signature_set(p, ctx, state, indexed)
    if not await _pool_verify(
        pool, [sig_set], batchable=True,
        priority=SignatureSetPriority.UNAGGREGATED,
        deadline=_storm_deadline(cfg),
    ):
        _reject("INVALID_SIGNATURE")
    # re-check after the async hop (attestation.ts:142-153 race guard)
    if seen_attesters.is_known(target_epoch, attester):
        _ignore("ATTESTATION_ALREADY_KNOWN")
    seen_attesters.add(target_epoch, attester)
    return [attester]


def _verify_head_block_and_target_root(p: Preset, fork_choice, data) -> None:
    """verifyHeadBlockAndTargetRoot (chain/validation/attestation.ts): the
    attested head block must not be newer than the attestation slot, and the
    attestation's target root must be the epoch-boundary ancestor of the
    head block — otherwise the attestation's vote is internally inconsistent
    and must be REJECTed (not re-gossiped).  Caller has already established
    has_block(beacon_block_root).  Descent from the finalized checkpoint is
    implied: proto-array pruning keeps only finalized descendants."""
    head_root = bytes(data.beacon_block_root)
    head_block = fork_choice.get_block(head_root)
    if head_block.slot > data.slot:
        _reject("HEAD_BLOCK_AFTER_ATTESTATION_SLOT")
    target_start_slot = data.target.epoch * p.SLOTS_PER_EPOCH
    if head_block.slot >= target_start_slot:
        # target must be the head block's own chain checkpoint
        expected = fork_choice.get_ancestor(head_root, target_start_slot)
    else:
        # head is from a prior epoch: target checkpoint root IS the head
        expected = head_root
    if expected != bytes(data.target.root):
        _reject("BAD_TARGET_ROOT")


def is_aggregator(p: Preset, committee_len: int, selection_proof: bytes) -> bool:
    """isAggregatorFromCommitteeLength (state-transition util/aggregator.ts):
    sha256(proof) little-endian uint64 % (committee_len // 16 or 1) == 0."""
    import hashlib

    from ..params.presets import TARGET_AGGREGATORS_PER_COMMITTEE

    modulo = max(1, committee_len // TARGET_AGGREGATORS_PER_COMMITTEE)
    digest = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(digest[:8], "little") % modulo == 0


async def validate_gossip_aggregate_and_proof(
    p: Preset,
    cfg: ChainConfig,
    *,
    signed_aggregate,
    clock_slot: int,
    fork_choice,
    seen_aggregators,
    seen_aggregates,
    ctx,
    state,
    pool,
) -> List[int]:
    """Three signature sets in one batchable job: selection proof,
    aggregator signature, aggregated attestation (aggregateAndProof.ts)."""
    t = get_types(p).phase0
    aggregate_and_proof = signed_aggregate.message
    aggregate = aggregate_and_proof.aggregate
    data = aggregate.data
    target_epoch = data.target.epoch
    if target_epoch != compute_epoch_at_slot(p, data.slot):
        _reject("BAD_TARGET_EPOCH")
    if not (data.slot <= clock_slot <= data.slot + 32):
        _ignore("INVALID_SLOT_TIME")
    aggregator = aggregate_and_proof.aggregator_index
    if seen_aggregators.is_known(target_epoch, aggregator):
        _ignore("AGGREGATOR_ALREADY_KNOWN")
    data_root = t.AttestationData.hash_tree_root(data)
    if seen_aggregates.is_known(target_epoch, data_root, aggregate.aggregation_bits):
        _ignore("AGGREGATE_ALREADY_KNOWN")
    if not fork_choice.has_block(bytes(data.beacon_block_root)):
        _ignore("UNKNOWN_BEACON_BLOCK_ROOT")
    _verify_head_block_and_target_root(p, fork_choice, data)
    committee = ctx.get_beacon_committee(data.slot, data.index)
    if aggregator not in [int(x) for x in committee]:
        _reject("AGGREGATOR_NOT_IN_COMMITTEE")
    if not is_aggregator(p, len(committee), bytes(aggregate_and_proof.selection_proof)):
        _reject("INVALID_AGGREGATOR")

    slot_domain = get_domain(p, state, DOMAIN_SELECTION_PROOF, target_epoch)
    selection_set = SingleSignatureSet(
        pubkey=ctx.index2pubkey[aggregator],
        signing_root=compute_signing_root(p, uint64, data.slot, slot_domain),
        signature=bytes(aggregate_and_proof.selection_proof),
    )
    agg_domain = get_domain(p, state, DOMAIN_AGGREGATE_AND_PROOF, target_epoch)
    aggregator_set = SingleSignatureSet(
        pubkey=ctx.index2pubkey[aggregator],
        signing_root=compute_signing_root(p, t.AggregateAndProof, aggregate_and_proof, agg_domain),
        signature=bytes(signed_aggregate.signature),
    )
    indexed = ctx.get_indexed_attestation(aggregate)
    att_set = indexed_attestation_signature_set(p, ctx, state, indexed)
    if not await _pool_verify(
        pool, [selection_set, aggregator_set, att_set], batchable=True,
        priority=SignatureSetPriority.AGGREGATE,
    ):
        _reject("INVALID_SIGNATURE")
    seen_aggregators.add(target_epoch, aggregator)
    seen_aggregates.add(target_epoch, data_root, aggregate.aggregation_bits)
    return list(indexed.attesting_indices)


async def validate_gossip_block(
    p: Preset,
    cfg: ChainConfig,
    *,
    signed_block,
    clock_slot: int,
    fork_choice,
    seen_block_proposers,
    ctx,
    state,
    pool,
    clock=None,
) -> None:
    """Gossip beacon_block checks (gossip/handlers/index.ts:90): slot not
    future (with MAXIMUM_GOSSIP_CLOCK_DISPARITY tolerance when a clock is
    supplied), not finalized-old, descends from the finalized checkpoint,
    first proposal for (slot, proposer), parent known, proposer signature
    (verified on the spot — the reference uses blsVerifyOnMainThread to
    keep gossip latency low; a non-batchable dispatch is the analog)."""
    from ..state_transition.signature_sets import block_proposer_signature_set

    block = signed_block.message
    if block.slot > clock_slot:
        # allow the standard 500 ms clock disparity for blocks broadcast
        # just before their slot starts (gossip/handlers/index.ts clock use)
        if clock is None or not clock.is_current_slot_given_disparity(block.slot):
            _ignore("FUTURE_SLOT")
    finalized = fork_choice.store.finalized_checkpoint
    finalized_slot = finalized.epoch * p.SLOTS_PER_EPOCH
    if block.slot <= finalized_slot:
        _ignore("WOULD_REVERT_FINALIZED_SLOT")
    if seen_block_proposers.is_known(block.slot, block.proposer_index):
        _ignore("REPEAT_PROPOSAL")
    if not fork_choice.has_block(bytes(block.parent_root)):
        _ignore("PARENT_UNKNOWN")
    # a known parent at a non-finalized slot can still sit on a pruned-out
    # branch: require actual descent from the finalized checkpoint root
    if fork_choice.has_block(finalized.root) and not fork_choice.is_descendant(
        finalized.root, bytes(block.parent_root)
    ):
        _reject("NOT_FINALIZED_DESCENDANT")
    expected_proposer = ctx.get_beacon_proposer(block.slot)
    if block.proposer_index != expected_proposer:
        _reject("INCORRECT_PROPOSER")
    sig_set = block_proposer_signature_set(p, ctx, state, signed_block)
    if not await _pool_verify(
        pool, [sig_set], batchable=False,
        priority=SignatureSetPriority.BLOCK_PROPOSAL,
    ):
        _reject("PROPOSAL_SIGNATURE_INVALID")
    seen_block_proposers.add(block.slot, block.proposer_index)


async def validate_gossip_voluntary_exit(
    p: Preset, cfg: ChainConfig, *, signed_exit, ctx, state, pool, op_pool
) -> None:
    exit_msg = signed_exit.message
    idx = exit_msg.validator_index
    if idx in op_pool.voluntary_exits:
        _ignore("ALREADY_EXISTS")
    # read-only validity predicate — the reference's isValidVoluntaryExit
    # with verifySignature=false never mutates state; a deepcopy dry-run
    # here would copy the whole state per gossip message (DoS vector)
    from ..params.presets import FAR_FUTURE_EPOCH
    from ..state_transition.misc import is_active_validator

    if idx >= len(state.validators):
        _reject("INVALID_EXIT")
    v = state.validators[idx]
    current_epoch = compute_epoch_at_slot(p, state.slot)
    if (
        not is_active_validator(v, current_epoch)
        or v.exit_epoch != FAR_FUTURE_EPOCH
        or current_epoch < exit_msg.epoch
        or current_epoch < v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD
    ):
        _reject("INVALID_EXIT")
    # exits (like slashings below) are rare, irreplaceable op-pool
    # messages gossip never sheds at intake: ride the AGGREGATE lane so
    # the overflow policy can't sacrifice them to storm traffic
    if not await _pool_verify(
        pool, [voluntary_exit_signature_set(p, ctx, state, signed_exit)],
        batchable=True, priority=SignatureSetPriority.AGGREGATE,
    ):
        _reject("INVALID_SIGNATURE")


async def validate_gossip_proposer_slashing(
    p: Preset, cfg: ChainConfig, *, slashing, ctx, state, pool, op_pool
) -> None:
    idx = slashing.signed_header_1.message.proposer_index
    if idx in op_pool.proposer_slashings:
        _ignore("ALREADY_EXISTS")
    h1, h2 = slashing.signed_header_1.message, slashing.signed_header_2.message
    t = get_types(p).phase0
    if h1.slot != h2.slot or h1.proposer_index != h2.proposer_index:
        _reject("HEADERS_NOT_SLASHABLE")
    if t.BeaconBlockHeader.serialize(h1) == t.BeaconBlockHeader.serialize(h2):
        _reject("HEADERS_EQUAL")
    if not is_slashable_validator(state.validators[idx], compute_epoch_at_slot(p, state.slot)):
        _reject("NOT_SLASHABLE")
    if not await _pool_verify(
        pool, proposer_slashing_signature_sets(p, ctx, state, slashing),
        batchable=True, priority=SignatureSetPriority.AGGREGATE,
    ):
        _reject("INVALID_SIGNATURE")


async def validate_gossip_attester_slashing(
    p: Preset, cfg: ChainConfig, *, slashing, ctx, state, pool, op_pool
) -> None:
    if not is_slashable_attestation_data(slashing.attestation_1.data, slashing.attestation_2.data):
        _reject("NOT_SLASHABLE")
    intersection = set(slashing.attestation_1.attesting_indices) & set(
        slashing.attestation_2.attesting_indices
    )
    epoch = compute_epoch_at_slot(p, state.slot)
    if not any(is_slashable_validator(state.validators[i], epoch) for i in intersection):
        _ignore("NO_SLASHABLE_VALIDATORS")
    if not await _pool_verify(
        pool, attester_slashing_signature_sets(p, ctx, state, slashing),
        batchable=True, priority=SignatureSetPriority.AGGREGATE,
    ):
        _reject("INVALID_SIGNATURE")
