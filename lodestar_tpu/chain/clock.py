"""Slot clock + typed chain event bus.

Reference: packages/beacon-node/src/chain/clock/LocalClock.ts:14.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from ..tracing import TRACER


class LocalClock:
    """Slot/epoch ticker.  ``now_fn`` is injectable so tests and the dev
    chain can drive time manually (the reference's sim tests tick real
    timers; manual time is both faster and deterministic)."""

    def __init__(
        self,
        genesis_time: int,
        seconds_per_slot: int,
        slots_per_epoch: int,
        now_fn: Callable[[], float] = time.time,
    ):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self.slots_per_epoch = slots_per_epoch
        self.now_fn = now_fn

    @property
    def current_slot(self) -> int:
        return max(0, int(self.now_fn() - self.genesis_time) // self.seconds_per_slot)

    @property
    def current_epoch(self) -> int:
        return self.current_slot // self.slots_per_epoch

    def slot_start_time(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        return (self.now_fn() - self.genesis_time) % self.seconds_per_slot

    def is_current_slot_given_disparity(self, slot: int, disparity_sec: float = 0.5) -> bool:
        """Gossip clock-disparity tolerance (LocalClock.ts helpers)."""
        lo = self.slot_start_time(slot) - disparity_sec
        hi = self.slot_start_time(slot + 1) + disparity_sec
        return lo <= self.now_fn() <= hi

    def annotate_slot(self, slot: int) -> None:
        """Drop a slot-boundary marker on the trace timeline so BLS spans
        can be read against slot/epoch edges."""
        if TRACER.enabled:
            TRACER.instant("clock.slot", cat="clock", slot=slot,
                           epoch=slot // self.slots_per_epoch)

    async def wait_for_slot(self, slot: int) -> None:
        delta = self.slot_start_time(slot) - self.now_fn()
        if delta > 0:
            await asyncio.sleep(delta)
        self.annotate_slot(slot)


class ManualClock(LocalClock):
    """A LocalClock whose time is advanced explicitly (dev chain / tests):
    ``set_slot(n)`` pins now() to the start of slot n."""

    def __init__(self, genesis_time: int, seconds_per_slot: int, slots_per_epoch: int):
        self._now = float(genesis_time)
        super().__init__(genesis_time, seconds_per_slot, slots_per_epoch, now_fn=lambda: self._now)

    def set_slot(self, slot: int, seconds_into: float = 0.0) -> None:
        self._now = self.genesis_time + slot * self.seconds_per_slot + seconds_into
        if seconds_into == 0.0:
            self.annotate_slot(slot)

    async def wait_for_slot(self, slot: int) -> None:
        self.set_slot(slot)
