"""Sync-committee message + contribution pools, and the gossip validators
for both (altair).

Reference: packages/beacon-node/src/chain/opPools/syncCommitteeMessagePool.ts
(per-slot/beacon-block-root aggregation into contributions),
opPools/syncContributionAndProofPool.ts (best contribution per subcommittee
for block production), and chain/validation/syncCommittee.ts +
syncCommitteeContributionAndProof.ts (gossip IGNORE/REJECT flows).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from ..config.chain_config import ChainConfig
from ..params import DOMAIN_SYNC_COMMITTEE, Preset
from ..params.presets import SYNC_COMMITTEE_SUBNET_COUNT
from ..ssz import Fields
from ..state_transition import compute_epoch_at_slot, compute_signing_root, get_domain
from ..types import get_types
from ..crypto.bls.verifier import SignatureSetPriority
from .validation import (
    GossipAction,
    GossipValidationError,
    _ignore,
    _pool_verify,
    _reject,
    _storm_deadline,
)

G2_INFINITY_SIG = b"\xc0" + b"\x00" * 95


class SyncCommitteeMessagePool:
    """slot -> block_root -> subcommittee -> accumulated signatures.

    The reference aggregates eagerly per (subnet, block_root); here we keep
    the individual messages and aggregate on demand (host-side aggregation
    is cheap at these counts; the batched device path verifies them).
    """

    SLOTS_RETAINED = 8

    def __init__(self, preset: Preset):
        self.p = preset
        # (slot, root, subcommittee) -> {index_in_subcommittee: signature}
        self._msgs: Dict[Tuple[int, bytes, int], Dict[int, bytes]] = {}

    def __len__(self) -> int:
        return sum(len(v) for v in self._msgs.values())

    def add(self, slot: int, block_root: bytes, subcommittee: int,
            index_in_subcommittee: int, signature: bytes) -> None:
        key = (slot, bytes(block_root), subcommittee)
        self._msgs.setdefault(key, {})[index_in_subcommittee] = signature

    def get_contribution(self, slot: int, block_root: bytes, subcommittee: int):
        """Build a SyncCommitteeContribution from pooled messages."""
        from ..crypto.bls.api import Signature, aggregate_signatures

        key = (slot, bytes(block_root), subcommittee)
        msgs = self._msgs.get(key)
        if not msgs:
            return None
        sub_size = self.p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        bits = [False] * sub_size
        sigs = []
        for idx, sig in sorted(msgs.items()):
            bits[idx] = True
            sigs.append(Signature.from_bytes(sig))
        return Fields(
            slot=slot,
            beacon_block_root=bytes(block_root),
            subcommittee_index=subcommittee,
            aggregation_bits=bits,
            signature=aggregate_signatures(sigs).to_bytes(),
        )

    def prune(self, clock_slot: int) -> None:
        for key in list(self._msgs):
            if key[0] < clock_slot - self.SLOTS_RETAINED:
                del self._msgs[key]


class SyncContributionAndProofPool:
    """Best contribution per (slot, root, subcommittee) for block packing
    (syncContributionAndProofPool.ts getSyncAggregate)."""

    SLOTS_RETAINED = 8

    def __init__(self, preset: Preset):
        self.p = preset
        self._best: Dict[Tuple[int, bytes, int], object] = {}

    def add(self, contribution) -> None:
        key = (
            contribution.slot,
            bytes(contribution.beacon_block_root),
            contribution.subcommittee_index,
        )
        cur = self._best.get(key)
        if cur is None or sum(contribution.aggregation_bits) > sum(cur.aggregation_bits):
            self._best[key] = contribution

    def get_sync_aggregate(self, slot: int, block_root: bytes):
        """Assemble the block's SyncAggregate from the best contributions
        for (slot-1's block root)."""
        from ..crypto.bls.api import Signature, aggregate_signatures

        sub_size = self.p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        bits = [False] * self.p.SYNC_COMMITTEE_SIZE
        sigs = []
        for sub in range(SYNC_COMMITTEE_SUBNET_COUNT):
            c = self._best.get((slot, bytes(block_root), sub))
            if c is None:
                continue
            for i, b in enumerate(c.aggregation_bits):
                if b:
                    bits[sub * sub_size + i] = True
            sigs.append(Signature.from_bytes(bytes(c.signature)))
        if not sigs:
            return Fields(
                sync_committee_bits=bits, sync_committee_signature=G2_INFINITY_SIG
            )
        return Fields(
            sync_committee_bits=bits,
            sync_committee_signature=aggregate_signatures(sigs).to_bytes(),
        )

    def prune(self, clock_slot: int) -> None:
        for key in list(self._best):
            if key[0] < clock_slot - self.SLOTS_RETAINED:
                del self._best[key]


# ---------------------------------------------------------------------------
# gossip validators (chain/validation/syncCommittee.ts)
# ---------------------------------------------------------------------------


def subcommittee_assignment(p: Preset, state, validator_index: int) -> List[int]:
    """Subcommittees where `validator_index`'s pubkey sits in the CURRENT
    sync committee (duplicates possible — the committee samples with
    replacement)."""
    pk = bytes(state.validators[validator_index].pubkey)
    sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    out = []
    for i, cpk in enumerate(state.current_sync_committee.pubkeys):
        if bytes(cpk) == pk:
            out.append(i // sub_size)
    return out


async def validate_sync_committee_message(
    p: Preset, cfg: ChainConfig, *, message, subnet: int, clock_slot: int,
    state, ctx, seen_sync_msgs, pool,
) -> int:
    """Returns index_in_subcommittee on acceptance (syncCommittee.ts).

    IGNORE: wrong slot window, already seen.  REJECT: validator not in the
    committee / wrong subnet / bad signature.
    """
    if message.slot != clock_slot:
        _ignore("NOT_CURRENT_SLOT")
    vi = message.validator_index
    if vi >= len(state.validators):
        _reject("UNKNOWN_VALIDATOR")
    subs = subcommittee_assignment(p, state, vi)
    if subnet not in subs:
        _reject("VALIDATOR_NOT_IN_SUBNET")
    if seen_sync_msgs.is_known(message.slot, subnet, vi):
        _ignore("ALREADY_SEEN")
    # signature over the block root at DOMAIN_SYNC_COMMITTEE
    from ..crypto.bls.verifier import SingleSignatureSet
    from ..crypto.bls.api import PublicKey

    epoch = compute_epoch_at_slot(p, message.slot)
    domain = get_domain(p, state, DOMAIN_SYNC_COMMITTEE, epoch)
    sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    # signing root: SigningData(object_root=block_root, domain) — the
    # message signs the beacon block root directly (spec p2p)
    from ..ssz import Fields as F

    t = get_types(p).phase0
    signing_root = t.SigningData.hash_tree_root(
        F(object_root=bytes(message.beacon_block_root), domain=domain)
    )
    sig_set = SingleSignatureSet(
        pubkey=PublicKey.from_bytes(bytes(state.validators[vi].pubkey)),
        signing_root=signing_root,
        signature=bytes(message.signature),
    )
    if not await _pool_verify(
        pool, [sig_set], batchable=True,
        priority=SignatureSetPriority.SYNC_COMMITTEE,
        deadline=_storm_deadline(cfg),
    ):
        _reject("INVALID_SIGNATURE")
    if seen_sync_msgs.is_known(message.slot, subnet, vi):
        _ignore("ALREADY_SEEN")
    seen_sync_msgs.add(message.slot, subnet, vi)
    # position within the subcommittee
    pk = bytes(state.validators[vi].pubkey)
    for i, cpk in enumerate(state.current_sync_committee.pubkeys):
        if bytes(cpk) == pk and i // sub_size == subnet:
            return i % sub_size
    _reject("VALIDATOR_NOT_IN_SUBNET")


def is_sync_committee_aggregator(p: Preset, selection_proof: bytes) -> bool:
    """isSyncCommitteeAggregator (spec: modulo over sync committee size /
    subnets / TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE=16)."""
    modulo = max(
        1,
        p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT // 16,
    )
    digest = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(digest[:8], "little") % modulo == 0


async def validate_sync_committee_contribution(
    p: Preset, cfg: ChainConfig, *, signed_contribution, clock_slot: int,
    state, ctx, seen_contributions, pool,
) -> None:
    """syncCommitteeContributionAndProof.ts: slot window, subcommittee
    range, aggregator selection, three signatures (selection proof,
    aggregator, aggregate)."""
    msg = signed_contribution.message
    contribution = msg.contribution
    if contribution.slot != clock_slot:
        _ignore("NOT_CURRENT_SLOT")
    if contribution.subcommittee_index >= SYNC_COMMITTEE_SUBNET_COUNT:
        _reject("BAD_SUBCOMMITTEE")
    if not any(contribution.aggregation_bits):
        _reject("EMPTY_CONTRIBUTION")
    key = (contribution.slot, msg.aggregator_index, contribution.subcommittee_index)
    if key in seen_contributions:
        _ignore("ALREADY_SEEN")
    from ..crypto.bls.api import PublicKey
    from ..crypto.bls.verifier import AggregatedSignatureSet, SingleSignatureSet
    from ..params import (
        DOMAIN_CONTRIBUTION_AND_PROOF,
        DOMAIN_SYNC_COMMITTEE,
        DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    )
    from ..ssz import Fields as F

    t_all = get_types(p)
    t0 = t_all.phase0
    t_alt = t_all.altair
    epoch = compute_epoch_at_slot(p, contribution.slot)

    # 1. selection proof: SyncAggregatorSelectionData signed by aggregator
    sel_domain = get_domain(p, state, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
    sel_data = F(slot=contribution.slot, subcommittee_index=contribution.subcommittee_index)
    sel_root = compute_signing_root(p, t_alt.SyncAggregatorSelectionData, sel_data, sel_domain)
    if not is_sync_committee_aggregator(p, bytes(msg.selection_proof)):
        _reject("NOT_AGGREGATOR")
    agg_pk = PublicKey.from_bytes(bytes(state.validators[msg.aggregator_index].pubkey))
    sets = [
        SingleSignatureSet(
            pubkey=agg_pk, signing_root=sel_root, signature=bytes(msg.selection_proof)
        )
    ]
    # 2. aggregator signature over ContributionAndProof
    cap_domain = get_domain(p, state, DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
    cap_root = compute_signing_root(p, t_alt.ContributionAndProof, msg, cap_domain)
    sets.append(
        SingleSignatureSet(
            pubkey=agg_pk, signing_root=cap_root,
            signature=bytes(signed_contribution.signature),
        )
    )
    # 3. the contribution aggregate itself over the block root
    sync_domain = get_domain(p, state, DOMAIN_SYNC_COMMITTEE, epoch)
    signing_root = t0.SigningData.hash_tree_root(
        F(object_root=bytes(contribution.beacon_block_root), domain=sync_domain)
    )
    sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    base = contribution.subcommittee_index * sub_size
    pks = [
        PublicKey.from_bytes(bytes(state.current_sync_committee.pubkeys[base + i]))
        for i, bit in enumerate(contribution.aggregation_bits)
        if bit
    ]
    sets.append(
        AggregatedSignatureSet(
            pubkeys=pks, signing_root=signing_root,
            signature=bytes(contribution.signature),
        )
    )
    # contributions ride the AGGREGATE lane, not SYNC_COMMITTEE: they are
    # the sync-committee analog of aggregate_and_proof (~1/512 of message
    # volume), and gossip intake deliberately never sheds them — admitting
    # them at intake only to make them the pool's first eviction victim
    # would be a priority inversion
    if not await _pool_verify(
        pool, sets, batchable=True,
        priority=SignatureSetPriority.AGGREGATE,
    ):
        _reject("INVALID_SIGNATURE")
    if key in seen_contributions:
        _ignore("ALREADY_SEEN")
    seen_contributions.add(key)
