"""ValidatorMonitor: opt-in per-validator duty tracking inside the node.

Reference: packages/beacon-node/src/metrics/validatorMonitor.ts:165 —
operators register the indices they care about; the node then records,
per epoch, whether each one attested (and with what inclusion delay) and
proposed, surfacing hit-rates through the metrics registry and epoch
summaries through logs.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Set

from ..params import Preset
from ..state_transition import compute_epoch_at_slot
from ..utils.logger import get_logger

logger = get_logger("validator-monitor")


class ValidatorMonitor:
    def __init__(self, preset: Preset, metrics=None):
        self.p = preset
        self.metrics = metrics
        self.registered: Set[int] = set()
        # epoch -> index -> min inclusion delay of an included attestation
        self._att_inclusion: Dict[int, Dict[int, int]] = defaultdict(dict)
        # epoch -> set of registered proposers who proposed
        self._proposals: Dict[int, Set[int]] = defaultdict(set)
        self._last_summarized_epoch = -1

    def register_local_validator(self, index: int) -> None:
        self.registered.add(int(index))

    # -- feed (called from BeaconChain on import) ----------------------------

    def on_block(self, block, ctx) -> None:
        """Record proposals by, and attestation inclusions of, registered
        validators (validatorMonitor registerBeaconBlock +
        registerAttestationInBlock)."""
        if not self.registered:
            return
        if int(block.proposer_index) in self.registered:
            epoch = compute_epoch_at_slot(self.p, block.slot)
            self._proposals[epoch].add(int(block.proposer_index))
            if self.metrics:
                self.metrics.monitor_proposals_total.inc()
        for att in block.body.attestations:
            data = att.data
            try:
                indices = ctx.get_attesting_indices(data, att.aggregation_bits)
            except Exception:
                continue
            delay = max(1, int(block.slot) - int(data.slot))
            epoch = data.target.epoch
            for vi in indices:
                vi = int(vi)
                if vi not in self.registered:
                    continue
                prev = self._att_inclusion[epoch].get(vi)
                if prev is None or delay < prev:
                    self._att_inclusion[epoch][vi] = delay

    def on_clock_epoch(self, epoch: int) -> None:
        """Summarize the epoch before last (its inclusions are final) —
        the reference's onceEveryEndOfEpoch summary."""
        done = epoch - 2
        if done < 0 or done <= self._last_summarized_epoch:
            return
        self._last_summarized_epoch = done
        summary = self.epoch_summary(done)
        if summary is None:
            return
        logger.info(
            "epoch %d: %d/%d registered validators attested (avg delay %.2f)",
            done, summary["attested"], summary["registered"],
            summary["avg_inclusion_delay"],
        )
        if self.metrics:
            self.metrics.monitor_attestation_hit_ratio.set(
                summary["attested"] / max(1, summary["registered"])
            )
        # prune old epochs
        for e in [e for e in self._att_inclusion if e < done - 2]:
            del self._att_inclusion[e]
        for e in [e for e in self._proposals if e < done - 2]:
            del self._proposals[e]

    # -- queries -------------------------------------------------------------

    def epoch_summary(self, epoch: int) -> Optional[dict]:
        if not self.registered:
            return None
        inc = self._att_inclusion.get(epoch, {})
        delays = [d for vi, d in inc.items()]
        return {
            "epoch": epoch,
            "registered": len(self.registered),
            "attested": len(inc),
            "missed": sorted(self.registered - set(inc)),
            "avg_inclusion_delay": (sum(delays) / len(delays)) if delays else 0.0,
            "proposals": sorted(self._proposals.get(epoch, ())),
        }
