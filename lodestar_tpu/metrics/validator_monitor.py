"""ValidatorMonitor: opt-in per-validator duty tracking inside the node.

Reference: packages/beacon-node/src/metrics/validatorMonitor.ts:165 —
operators register the indices they care about; the node then records,
per epoch, whether each one attested (inclusion delay, target/head
correctness), proposed, and fulfilled sync-committee duties, surfacing
hit-rates and timeliness through the metrics registry and epoch
summaries through logs (the reference's registerAttestationInBlock /
registerBeaconBlock / registerSyncAggregateInBlock +
onceEveryEndOfEpoch summary).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Callable, Dict, Optional, Sequence, Set

from ..params import Preset
from ..state_transition import compute_epoch_at_slot, compute_start_slot_at_epoch
from ..utils.logger import get_logger

logger = get_logger("validator-monitor")


class _Inclusion:
    __slots__ = ("delay", "target_correct", "head_correct")

    def __init__(self, delay: int, target_correct: bool, head_correct: bool):
        self.delay = delay
        self.target_correct = target_correct
        self.head_correct = head_correct


class ValidatorMonitor:
    def __init__(self, preset: Preset, metrics=None):
        self.p = preset
        self.metrics = metrics
        self.registered: Set[int] = set()
        # epoch -> index -> best (lowest-delay) inclusion record
        self._att_inclusion: Dict[int, Dict[int, _Inclusion]] = defaultdict(dict)
        # epoch -> set of registered proposers who proposed
        self._proposals: Dict[int, Set[int]] = defaultdict(set)
        # epoch -> index -> [hits, duties] for sync-committee participation
        self._sync_duty: Dict[int, Dict[int, list]] = defaultdict(dict)
        self._last_summarized_epoch = -1

    def register_local_validator(self, index: int) -> None:
        self.registered.add(int(index))

    # -- feed (called from BeaconChain on import) ----------------------------

    def on_block(
        self,
        block,
        ctx,
        ancestor_at: Optional[Callable[[int], Optional[bytes]]] = None,
        sync_committee_indices: Optional[Sequence[int]] = None,
    ) -> None:
        """Record proposals by, attestation inclusions of, and
        sync-committee participation by registered validators.

        ``ancestor_at(slot)`` resolves the canonical block root at a slot
        on the imported block's chain — used to judge target/head vote
        correctness (validatorMonitor registerAttestationInBlock's
        correctHead/correctTarget).  ``sync_committee_indices`` is the
        validator index per committee position for the block's period
        (registerSyncAggregateInBlock)."""
        if not self.registered:
            return
        if int(block.proposer_index) in self.registered:
            epoch = compute_epoch_at_slot(self.p, block.slot)
            self._proposals[epoch].add(int(block.proposer_index))
            if self.metrics:
                self.metrics.monitor_proposals_total.inc()
        for att in block.body.attestations:
            data = att.data
            try:
                indices = ctx.get_attesting_indices(data, att.aggregation_bits)
            except Exception:
                continue
            watched = [int(vi) for vi in indices if int(vi) in self.registered]
            if not watched:
                continue
            delay = max(1, int(block.slot) - int(data.slot))
            epoch = data.target.epoch
            target_correct = head_correct = True
            if ancestor_at is not None:
                boundary = ancestor_at(
                    compute_start_slot_at_epoch(self.p, data.target.epoch)
                )
                if boundary is not None:
                    target_correct = bytes(data.target.root) == boundary
                head = ancestor_at(int(data.slot))
                if head is not None:
                    head_correct = bytes(data.beacon_block_root) == head
            rec = _Inclusion(delay, target_correct, head_correct)
            for vi in watched:
                prev = self._att_inclusion[epoch].get(vi)
                if prev is None or delay < prev.delay:
                    self._att_inclusion[epoch][vi] = rec
                    # observe on REPLACEMENT too (ADVICE r5): a later block
                    # carrying a lower-delay inclusion is the record the
                    # dashboards should reflect, not only the first sight
                    if self.metrics:
                        self.metrics.monitor_inclusion_delay.observe(delay)
                        if target_correct:
                            self.metrics.monitor_timely_total.labels(
                                flag="target"
                            ).inc()
                        if head_correct:
                            self.metrics.monitor_timely_total.labels(flag="head").inc()
        if sync_committee_indices and "sync_aggregate" in block.body.keys():
            agg = block.body.sync_aggregate
            epoch = compute_epoch_at_slot(self.p, block.slot)
            for pos, vi in enumerate(sync_committee_indices):
                vi = int(vi)
                if vi not in self.registered:
                    continue
                cell = self._sync_duty[epoch].setdefault(vi, [0, 0])
                cell[1] += 1
                if agg.sync_committee_bits[pos]:
                    cell[0] += 1

    def on_clock_epoch(self, epoch: int) -> None:
        """Summarize the epoch before last (its inclusions are final) —
        the reference's onceEveryEndOfEpoch summary."""
        done = epoch - 2
        if done < 0 or done <= self._last_summarized_epoch:
            return
        self._last_summarized_epoch = done
        summary = self.epoch_summary(done)
        if summary is None:
            return
        logger.info(
            "epoch %d: %d/%d registered attested (avg delay %.2f, "
            "target-correct %d, head-correct %d); sync duties %d/%d",
            done, summary["attested"], summary["registered"],
            summary["avg_inclusion_delay"], summary["target_correct"],
            summary["head_correct"], summary["sync_hits"],
            summary["sync_duties"],
        )
        if self.metrics:
            self.metrics.monitor_attestation_hit_ratio.set(
                summary["attested"] / max(1, summary["registered"])
            )
            if summary["sync_duties"]:
                self.metrics.monitor_sync_committee_hit_ratio.set(
                    summary["sync_hits"] / summary["sync_duties"]
                )
        # prune old epochs
        for store in (self._att_inclusion, self._proposals, self._sync_duty):
            for e in [e for e in store if e < done - 2]:
                del store[e]

    # -- queries -------------------------------------------------------------

    def epoch_summary(self, epoch: int) -> Optional[dict]:
        if not self.registered:
            return None
        inc = self._att_inclusion.get(epoch, {})
        delays = [r.delay for r in inc.values()]
        sync = self._sync_duty.get(epoch, {})
        return {
            "epoch": epoch,
            "registered": len(self.registered),
            "attested": len(inc),
            "missed": sorted(self.registered - set(inc)),
            "avg_inclusion_delay": (sum(delays) / len(delays)) if delays else 0.0,
            "target_correct": sum(1 for r in inc.values() if r.target_correct),
            "head_correct": sum(1 for r in inc.values() if r.head_correct),
            "proposals": sorted(self._proposals.get(epoch, ())),
            "sync_hits": sum(c[0] for c in sync.values()),
            "sync_duties": sum(c[1] for c in sync.values()),
        }
