"""Metrics registry + beacon metric groups.

Reference: packages/beacon-node/src/metrics (prom-client registry,
metrics/metrics/lodestar.ts metric definitions, server/http.ts exposition).
Built on prometheus_client (in the image); a no-op fallback keeps the
package importable without it.
"""

from .registry import Metrics, MetricsRegistry, create_metrics  # noqa: F401
