"""Prometheus-backed metrics registry.

Reference: packages/beacon-node/src/metrics/metrics/lodestar.ts (the
framework-internal metric groups; blsThreadPool.* at :385 is the model for
the device-pool metrics here) and metrics/server/http.ts (exposition).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..observatory.latency import COMPILE_BUCKETS_S, SLO_LATENCY_BUCKETS_S

try:  # prometheus_client is present in the image; gate anyway
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROM = True
except Exception:  # pragma: no cover
    HAVE_PROM = False


class _NoopMetric:
    def labels(self, *a, **k):
        return self

    def inc(self, *a, **k):
        pass

    def dec(self, *a, **k):
        pass

    def set(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass


class MetricsRegistry:
    """Thin factory over a CollectorRegistry."""

    def __init__(self):
        self.registry = CollectorRegistry() if HAVE_PROM else None

    def counter(self, name: str, help: str, labels: Sequence[str] = ()):
        if not HAVE_PROM:
            return _NoopMetric()
        return Counter(name, help, labelnames=list(labels), registry=self.registry)

    def gauge(self, name: str, help: str, labels: Sequence[str] = ()):
        if not HAVE_PROM:
            return _NoopMetric()
        return Gauge(name, help, labelnames=list(labels), registry=self.registry)

    def histogram(self, name: str, help: str, buckets, labels: Sequence[str] = ()):
        if not HAVE_PROM:
            return _NoopMetric()
        return Histogram(name, help, labelnames=list(labels), buckets=buckets, registry=self.registry)

    def expose(self) -> bytes:
        """Prometheus text exposition (server/http.ts GET /metrics body)."""
        if not HAVE_PROM:
            return b""
        return generate_latest(self.registry)


class Metrics:
    """The framework's metric groups (subset of lodestar.ts, grown as
    subsystems land)."""

    def __init__(self):
        self.reg = MetricsRegistry()
        r = self.reg
        # device BLS pool (blsThreadPool.* analog, lodestar.ts:385)
        self.bls_pool_queue_length = r.gauge(
            "lodestar_bls_pool_queue_length", "pending signature sets in the device pool"
        )
        self.bls_pool_dispatches_total = r.counter(
            "lodestar_bls_pool_dispatches_total", "device batch-verify dispatches"
        )
        self.bls_pool_sets_total = r.counter(
            "lodestar_bls_pool_sets_total", "signature sets verified", labels=("result",)
        )
        self.bls_pool_batch_size = r.histogram(
            "lodestar_bls_pool_batch_size",
            "live sets per dispatch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
        )
        self.bls_pool_dispatch_seconds = r.histogram(
            "lodestar_bls_pool_dispatch_seconds",
            "device dispatch latency",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5),
        )
        # validator monitor (metrics/validatorMonitor.ts)
        self.monitor_proposals_total = r.counter(
            "lodestar_validator_monitor_proposals_total",
            "blocks proposed by registered validators",
        )
        self.monitor_attestation_hit_ratio = r.gauge(
            "lodestar_validator_monitor_attestation_hit_ratio",
            "fraction of registered validators attesting per epoch",
        )
        self.bls_pool_job_wait_seconds = r.histogram(
            "lodestar_bls_pool_job_wait_seconds",
            "time a set waits in the buffer before dispatch",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1),
        )
        # pipelined dispatch stages (round-6: pack -> device -> final exp)
        self.bls_pool_pack_seconds = r.histogram(
            "lodestar_bls_pool_pack_seconds",
            "host packing stage (bytes -> limb arrays) per dispatch",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5),
        )
        self.bls_pool_final_exp_seconds = r.histogram(
            "lodestar_bls_pool_final_exp_seconds",
            "device readback + host final exponentiation per dispatch",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1),
        )
        self.bls_pool_inflight_depth = r.gauge(
            "lodestar_bls_pool_inflight_depth",
            "merged batches concurrently in flight on the device pipeline",
        )
        # span-derived pipeline observability (docs/observability.md)
        self.bls_pool_queue_wait_seconds = r.histogram(
            "lodestar_bls_pool_queue_wait_seconds",
            "DEPRECATED (one release, round 11): laneless queue-wait "
            "histogram on ad-hoc buckets — use bls_queue_wait_seconds "
            "(per lane, SLO-ladder buckets)",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.02, 0.05, 0.1, 0.5, 1),
        )
        self.bls_pool_overlap_ratio = r.gauge(
            "lodestar_bls_pool_overlap_ratio",
            "sum of in-flight batch busy time / flush wall time "
            "(>1 means batches overlapped; 1 is fully serial)",
        )
        self.bls_pool_inflight_peak = r.gauge(
            "lodestar_bls_pool_inflight_peak",
            "highest in-flight depth the pipeline has reached",
        )
        self.bls_verifier_stage_seconds = r.gauge(
            "lodestar_bls_verifier_stage_seconds",
            "DEPRECATED (one release, round 11): cumulative wall seconds "
            "per stage as a last-write gauge snapshot at flush — use the "
            "per-dispatch histogram bls_verifier_stage_duration_seconds",
            labels=("stage",),
        )
        # multi-chip executor pool + pack-side caches (round 8)
        self.bls_device_inflight = r.gauge(
            "lodestar_bls_device_inflight",
            "merged batches in flight per device executor "
            "(the least-loaded scheduler's placement signal)",
            labels=("device",),
        )
        self.bls_sets_per_sec_per_chip = r.gauge(
            "lodestar_bls_sets_per_sec_per_chip",
            "signature sets resolved per second per device in the last "
            "pool flush — the BASELINE.json north star, live",
        )
        self.bls_pack_cache_hits_total = r.counter(
            "lodestar_bls_pack_cache_hits_total",
            "pack-stage point-cache hits (affine point reused, "
            "decompression/aggregation/inversion skipped)",
        )
        self.bls_pack_cache_misses_total = r.counter(
            "lodestar_bls_pack_cache_misses_total",
            "pack-stage point-cache misses (full decompression + batched "
            "inversion paid)",
        )
        self.bls_pack_rejected_total = r.counter(
            "lodestar_bls_pack_rejected_total",
            "pack-stage rejections (malformed bytes or infinity point; "
            "the batch never dispatched)",
        )
        # overload survival: QoS lanes, shedding, backpressure (round 10,
        # docs/overload.md)
        self.bls_pool_dropped_total = r.counter(
            "lodestar_bls_pool_dropped_total",
            "signature sets dropped by the overload policy instead of "
            "verified (deadline shed / overflow eviction / shutdown), "
            "by reason and QoS lane — every drop is accounted here",
            labels=("reason", "lane"),
        )
        self.bls_pool_backpressure = r.gauge(
            "lodestar_bls_pool_backpressure",
            "1 while pending sets sit above the pool high-water mark "
            "(gossip intake slows its sheddable topics), 0 once drained "
            "below the low-water release point",
        )
        self.bls_pool_lane_pending = r.gauge(
            "lodestar_bls_pool_lane_pending",
            "pending verification jobs per QoS lane "
            "(block_proposal/aggregate/unaggregated/sync_committee)",
            labels=("lane",),
        )
        # performance observatory (round 11, docs/observability.md
        # §Performance observatory)
        self.bls_queue_wait_seconds = r.histogram(
            "lodestar_bls_queue_wait_seconds",
            "per-job pool buffer wait by QoS lane, on the firehose SLO "
            "bucket ladder — p50/p99 here, in firehose reports, and in "
            "bls.queue_wait spans agree to one bucket "
            "(replaces the deprecated laneless bls_pool_queue_wait_seconds)",
            buckets=SLO_LATENCY_BUCKETS_S,
            labels=("lane",),
        )
        self.bls_e2e_verify_seconds = r.histogram(
            "lodestar_bls_e2e_verify_seconds",
            "end-to-end verify latency by QoS lane: job enqueue -> "
            "verdict resolved (drops excluded — they land in "
            "bls_pool_dropped_total), SLO-ladder buckets",
            buckets=SLO_LATENCY_BUCKETS_S,
            labels=("lane",),
        )
        self.bls_verifier_stage_duration_seconds = r.histogram(
            "lodestar_bls_verifier_stage_duration_seconds",
            "per-call verifier stage duration (pack/dispatch/final_exp) — "
            "the histogram the deprecated bls_verifier_stage_seconds gauge "
            "snapshot could never be",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5),
            labels=("stage",),
        )
        self.bls_compile_seconds = r.histogram(
            "lodestar_bls_compile_seconds",
            "program materialization cost by entry and kind: cold = real "
            "XLA/Mosaic backend compile, warm_load = persistent-cache "
            "load, aot_load = durable AOT executable store deserialize "
            "(docs/aot.md — no trace, no lower, no backend compile), "
            "hit = already live in-process (compile ledger, persisted "
            "in .jax_cache/compile_ledger.json)",
            buckets=COMPILE_BUCKETS_S,
            labels=("entry", "kind"),
        )
        self.bls_device_hbm_bytes = r.gauge(
            "lodestar_bls_device_hbm_bytes",
            "per-device memory from Device.memory_stats() by kind "
            "(bytes_in_use/peak_bytes_in_use/bytes_limit/...), sampled by "
            "the observatory device sampler",
            labels=("device", "kind"),
        )
        self.bls_device_busy_ratio = r.gauge(
            "lodestar_bls_device_busy_ratio",
            "fraction of recent sampler ticks each device had >= 1 "
            "unresolved batch in flight — the is-the-mesh-actually-full "
            "signal roadmap item 1 is judged by",
            labels=("device",),
        )
        self.bls_sets_per_sec_mesh = r.gauge(
            "lodestar_bls_sets_per_sec_mesh",
            "whole-mesh signature sets resolved per second in the last "
            "pool flush (sets/wall, NOT divided by device count) — the "
            "headline the sharded-kernel roadmap item is measured against",
        )
        self.bls_sharded_batches_total = r.counter(
            "lodestar_bls_sharded_batches_total",
            "merged batches dispatched as ONE mesh-spanning shard_map "
            "program (the sharded verifier tier, docs/multichip.md) — "
            "zero on a busy multi-device pool means big batches are "
            "fanning out per-device instead of using the whole mesh",
        )
        # mesh observatory: profile-window attribution (ISSUE 20,
        # docs/observability.md §Mesh observatory)
        self.bls_mesh_overlap_ratio = r.gauge(
            "lodestar_bls_mesh_overlap_ratio",
            "fraction of device-busy (dispatch-window) time during which "
            "the host was packing ANOTHER merged batch — 1.0 means the "
            "pipeline fully hides host pack behind device compute, 0 "
            "means the stages strictly alternate (attribution engine, "
            "updated per profile window)",
        )
        self.bls_sharded_combine_seconds = r.histogram(
            "lodestar_bls_sharded_combine_seconds",
            "per-mesh-batch cross-chip collective (GT combine) seconds "
            "attributed from profile-window device events inside the "
            "dispatch window — the communication term of the "
            "scaling-loss breakdown",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1),
        )
        self.bls_pipeline_bubble_seconds = r.histogram(
            "lodestar_bls_pipeline_bubble_seconds",
            "per-merged-batch end-to-end seconds the six-way attribution "
            "(queue/pack/device/combine/final_exp) could NOT explain — "
            "scheduler idle between pipeline stages",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1),
        )
        self.bls_scaling_loss = r.gauge(
            "lodestar_bls_scaling_loss",
            "mesh scaling loss (1 - scaling efficiency) split by "
            "component: communication (cross-chip collectives), "
            "shard_imbalance (slowest vs mean shard), serial_host "
            "(pack/final-exp the mesh waits on) — components sum to "
            "the measured gap within tolerance",
            labels=("component",),
        )
        # chaos campaign & self-healing device pool (round 12, docs/chaos.md)
        self.bls_degrade_total = r.counter(
            "lodestar_bls_degrade_total",
            "degradation-ladder hops (fused -> XLA -> host-native) by "
            "failure site and the tier degraded TO — the metric face of "
            "the bls.degrade journal events (one increment per hop)",
            labels=("where", "tier"),
        )
        self.bls_batch_requeues_total = r.counter(
            "lodestar_bls_batch_requeues_total",
            "failed in-flight batches re-dispatched (same packed payload) "
            "onto a surviving executor before any per-job retry",
        )
        self.bls_device_quarantines_total = r.counter(
            "lodestar_bls_device_quarantines_total",
            "executor quarantine entries (threshold consecutive failures, "
            "or a failed re-admission probe) per device",
            labels=("device",),
        )
        self.bls_device_health = r.gauge(
            "lodestar_bls_device_health",
            "executor health state per device: 0 healthy, 1 suspect, "
            "2 probing (one re-admission batch in flight), 3 quarantined",
            labels=("device",),
        )
        # flight recorder & failure forensics (round 9)
        self.bls_watchdog_stalls_total = r.counter(
            "lodestar_bls_watchdog_stalls_total",
            "dispatched batches flagged by the watchdog as unresolved past "
            "the deadline (a silent device wedge made visible)",
            labels=("device",),
        )
        self.tracing_spans_dropped_total = r.gauge(
            "lodestar_tracing_spans_dropped_total",
            "spans evicted from the tracer ring buffer (history a trace "
            "dump is missing)",
        )
        self.forensics_journal_dropped_total = r.gauge(
            "lodestar_forensics_journal_dropped_total",
            "events evicted from the forensics journal ring (history a "
            "diagnostic bundle is missing)",
        )
        self.forensics_bundles_written_total = r.counter(
            "lodestar_forensics_bundles_written_total",
            "diagnostic bundles written, by trigger reason "
            "(watchdog/sigterm/sigusr2/crash-*/api)",
            labels=("reason",),
        )
        # chain
        self.block_processing_seconds = r.histogram(
            "lodestar_block_processing_seconds",
            "verifyBlock+importBlock wall time",
            buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 10),
        )
        self.head_slot = r.gauge("lodestar_head_slot", "fork-choice head slot")
        self.finalized_epoch = r.gauge("lodestar_finalized_epoch", "finalized checkpoint epoch")
        # gossip queues (gossip/validation/queue.ts analog)
        self.gossip_queue_length = r.gauge(
            "lodestar_gossip_queue_length", "pending gossip jobs", labels=("topic",)
        )
        self.gossip_queue_dropped_total = r.counter(
            "lodestar_gossip_queue_dropped_total", "dropped gossip jobs", labels=("topic",)
        )
        # regen + state caches (regen/queued.ts metrics)
        self.regen_replays_total = r.counter(
            "lodestar_regen_replayed_blocks_total",
            "blocks replayed to regenerate a state on cache miss",
        )
        self.state_cache_size = r.gauge(
            "lodestar_state_cache_size", "states held in the LRU state cache"
        )
        # network (network/metrics.ts)
        self.peers = r.gauge("lodestar_peers", "connected peers")
        self.gossip_messages_total = r.counter(
            "lodestar_gossip_messages_total", "gossip messages", labels=("dir",)
        )
        self.reqresp_requests_total = r.counter(
            "lodestar_reqresp_requests_total", "req/resp requests", labels=("method", "dir")
        )
        # sync (sync/metrics)
        self.sync_batches_total = r.counter(
            "lodestar_range_sync_batches_total", "range sync batches imported"
        )
        self.sync_blocks_total = r.counter(
            "lodestar_range_sync_blocks_total", "blocks imported via range sync"
        )
        # api server
        self.api_requests_total = r.counter(
            "lodestar_api_requests_total", "REST API requests", labels=("status",)
        )
        self.api_response_seconds = r.histogram(
            "lodestar_api_response_seconds",
            "REST API handler latency",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5),
        )
        # db controller (db/controller metrics — lodestar.ts dbReadReq/dbWriteReq)
        self.db_op_seconds = r.histogram(
            "lodestar_db_op_seconds",
            "db controller operation latency",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5),
            labels=("op",),
        )
        self.db_ops_total = r.counter(
            "lodestar_db_ops_total", "db controller operations", labels=("op",)
        )
        # reqresp (lodestar.ts reqResp* family)
        self.reqresp_request_seconds = r.histogram(
            "lodestar_reqresp_request_seconds",
            "outbound req/resp round-trip latency",
            buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 10),
            labels=("method",),
        )
        self.reqresp_errors_total = r.counter(
            "lodestar_reqresp_errors_total",
            "req/resp failures",
            labels=("method", "reason"),
        )
        # gossipsub mesh + scoring (lodestar.ts gossipPeer.score*, mesh*)
        self.gossip_mesh_peers = r.gauge(
            "lodestar_gossip_mesh_peers", "mesh degree per topic", labels=("topic",)
        )
        self.gossip_peer_score = r.histogram(
            "lodestar_gossip_peer_score",
            "gossip peer score distribution at heartbeat",
            buckets=(-100, -10, -1, 0, 1, 10, 100),
        )
        self.gossip_control_total = r.counter(
            "lodestar_gossip_control_total",
            "gossipsub control records",
            labels=("kind", "dir"),
        )
        self.gossip_validation_total = r.counter(
            "lodestar_gossip_validation_total",
            "gossip validation verdicts",
            labels=("topic", "verdict"),
        )
        # state transition (lodestar.ts stfn* family)
        self.epoch_transition_seconds = r.histogram(
            "lodestar_epoch_transition_seconds",
            "epoch transition wall time",
            buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 30),
        )
        self.state_transition_seconds = r.histogram(
            "lodestar_state_transition_seconds",
            "per-block state transition wall time",
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1, 5),
        )
        self.prepare_next_slot_hits_total = r.counter(
            "lodestar_prepare_next_slot_hits_total",
            "block imports/productions served by the precomputed next-slot state",
        )
        # op pools (lodestar.ts opPool* family)
        self.op_pool_size = r.gauge(
            "lodestar_op_pool_size", "operations pooled", labels=("pool",)
        )
        # seen caches
        self.seen_cache_hits_total = r.counter(
            "lodestar_seen_cache_hits_total", "seen-cache hits", labels=("cache",)
        )
        # state cache effectiveness (stateCache.hits/misses)
        self.state_cache_hits_total = r.counter(
            "lodestar_state_cache_hits_total", "state cache hits"
        )
        self.state_cache_misses_total = r.counter(
            "lodestar_state_cache_misses_total",
            "state cache misses (regen replay needed)",
        )
        self.regen_seconds = r.histogram(
            "lodestar_regen_seconds",
            "state regeneration latency (checkpoint load + replay)",
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1, 5),
        )
        # sync extras
        self.sync_batch_seconds = r.histogram(
            "lodestar_range_sync_batch_seconds",
            "range sync per-batch import wall time (download excluded)",
            buckets=(0.1, 0.5, 1, 5, 10, 30),
        )
        self.backfill_blocks_total = r.counter(
            "lodestar_backfill_blocks_total", "blocks imported via backfill sync"
        )
        # validator monitor depth (validatorMonitor.ts:165)
        self.monitor_inclusion_delay = r.histogram(
            "lodestar_validator_monitor_inclusion_delay_slots",
            "attestation inclusion delay of registered validators",
            buckets=(1, 2, 3, 4, 8, 16, 32),
        )
        self.monitor_sync_committee_hit_ratio = r.gauge(
            "lodestar_validator_monitor_sync_committee_hit_ratio",
            "fraction of registered sync-committee duties fulfilled per epoch",
        )
        self.monitor_timely_total = r.counter(
            "lodestar_validator_monitor_timely_total",
            "registered validators' attestation timeliness flags",
            labels=("flag",),
        )
        # clock
        self.clock_slot = r.gauge("lodestar_clock_slot", "current wall-clock slot")


def create_metrics() -> Metrics:
    return Metrics()
