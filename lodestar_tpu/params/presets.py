"""Preset (compile-time-ish) spec constants.

Reference: packages/params/src/presets/{mainnet,minimal}/{phase0,altair,bellatrix}.ts
and packages/params/src/index.ts (non-preset constants).

A ``Preset`` is a frozen dataclass: explicit, hashable (usable as a jit static
arg), and cheap to thread through pure functions — the TPU-first equivalent of
the reference's module-level frozen singleton.
"""

from __future__ import annotations

import dataclasses
import os

UINT64_MAX = 2**64 - 1

# ---------------------------------------------------------------------------
# Non-preset constants (packages/params/src/index.ts)
# ---------------------------------------------------------------------------

GENESIS_SLOT = 0
GENESIS_EPOCH = 0
# The reference uses JS Infinity; we use uint64 max per consensus spec.
FAR_FUTURE_EPOCH = UINT64_MAX
BASE_REWARDS_PER_EPOCH = 4
DEPOSIT_CONTRACT_TREE_DEPTH = 32
JUSTIFICATION_BITS_LENGTH = 4

BLS_WITHDRAWAL_PREFIX = bytes([0])
ETH1_ADDRESS_WITHDRAWAL_PREFIX = bytes([1])

DOMAIN_BEACON_PROPOSER = bytes([0, 0, 0, 0])
DOMAIN_BEACON_ATTESTER = bytes([1, 0, 0, 0])
DOMAIN_RANDAO = bytes([2, 0, 0, 0])
DOMAIN_DEPOSIT = bytes([3, 0, 0, 0])
DOMAIN_VOLUNTARY_EXIT = bytes([4, 0, 0, 0])
DOMAIN_SELECTION_PROOF = bytes([5, 0, 0, 0])
DOMAIN_AGGREGATE_AND_PROOF = bytes([6, 0, 0, 0])
DOMAIN_SYNC_COMMITTEE = bytes([7, 0, 0, 0])
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = bytes([8, 0, 0, 0])
DOMAIN_CONTRIBUTION_AND_PROOF = bytes([9, 0, 0, 0])
DOMAIN_APPLICATION_BUILDER = bytes([0, 0, 0, 1])

TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2

TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = (TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT, TIMELY_HEAD_WEIGHT)

TARGET_AGGREGATORS_PER_COMMITTEE = 16
RANDOM_SUBNETS_PER_VALIDATOR = 1
EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION = 256
ATTESTATION_SUBNET_COUNT = 64
TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16
SYNC_COMMITTEE_SUBNET_COUNT = 4
MAX_REQUEST_BLOCKS = 1024

GENESIS_GAS_LIMIT = 30_000_000
GENESIS_BASE_FEE_PER_GAS = 1_000_000_000

# Altair light-client generalized indices
FINALIZED_ROOT_GINDEX = 105
FINALIZED_ROOT_DEPTH = 6
FINALIZED_ROOT_INDEX = 41
NEXT_SYNC_COMMITTEE_GINDEX = 55
NEXT_SYNC_COMMITTEE_DEPTH = 5
NEXT_SYNC_COMMITTEE_INDEX = 23

SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY = 128
INTERVALS_PER_SLOT = 3


# ---------------------------------------------------------------------------
# Presets
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Preset:
    """One preset = phase0 + altair + bellatrix preset values."""

    name: str

    # phase0 — misc
    MAX_COMMITTEES_PER_SLOT: int
    TARGET_COMMITTEE_SIZE: int
    MAX_VALIDATORS_PER_COMMITTEE: int
    SHUFFLE_ROUND_COUNT: int
    HYSTERESIS_QUOTIENT: int = 4
    HYSTERESIS_DOWNWARD_MULTIPLIER: int = 1
    HYSTERESIS_UPWARD_MULTIPLIER: int = 5
    SAFE_SLOTS_TO_UPDATE_JUSTIFIED: int = 8

    # phase0 — gwei
    MIN_DEPOSIT_AMOUNT: int = 1_000_000_000
    MAX_EFFECTIVE_BALANCE: int = 32_000_000_000
    EFFECTIVE_BALANCE_INCREMENT: int = 1_000_000_000

    # phase0 — time
    MIN_ATTESTATION_INCLUSION_DELAY: int = 1
    SLOTS_PER_EPOCH: int = 32
    MIN_SEED_LOOKAHEAD: int = 1
    MAX_SEED_LOOKAHEAD: int = 4
    EPOCHS_PER_ETH1_VOTING_PERIOD: int = 64
    SLOTS_PER_HISTORICAL_ROOT: int = 8192
    MIN_EPOCHS_TO_INACTIVITY_PENALTY: int = 4

    # phase0 — state list lengths
    EPOCHS_PER_HISTORICAL_VECTOR: int = 65536
    EPOCHS_PER_SLASHINGS_VECTOR: int = 8192
    HISTORICAL_ROOTS_LIMIT: int = 16_777_216
    VALIDATOR_REGISTRY_LIMIT: int = 1_099_511_627_776

    # phase0 — rewards & penalties
    BASE_REWARD_FACTOR: int = 64
    WHISTLEBLOWER_REWARD_QUOTIENT: int = 512
    PROPOSER_REWARD_QUOTIENT: int = 8
    INACTIVITY_PENALTY_QUOTIENT: int = 67_108_864
    MIN_SLASHING_PENALTY_QUOTIENT: int = 128
    PROPORTIONAL_SLASHING_MULTIPLIER: int = 1

    # phase0 — max operations per block
    MAX_PROPOSER_SLASHINGS: int = 16
    MAX_ATTESTER_SLASHINGS: int = 2
    MAX_ATTESTATIONS: int = 128
    MAX_DEPOSITS: int = 16
    MAX_VOLUNTARY_EXITS: int = 16

    # altair
    SYNC_COMMITTEE_SIZE: int = 512
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD: int = 256
    INACTIVITY_PENALTY_QUOTIENT_ALTAIR: int = 50_331_648
    MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR: int = 64
    PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR: int = 2
    MIN_SYNC_COMMITTEE_PARTICIPANTS: int = 1
    UPDATE_TIMEOUT: int = 8192

    # bellatrix
    INACTIVITY_PENALTY_QUOTIENT_BELLATRIX: int = 16_777_216
    MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX: int = 32
    PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX: int = 3
    MAX_BYTES_PER_TRANSACTION: int = 1_073_741_824
    MAX_TRANSACTIONS_PER_PAYLOAD: int = 1_048_576
    BYTES_PER_LOGS_BLOOM: int = 256
    MAX_EXTRA_DATA_BYTES: int = 32

    @property
    def SYNC_COMMITTEE_SUBNET_SIZE(self) -> int:
        return self.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT


MAINNET = Preset(
    name="mainnet",
    MAX_COMMITTEES_PER_SLOT=64,
    TARGET_COMMITTEE_SIZE=128,
    MAX_VALIDATORS_PER_COMMITTEE=2048,
    SHUFFLE_ROUND_COUNT=90,
)

MINIMAL = Preset(
    name="minimal",
    MAX_COMMITTEES_PER_SLOT=4,
    TARGET_COMMITTEE_SIZE=4,
    MAX_VALIDATORS_PER_COMMITTEE=2048,
    SHUFFLE_ROUND_COUNT=10,
    SAFE_SLOTS_TO_UPDATE_JUSTIFIED=2,
    SLOTS_PER_EPOCH=8,
    EPOCHS_PER_ETH1_VOTING_PERIOD=4,
    SLOTS_PER_HISTORICAL_ROOT=64,
    EPOCHS_PER_HISTORICAL_VECTOR=64,
    EPOCHS_PER_SLASHINGS_VECTOR=64,
    INACTIVITY_PENALTY_QUOTIENT=33_554_432,
    MIN_SLASHING_PENALTY_QUOTIENT=64,
    PROPORTIONAL_SLASHING_MULTIPLIER=2,
    SYNC_COMMITTEE_SIZE=32,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
    UPDATE_TIMEOUT=64,
)

# Gnosis chain: mainnet-shaped state with a 5s slot cadence
# (packages/params/src/presets/gnosis.ts — identical preset values to
# mainnet; the chain differences live in the ChainConfig: SECONDS_PER_SLOT,
# fork versions, deposit contract).  A distinct instance so `name`
# round-trips through config/SSZ context checks.
GNOSIS = Preset(
    name="gnosis",
    MAX_COMMITTEES_PER_SLOT=64,
    TARGET_COMMITTEE_SIZE=128,
    MAX_VALIDATORS_PER_COMMITTEE=2048,
    SHUFFLE_ROUND_COUNT=90,
)

_PRESETS = {"mainnet": MAINNET, "minimal": MINIMAL, "gnosis": GNOSIS}


def active_preset() -> Preset:
    """Preset selected via LODESTAR_PRESET env var (default mainnet).

    Mirrors packages/params/src/presetName.ts behavior.
    """
    name = os.environ.get("LODESTAR_PRESET", "mainnet")
    try:
        return _PRESETS[name]
    except KeyError:
        raise ValueError(f"unknown preset {name!r}; known: {sorted(_PRESETS)}") from None
