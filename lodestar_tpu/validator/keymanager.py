"""Keymanager API server: the standard key-management namespace served by
the validator-client process.

Reference: packages/api/src/keymanager/routes.ts (the
eth/v1/keystores list/import/delete surface of the keymanager-APIs spec)
+ the reference VC's keymanager server.  Import/delete integrate the
EIP-2335 codec (validator/keystore.py) and the EIP-3076 slashing
interchange so a migrating operator carries protection history with the
keys.
"""

from __future__ import annotations

import asyncio
import json
import re
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from ..crypto.bls.api import SecretKey
from ..utils.logger import get_logger
from .keystore import KeystoreError, decrypt_keystore
from .slashing_protection import SlashingProtection

logger = get_logger("keymanager")


class KeymanagerApi:
    """Route logic, server-agnostic (testable without sockets)."""

    def __init__(self, store, protection: SlashingProtection, index_resolver=None,
                 client=None):
        self.store = store  # ValidatorStore
        self.protection = protection
        # pubkey -> validator index; None = unknown (not yet activated)
        self.index_resolver = index_resolver or (lambda pk: None)
        # ValidatorClient, for fee-recipient / gas-limit defaults
        self.client = client
        self._fee_recipients: Dict[bytes, bytes] = {}
        self._gas_limits: Dict[bytes, int] = {}

    def _placeholder_index(self) -> int:
        """Synthetic negative index for a not-yet-activated key: strictly
        below every existing index so deletes can never make two imports
        collide (len-based schemes reuse freed slots)."""
        indices = list(self.store.pubkeys) + list(self.store.keys)
        return min([0] + indices) - 1

    def list_keystores(self) -> dict:
        data = [
            {
                "validating_pubkey": "0x" + pk.hex(),
                "derivation_path": "",
                "readonly": False,
            }
            for pk in sorted(self.store.pubkeys.values())
        ]
        return {"data": data}

    def import_keystores(self, body: dict) -> dict:
        keystores = body.get("keystores", [])
        passwords = body.get("passwords", [])
        interchange = body.get("slashing_protection")
        if interchange:
            self.protection.import_interchange(
                json.loads(interchange) if isinstance(interchange, str) else interchange
            )
        # one status per submitted keystore, always: unmatched trailing
        # entries get explicit error statuses instead of being silently
        # dropped by zip (keymanager API contract)
        if len(passwords) < len(keystores):
            passwords = list(passwords) + [None] * (len(keystores) - len(passwords))
        statuses = []
        for raw, password in zip(keystores, passwords):
            if password is None:
                statuses.append({"status": "error", "message": "missing password"})
                continue
            try:
                ks = json.loads(raw) if isinstance(raw, str) else raw
                secret = decrypt_keystore(ks, password)
                sk = SecretKey.from_bytes(secret)
                pk = sk.to_public_key().to_bytes()
                if pk in self.store.pubkeys.values():
                    statuses.append({"status": "duplicate", "message": ""})
                    continue
                idx = self.index_resolver(pk)
                if idx is None:
                    # keep the key under a synthetic negative index until
                    # it activates; signing paths resolve by index so an
                    # unknown validator simply has no duties yet
                    idx = self._placeholder_index()
                self.store.keys[idx] = sk
                self.store.pubkeys[idx] = pk
                statuses.append({"status": "imported", "message": ""})
            except (KeystoreError, ValueError, KeyError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return {"data": statuses}

    # -- remotekeys namespace (keymanager routes.ts remote-key CRUD) -------

    def list_remote_keys(self) -> dict:
        store = self.store
        local = set(store.keys)
        data = [
            {
                "pubkey": "0x" + pk.hex(),
                "url": getattr(store.remote_signer, "url", ""),
                "readonly": False,
            }
            for i, pk in sorted(store.pubkeys.items())
            if i not in local
        ]
        return {"data": data}

    def import_remote_keys(self, body: dict) -> dict:
        """POST /eth/v1/remotekeys: register pubkeys whose signatures come
        from the remote signer.  Indices resolve like keystore imports."""
        statuses = []
        for entry in body.get("remote_keys", []):
            try:
                pk = bytes.fromhex(entry["pubkey"][2:])
                if pk in self.store.pubkeys.values():
                    statuses.append({"status": "duplicate", "message": ""})
                    continue
                if self.store.remote_signer is None:
                    statuses.append(
                        {"status": "error", "message": "no remote signer configured"}
                    )
                    continue
                idx = self.index_resolver(pk)
                if idx is None:
                    idx = self._placeholder_index()
                self.store.pubkeys[idx] = pk
                statuses.append({"status": "imported", "message": ""})
            except (ValueError, KeyError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return {"data": statuses}

    def delete_remote_keys(self, body: dict) -> dict:
        statuses = []
        for pk in body.get("pubkeys", []):
            raw = bytes.fromhex(pk[2:])
            idx = next(
                (
                    i
                    for i, p in self.store.pubkeys.items()
                    if p == raw and i not in self.store.keys
                ),
                None,
            )
            if idx is None:
                statuses.append({"status": "not_found", "message": ""})
                continue
            del self.store.pubkeys[idx]
            statuses.append({"status": "deleted", "message": ""})
        return {"data": statuses}

    # -- per-validator feerecipient / gas_limit (keymanager routes.ts) -----
    # Single source of truth: the ValidatorClient's override maps (which
    # the preparation/registration services read).  The private maps only
    # exist for the client-less (standalone API) configuration.

    def _fr_map(self):
        return (
            self.client.fee_recipient_overrides
            if self.client is not None
            else self._fee_recipients
        )

    def _gl_map(self):
        return (
            self.client.gas_limit_overrides
            if self.client is not None
            else self._gas_limits
        )

    def get_fee_recipient(self, pubkey_hex: str) -> dict:
        fr = self._fr_map().get(bytes.fromhex(pubkey_hex[2:]))
        if fr is None and self.client is not None:
            fr = self.client.fee_recipient
        return {
            "data": {
                "pubkey": pubkey_hex,
                "ethaddress": "0x" + (fr or b"\x00" * 20).hex(),
            }
        }

    def set_fee_recipient(self, pubkey_hex: str, body: dict) -> dict:
        self._fr_map()[bytes.fromhex(pubkey_hex[2:])] = bytes.fromhex(
            body["ethaddress"][2:]
        )
        return {}

    def delete_fee_recipient(self, pubkey_hex: str) -> dict:
        self._fr_map().pop(bytes.fromhex(pubkey_hex[2:]), None)
        return {}

    def get_gas_limit(self, pubkey_hex: str) -> dict:
        gl = self._gl_map().get(bytes.fromhex(pubkey_hex[2:]))
        if gl is None and self.client is not None:
            gl = self.client.gas_limit
        return {"data": {"pubkey": pubkey_hex, "gas_limit": str(gl or 30_000_000)}}

    def set_gas_limit(self, pubkey_hex: str, body: dict) -> dict:
        self._gl_map()[bytes.fromhex(pubkey_hex[2:])] = int(body["gas_limit"])
        return {}

    def delete_gas_limit(self, pubkey_hex: str) -> dict:
        self._gl_map().pop(bytes.fromhex(pubkey_hex[2:]), None)
        return {}

    def delete_keystores(self, body: dict) -> dict:
        statuses = []
        for pk in body.get("pubkeys", []):
            raw = bytes.fromhex(pk[2:])
            idx = next((i for i, p in self.store.pubkeys.items() if p == raw), None)
            if idx is None:
                statuses.append({"status": "not_found", "message": ""})
                continue
            if self.store.keys.pop(idx, None) is None:
                # remote-only pubkey: not a local keystore (keymanager spec
                # says report it, don't 500 the whole request)
                statuses.append({"status": "not_found", "message": "remote key"})
                continue
            del self.store.pubkeys[idx]
            statuses.append({"status": "deleted", "message": ""})
        # export the whole protection history for the deleted keys' owner
        # (keymanager spec: the response carries the interchange)
        return {
            "data": statuses,
            "slashing_protection": json.dumps(self.protection.export_interchange()),
        }


class KeymanagerServer:
    """Minimal asyncio HTTP host for the keymanager routes (the VC-side
    analog of BeaconRestApiServer; bearer-token auth like the reference's
    keymanager server).

    Auth is ON by default: like the reference (which always writes an
    api-token.txt and enforces it), a missing token is GENERATED, not
    skipped — key import/delete and fee-recipient routes must never be
    open by accident.  Pass ``require_auth=False`` to explicitly disable
    (tests/local tooling only).  ``token_path`` persists the generated
    token for operator tooling."""

    def __init__(
        self,
        api: KeymanagerApi,
        token: Optional[str] = None,
        host: str = "127.0.0.1",
        require_auth: bool = True,
        token_path: Optional[str] = None,
    ):
        self.api = api
        if token is None and require_auth:
            import secrets

            token = "api-token-0x" + secrets.token_hex(32)
            if token_path:
                import os

                with open(token_path, "w") as fh:
                    fh.write(token)
                os.chmod(token_path, 0o600)
        self.token = token
        self.host = host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def listen(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._conn, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("keymanager API on http://%s:%d", self.host, self.port)
        return self.port

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _conn(self, reader, writer):
        try:
            line = await reader.readline()
            if not line:
                return
            method, target, _ = line.decode().split()
            headers = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))
            status, payload = self._dispatch(method, urlparse(target).path, headers, body)
            data = json.dumps(payload).encode()
            writer.write(
                b"HTTP/1.1 %d %s\r\ncontent-type: application/json\r\n"
                % (status, b"OK" if status < 400 else b"Error")
                + b"content-length: %d\r\n\r\n" % len(data)
                + data
            )
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _dispatch(self, method: str, path: str, headers: dict, body: bytes):
        if self.token:
            import hmac

            auth = headers.get("authorization", "")
            if not hmac.compare_digest(auth, f"Bearer {self.token}"):
                return 401, {"code": 401, "message": "missing or bad bearer token"}
        try:
            parsed = json.loads(body) if body else {}
        except ValueError:
            return 400, {"code": 400, "message": "bad json"}
        try:
            if path == "/eth/v1/keystores":
                if method == "GET":
                    return 200, self.api.list_keystores()
                if method == "POST":
                    return 200, self.api.import_keystores(parsed)
                if method == "DELETE":
                    return 200, self.api.delete_keystores(parsed)
            if path == "/eth/v1/remotekeys":
                if method == "GET":
                    return 200, self.api.list_remote_keys()
                if method == "POST":
                    return 200, self.api.import_remote_keys(parsed)
                if method == "DELETE":
                    return 200, self.api.delete_remote_keys(parsed)
            m = re.fullmatch(r"/eth/v1/validator/(0x[0-9a-fA-F]{96})/feerecipient", path)
            if m:
                if method == "GET":
                    return 200, self.api.get_fee_recipient(m.group(1))
                if method == "POST":
                    return 202, self.api.set_fee_recipient(m.group(1), parsed)
                if method == "DELETE":
                    return 204, self.api.delete_fee_recipient(m.group(1))
            m = re.fullmatch(r"/eth/v1/validator/(0x[0-9a-fA-F]{96})/gas_limit", path)
            if m:
                if method == "GET":
                    return 200, self.api.get_gas_limit(m.group(1))
                if method == "POST":
                    return 202, self.api.set_gas_limit(m.group(1), parsed)
                if method == "DELETE":
                    return 204, self.api.delete_gas_limit(m.group(1))
            return 404, {"code": 404, "message": f"no route {method} {path}"}
        except Exception as e:  # noqa: BLE001
            return 500, {"code": 500, "message": str(e)}
