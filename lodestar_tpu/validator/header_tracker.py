"""ChainHeaderTracker: follow the node's head via the events SSE stream.

Reference: packages/validator/src/services/chainHeaderTracker.ts — the VC
subscribes to head events so attestation duties fire the moment the
slot's block arrives instead of blind at the 1/3-slot clock mark
(VERDICT r3 item 9).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..api.client import ApiClient
from ..utils.logger import get_logger

logger = get_logger("header-tracker")


class ChainHeaderTracker:
    def __init__(self, api: ApiClient):
        self.api = api
        self.head_slot: int = -1
        self.head_root: Optional[str] = None
        self.events_seen = 0
        self._waiters: Dict[int, asyncio.Event] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                async for name, data in self.api.events("head"):
                    if name != "head":
                        continue
                    slot = int(data["slot"])
                    self.head_slot = max(self.head_slot, slot)
                    self.head_root = data["block"]
                    self.events_seen += 1
                    ev = self._waiters.pop(slot, None)
                    if ev is not None:
                        ev.set()
                # clean EOF also backs off: an immediately-closing server
                # must not become a tight reconnect loop
                logger.warning("events stream ended; reconnecting in 1s")
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - reconnect on stream loss
                # WARNING, not debug: a node without /eth/v1/events leaves
                # the VC degraded to clock-only attesting — say so
                logger.warning("events stream unavailable (%s); retrying", e)
            await asyncio.sleep(1.0)

    async def wait_for_slot_head(self, slot: int, timeout: float) -> bool:
        """True when the head for `slot` arrived (possibly already);
        False when the deadline passed first — the caller then attests on
        the clock, exactly the reference's fallback."""
        if self.head_slot >= slot:
            return True
        ev = self._waiters.setdefault(slot, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._waiters.pop(slot, None)
