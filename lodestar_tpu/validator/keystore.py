"""EIP-2335 keystores: scrypt/pbkdf2 KDF + AES-128-CTR, plus keystore
directory loading for the validator client.

Reference: packages/cli/src/cmds/account/ (eth2 wallet/keystore manager)
and the @chainsafe/bls-keystore dep it builds on.  The cipher is a
self-contained AES-128-CTR (the payload is one 32-byte secret — two
blocks; a C cipher would be overkill and the image bans new deps).
Vectors: the EIP-2335 spec test keystores (scrypt + pbkdf2) pass
round-trip in tests/test_keystore.py.
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets as _secrets
import unicodedata
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# AES-128 (encrypt-only core; CTR mode needs no decrypt direction)
# ---------------------------------------------------------------------------

_SBOX = None


def _build_sbox() -> bytes:
    # multiplicative inverse in GF(2^8) + affine transform (FIPS-197)
    inv = [0] * 256
    p, q = 1, 1
    while True:
        # p *= 3 in GF(2^8)
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        # q /= 3
        q ^= (q << 1) & 0xFF
        q ^= (q << 2) & 0xFF
        q ^= (q << 4) & 0xFF
        if q & 0x80:
            q ^= 0x09
        inv[p] = q
        if p == 1:
            break
    inv[0] = 0
    sbox = bytearray(256)
    for i in range(256):
        x = inv[i] if i else 0
        x = x ^ ((x << 1) | (x >> 7)) & 0xFF ^ ((x << 2) | (x >> 6)) & 0xFF \
            ^ ((x << 3) | (x >> 5)) & 0xFF ^ ((x << 4) | (x >> 4)) & 0xFF ^ 0x63
        sbox[i] = x & 0xFF
    return bytes(sbox)


def _sbox() -> bytes:
    global _SBOX
    if _SBOX is None:
        _SBOX = _build_sbox()
        # FIPS-197 KAT pins the table construction
        assert _SBOX[0x00] == 0x63 and _SBOX[0x53] == 0xED and _SBOX[0xFF] == 0x16
    return _SBOX


def _xtime(b: int) -> int:
    return ((b << 1) ^ 0x1B) & 0xFF if b & 0x80 else b << 1


def _aes128_key_schedule(key: bytes) -> List[bytes]:
    sbox = _sbox()
    words = [key[i : i + 4] for i in range(0, 16, 4)]
    rcon = 1
    for i in range(4, 44):
        tmp = words[i - 1]
        if i % 4 == 0:
            tmp = bytes(
                (sbox[tmp[1]] ^ (rcon if j == 0 else 0)) if j == 0 else sbox[tmp[(j + 1) % 4]]
                for j in range(4)
            )
            rcon = _xtime(rcon)
        words.append(bytes(a ^ b for a, b in zip(words[i - 4], tmp)))
    return [b"".join(words[4 * r : 4 * r + 4]) for r in range(11)]


def _aes128_encrypt_block(rks: List[bytes], block: bytes) -> bytes:
    sbox = _sbox()
    s = bytearray(a ^ b for a, b in zip(block, rks[0]))
    for rnd in range(1, 11):
        # SubBytes
        s = bytearray(sbox[b] for b in s)
        # ShiftRows (state is column-major: s[r + 4c])
        t = bytearray(16)
        for c in range(4):
            for r in range(4):
                t[r + 4 * c] = s[r + 4 * ((c + r) % 4)]
        s = t
        # MixColumns (skipped in the final round)
        if rnd != 10:
            m = bytearray(16)
            for c in range(4):
                a = s[4 * c : 4 * c + 4]
                m[4 * c + 0] = _xtime(a[0]) ^ (_xtime(a[1]) ^ a[1]) ^ a[2] ^ a[3]
                m[4 * c + 1] = a[0] ^ _xtime(a[1]) ^ (_xtime(a[2]) ^ a[2]) ^ a[3]
                m[4 * c + 2] = a[0] ^ a[1] ^ _xtime(a[2]) ^ (_xtime(a[3]) ^ a[3])
                m[4 * c + 3] = (_xtime(a[0]) ^ a[0]) ^ a[1] ^ a[2] ^ _xtime(a[3])
            s = m
        s = bytearray(a ^ b for a, b in zip(s, rks[rnd]))
    return bytes(s)


def aes128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    """CTR keystream XOR (works both directions)."""
    if len(key) != 16 or len(iv) != 16:
        raise ValueError("aes-128-ctr needs 16-byte key and iv")
    rks = _aes128_key_schedule(key)
    counter = int.from_bytes(iv, "big")
    out = bytearray()
    for i in range(0, len(data), 16):
        ks = _aes128_encrypt_block(rks, counter.to_bytes(16, "big"))
        counter = (counter + 1) % (1 << 128)
        chunk = data[i : i + 16]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


# ---------------------------------------------------------------------------
# EIP-2335
# ---------------------------------------------------------------------------


class KeystoreError(Exception):
    pass


def _normalize_password(password: str) -> bytes:
    # EIP-2335: NFKD normalize, strip C0/C1/Delete control codes
    norm = unicodedata.normalize("NFKD", password)
    return "".join(
        c for c in norm if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F)
    ).encode()


def _kdf(crypto: dict, password: bytes) -> bytes:
    kdf = crypto["kdf"]
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password, salt=salt, n=params["n"], r=params["r"], p=params["p"],
            dklen=params["dklen"], maxmem=2**31 - 1,
        )
    if kdf["function"] == "pbkdf2":
        if params.get("prf", "hmac-sha256") != "hmac-sha256":
            raise KeystoreError(f"unsupported prf {params.get('prf')}")
        return hashlib.pbkdf2_hmac("sha256", password, salt, params["c"], params["dklen"])
    raise KeystoreError(f"unsupported kdf {kdf['function']}")


def decrypt_keystore(keystore: dict, password: str) -> bytes:
    """Returns the 32-byte BLS secret (EIP-2335 decrypt)."""
    crypto = keystore["crypto"]
    dk = _kdf(crypto, _normalize_password(password))
    cipher_msg = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_msg).hexdigest()
    if checksum != crypto["checksum"]["message"]:
        raise KeystoreError("wrong password (checksum mismatch)")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError(f"unsupported cipher {crypto['cipher']['function']}")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return aes128_ctr(dk[:16], iv, cipher_msg)


def create_keystore(
    secret: bytes, password: str, *, path: str = "m/12381/3600/0/0/0",
    kdf: str = "scrypt", pubkey: Optional[bytes] = None,
) -> dict:
    """EIP-2335 encrypt (account-manager `create` flow)."""
    if len(secret) != 32:
        raise KeystoreError("BLS secret must be 32 bytes")
    salt = _secrets.token_bytes(32)
    pw = _normalize_password(password)
    if kdf == "scrypt":
        params = {"dklen": 32, "n": 262144, "r": 8, "p": 1, "salt": salt.hex()}
        dk = hashlib.scrypt(
            pw, salt=salt, n=params["n"], r=params["r"], p=params["p"],
            dklen=32, maxmem=2**31 - 1,
        )
        kdf_obj = {"function": "scrypt", "params": params, "message": ""}
    else:
        params = {"dklen": 32, "c": 262144, "prf": "hmac-sha256", "salt": salt.hex()}
        dk = hashlib.pbkdf2_hmac("sha256", pw, salt, params["c"], 32)
        kdf_obj = {"function": "pbkdf2", "params": params, "message": ""}
    iv = _secrets.token_bytes(16)
    cipher_msg = aes128_ctr(dk[:16], iv, secret)
    if pubkey is None:
        from ..crypto.bls.api import SecretKey

        pubkey = SecretKey.from_bytes(secret).to_public_key().to_bytes()
    return {
        "version": 4,
        "uuid": _uuid4(),
        "path": path,
        "pubkey": pubkey.hex(),
        "description": "",
        "crypto": {
            "kdf": kdf_obj,
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": hashlib.sha256(dk[16:32] + cipher_msg).hexdigest(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_msg.hex(),
            },
        },
    }


def _uuid4() -> str:
    b = bytearray(_secrets.token_bytes(16))
    b[6] = (b[6] & 0x0F) | 0x40
    b[8] = (b[8] & 0x3F) | 0x80
    h = bytes(b).hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def load_keystores_dir(
    directory: str, password: str
) -> Dict[bytes, bytes]:
    """pubkey -> secret for every keystore-*.json in `directory`
    (cmds/validator keystore import flow).  The password may also be a
    path to a file holding it (one per line matched in order is NOT
    supported — one shared password, the common lodestar setup)."""
    out: Dict[bytes, bytes] = {}
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(directory, name)) as f:
            ks = json.load(f)
        if "crypto" not in ks:
            continue
        secret = decrypt_keystore(ks, password)
        pk = bytes.fromhex(ks["pubkey"]) if ks.get("pubkey") else None
        if pk is None:
            from ..crypto.bls.api import SecretKey

            pk = SecretKey.from_bytes(secret).to_public_key().to_bytes()
        out[pk] = secret
    return out
