"""Remote-signer client (web3signer-compatible HTTP API).

Reference: packages/validator/src/services/validatorStore.ts:80
(SignerType.Remote → requestSignature posting to an external signer) and
packages/validator/src/util/externalSignerClient.ts (POST
/api/v1/eth2/sign/{pubkey} with the signing root; GET
/api/v1/eth2/publicKeys).

The signing paths in ValidatorStore stay synchronous (they gate on
slashing protection before any bytes leave the process), so this client
is deliberately blocking http.client, not asyncio.
"""

from __future__ import annotations

import http.client
import json
import ssl
from typing import List
from urllib.parse import urlparse


class RemoteSignerError(Exception):
    pass


class RemoteSignerClient:
    def __init__(self, url: str, timeout: float = 5.0):
        self.url = url
        parsed = urlparse(url)
        self.host = parsed.hostname or "127.0.0.1"
        self.scheme = parsed.scheme or "http"
        self.port = parsed.port or (443 if self.scheme == "https" else 80)
        self.timeout = timeout

    def _connect(self):
        """https URLs negotiate TLS with certificate verification — signing
        requests must never leave the process in cleartext against a TLS
        signer (advisor round-4 finding)."""
        if self.scheme == "https":
            return http.client.HTTPSConnection(
                self.host,
                self.port,
                timeout=self.timeout,
                context=ssl.create_default_context(),
            )
        return http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)

    def _request(self, method: str, path: str, body: dict | None = None):
        conn = self._connect()
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(
                method, path, body=payload,
                headers={"content-type": "application/json"} if payload else {},
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise RemoteSignerError(f"remote signer {resp.status}: {data[:200]!r}")
            return json.loads(data) if data else None
        except (OSError, ValueError) as e:
            raise RemoteSignerError(f"remote signer unreachable: {e}") from e
        finally:
            conn.close()

    def public_keys(self) -> List[bytes]:
        """GET /api/v1/eth2/publicKeys -> the keys this signer holds."""
        keys = self._request("GET", "/api/v1/eth2/publicKeys") or []
        return [bytes.fromhex(k[2:] if k.startswith("0x") else k) for k in keys]

    def sign(self, pubkey: bytes, signing_root: bytes, sign_type: str = "BEACON") -> bytes:
        """POST /api/v1/eth2/sign/{pubkey}: the signer only ever sees the
        32-byte signing root — message construction and slashing
        protection stay on our side."""
        resp = self._request(
            "POST",
            f"/api/v1/eth2/sign/0x{bytes(pubkey).hex()}",
            {"type": sign_type, "signingRoot": "0x" + bytes(signing_root).hex()},
        )
        sig = resp["signature"] if isinstance(resp, dict) else resp
        return bytes.fromhex(sig[2:] if sig.startswith("0x") else sig)

    def up_check(self) -> bool:
        try:
            self._request("GET", "/upcheck")
            return True
        except RemoteSignerError:
            return False
