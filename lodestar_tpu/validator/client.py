"""ValidatorClient: duty polling + production over the REST API.

Reference: packages/validator/src/validator.ts:60 (orchestrator),
services/block.ts (produce->sign->publish), services/attestation.ts:22
(duties->attestation_data->sign->submit).  The client is clock-agnostic:
`run_slot(slot)` performs the duties for one slot so tests (and a real
timer loop) drive it explicitly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api.client import ApiClient
from ..api.serde import from_json, to_json
from ..config.chain_config import ChainConfig
from ..params import Preset
from ..ssz import Fields
from ..state_transition import compute_epoch_at_slot
from ..utils.logger import get_logger
from .store import ValidatorStore

logger = get_logger("validator")


class ValidatorClient:
    def __init__(self, preset: Preset, cfg: ChainConfig, store: ValidatorStore, api: ApiClient,
                 doppelganger_epochs: int = 0,
                 fee_recipient: bytes = b"\x00" * 20,
                 gas_limit: int = 30_000_000,
                 builder_enabled: bool = False):
        self.p = preset
        self.cfg = cfg
        self.store = store
        self.api = api
        # proposer preparation + builder registration config
        # (services/prepareBeaconProposer.ts, --suggestedFeeRecipient flag)
        self.fee_recipient = fee_recipient
        self.gas_limit = gas_limit
        self.builder_enabled = builder_enabled
        # per-pubkey overrides, written by the keymanager
        # feerecipient/gas_limit routes (keymanager routes.ts)
        self.fee_recipient_overrides: Dict[bytes, bytes] = {}
        self.gas_limit_overrides: Dict[bytes, int] = {}
        self._attester_duties: Dict[int, List[dict]] = {}  # epoch -> duties
        self._proposer_duties: Dict[int, List[dict]] = {}
        # doppelganger protection (validator.ts + services/doppelgangerService):
        # observe N full epochs of chain liveness before signing anything;
        # if one of our validators attests during the window, another
        # instance is live with our keys -> refuse to start
        self.doppelganger_epochs = doppelganger_epochs
        self._doppelganger_window: Optional[set] = None
        # optional ChainHeaderTracker (services/chainHeaderTracker.ts):
        # when present, attestations trigger on the head SSE event
        self.header_tracker = None
        self.attested_on_event = 0

    def _fee_recipient_for(self, pubkey: bytes) -> bytes:
        return self.fee_recipient_overrides.get(bytes(pubkey), self.fee_recipient)

    def _gas_limit_for(self, pubkey: bytes) -> int:
        return self.gas_limit_overrides.get(bytes(pubkey), self.gas_limit)

    class DoppelgangerDetected(Exception):
        pass

    async def check_doppelganger(self, current_epoch: int) -> bool:
        """True once EVERY epoch of the observation window has been probed
        clean via the liveness API.  Raises DoppelgangerDetected if any of
        our validators was seen attesting in a probed epoch.

        Window = the immediately-past epoch plus the next
        ``doppelganger_epochs`` epochs; an epoch becomes probe-able only
        after it has completed, so the final window epoch is actually
        queried before the check clears (the reference's
        doppelgangerService semantics)."""
        if self.doppelganger_epochs == 0:
            return True
        if self._doppelganger_window is None:
            self._doppelganger_window = set(
                range(max(0, current_epoch - 1), current_epoch + self.doppelganger_epochs)
            )
        indices = [str(i) for i in self.store.pubkeys]
        for epoch in sorted(self._doppelganger_window):
            if epoch >= current_epoch:
                continue  # not complete yet — probe on a later call
            try:
                resp = await self.api.post(
                    f"/eth/v1/validator/liveness/{epoch}", indices
                )
            except Exception:
                return False  # cannot prove liveness either way: keep waiting
            live = [d for d in resp.get("data", []) if d.get("is_live")]
            if live:
                raise self.DoppelgangerDetected(
                    f"validators {[d['index'] for d in live]} are live elsewhere"
                )
            self._doppelganger_window.discard(epoch)
        return not self._doppelganger_window

    # -- duties (services/attestationDuties.ts / blockDuties.ts) --------------

    async def poll_duties(self, epoch: int) -> None:
        indices = [str(i) for i in self.store.pubkeys]
        att = await self.api.post(f"/eth/v1/validator/duties/attester/{epoch}", indices)
        self._attester_duties[epoch] = att["data"]
        prop = await self.api.get(f"/eth/v1/validator/duties/proposer/{epoch}")
        ours = {str(i) for i in self.store.pubkeys}
        self._proposer_duties[epoch] = [
            d for d in prop["data"] if d["validator_index"] in ours
        ]
        # advertise committee subnets for the polled duties (the
        # AttnetsService feed, services/attestationDuties.ts subscriptions)
        subs = [
            {
                "validator_index": d["validator_index"],
                "committee_index": d["committee_index"],
                "committees_at_slot": d.get("committees_at_slot", 1),
                "slot": d["slot"],
                "is_aggregator": True,
            }
            for d in att["data"]
        ]
        if subs:
            try:
                await self.api.post(
                    "/eth/v1/validator/beacon_committee_subscriptions", subs
                )
            except Exception:  # noqa: BLE001 - advertisement is best-effort
                pass
        # re-send proposer preparations every epoch so the entries survive
        # the node's PROPOSER_PRESERVE_EPOCHS pruning
        # (services/prepareBeaconProposer.ts)
        try:
            await self.prepare_beacon_proposer()
            if self.builder_enabled:
                await self.register_validators()
        except Exception:  # noqa: BLE001 - preparation is best-effort
            pass

    async def prepare_beacon_proposer(self) -> None:
        entries = [
            {
                "validator_index": str(i),
                "fee_recipient": "0x" + self._fee_recipient_for(pk).hex(),
            }
            for i, pk in self.store.pubkeys.items()
        ]
        if entries:
            await self.api.post("/eth/v1/validator/prepare_beacon_proposer", entries)

    async def register_validators(self, timestamp: Optional[int] = None) -> None:
        """Sign + submit builder registrations for every managed validator
        (services/validatorRegistration — DOMAIN_APPLICATION_BUILDER)."""
        import time as _time

        ts = int(timestamp if timestamp is not None else _time.time())
        regs = [
            to_json(
                self.store.sign_validator_registration(
                    i, self._fee_recipient_for(pk), self._gas_limit_for(pk), ts
                )
            )
            for i, pk in self.store.pubkeys.items()
        ]
        if regs:
            await self.api.post("/eth/v1/validator/register_validator", regs)

    # -- block production ------------------------------------------------------

    async def propose_if_due(self, slot: int) -> Optional[bytes]:
        epoch = compute_epoch_at_slot(self.p, slot)
        if epoch not in self._proposer_duties:
            await self.poll_duties(epoch)
        duty = next(
            (d for d in self._proposer_duties[epoch] if int(d["slot"]) == slot), None
        )
        if duty is None:
            return None
        vi = int(duty["validator_index"])
        randao = self.store.sign_randao(vi, epoch)
        # builder path first when enabled (services/block.ts
        # produceBlindedBlock preference), full production as fallback
        blinded = False
        resp = None
        if self.builder_enabled:
            try:
                resp = await self.api.get(
                    f"/eth/v1/validator/blinded_blocks/{slot}?randao_reveal=0x{randao.hex()}"
                )
                blinded = True
            except Exception:  # noqa: BLE001 - builder down -> local block
                resp = None
        if resp is None:
            resp = await self.api.get(
                f"/eth/v2/validator/blocks/{slot}?randao_reveal=0x{randao.hex()}"
            )
        block = from_json(resp["data"])
        sig = self.store.sign_block(vi, block)
        publish_path = "/eth/v1/beacon/blinded_blocks" if blinded else "/eth/v1/beacon/blocks"
        out = await self.api.post(
            publish_path, to_json(Fields(message=block, signature=sig))
        )
        root = bytes.fromhex(out["data"]["root"][2:])
        logger.info("proposed block at slot %d: %s", slot, root.hex()[:12])
        return root

    # -- attestations ----------------------------------------------------------

    async def attest(self, slot: int) -> int:
        epoch = compute_epoch_at_slot(self.p, slot)
        if epoch not in self._attester_duties:
            await self.poll_duties(epoch)
        duties = [d for d in self._attester_duties[epoch] if int(d["slot"]) == slot]
        submitted = 0
        by_committee: Dict[int, List[dict]] = {}
        for d in duties:
            by_committee.setdefault(int(d["committee_index"]), []).append(d)
        for committee_index, ds in by_committee.items():
            resp = await self.api.get(
                f"/eth/v1/validator/attestation_data?slot={slot}&committee_index={committee_index}"
            )
            data = from_json(resp["data"])
            atts = []
            for d in ds:
                vi = int(d["validator_index"])
                sig = self.store.sign_attestation(vi, data)
                bits = [False] * int(d["committee_length"])
                bits[int(d["validator_committee_index"])] = True
                atts.append(to_json(Fields(aggregation_bits=bits, data=data, signature=sig)))
            await self.api.post("/eth/v1/beacon/pool/attestations", atts)
            submitted += len(atts)
        return submitted

    # -- aggregation (services/attestation.ts aggregation phase) ---------------

    async def aggregate(self, slot: int) -> int:
        """2/3-slot duty: for each committee where one of our validators is
        an aggregator, fetch the pool aggregate and publish a signed
        AggregateAndProof."""
        from ..chain.validation import is_aggregator
        from ..types import get_types

        t = get_types(self.p).phase0
        epoch = compute_epoch_at_slot(self.p, slot)
        duties = [d for d in self._attester_duties.get(epoch, []) if int(d["slot"]) == slot]
        submitted = 0
        done_committees = set()
        for d in duties:
            committee_index = int(d["committee_index"])
            if committee_index in done_committees:
                continue
            vi = int(d["validator_index"])
            proof = self.store.sign_selection_proof(vi, slot)
            if not is_aggregator(self.p, int(d["committee_length"]), proof):
                continue
            done_committees.add(committee_index)
            resp = await self.api.get(
                f"/eth/v1/validator/attestation_data?slot={slot}&committee_index={committee_index}"
            )
            data = from_json(resp["data"])
            data_root = t.AttestationData.hash_tree_root(data)
            try:
                agg_resp = await self.api.get(
                    f"/eth/v1/validator/aggregate_attestation?slot={slot}"
                    f"&attestation_data_root=0x{data_root.hex()}"
                )
            except Exception:
                continue  # nothing in the pool for this committee
            aggregate = from_json(agg_resp["data"])
            anp = Fields(
                aggregator_index=vi, aggregate=aggregate, selection_proof=proof
            )
            sig = self.store.sign_aggregate_and_proof(vi, anp)
            await self.api.post(
                "/eth/v1/validator/aggregate_and_proofs",
                [to_json(Fields(message=anp, signature=sig))],
            )
            submitted += 1
        return submitted

    # -- sync-committee duties (services/syncCommittee.ts) ---------------------

    async def sync_committee_duties(self, slot: int) -> int:
        """Sign + submit sync-committee messages over the head root; for
        aggregator validators, fetch the pooled contribution and publish a
        signed ContributionAndProof."""
        from ..chain.sync_committee_pools import is_sync_committee_aggregator

        indices = [str(i) for i in self.store.pubkeys]
        epoch = compute_epoch_at_slot(self.p, slot)
        try:
            duties = (await self.api.post(f"/eth/v1/validator/duties/sync/{epoch}", indices))["data"]
        except Exception:
            return 0  # pre-altair node
        if not duties:
            return 0
        head = await self.api.get("/eth/v1/beacon/headers/head")
        head_root = bytes.fromhex(head["data"]["root"][2:])
        msgs = []
        for d in duties:
            vi = int(d["validator_index"])
            msgs.append(to_json(self.store.sign_sync_committee_message(vi, slot, head_root)))
        await self.api.post("/eth/v1/beacon/pool/sync_committees", msgs)
        submitted = len(msgs)
        # aggregation phase
        done_subs = set()
        for d in duties:
            vi = int(d["validator_index"])
            for sub_s in d["validator_sync_committee_indices"]:
                sub = int(sub_s)
                if sub in done_subs:
                    continue
                proof = self.store.sign_sync_selection_proof(vi, slot, sub)
                if not is_sync_committee_aggregator(self.p, proof):
                    continue
                done_subs.add(sub)
                try:
                    c = await self.api.get(
                        f"/eth/v1/validator/sync_committee_contribution?slot={slot}"
                        f"&subcommittee_index={sub}&beacon_block_root=0x{head_root.hex()}"
                    )
                except Exception:
                    continue
                contribution = from_json(c["data"])
                msg = Fields(
                    aggregator_index=vi, contribution=contribution, selection_proof=proof
                )
                sig = self.store.sign_contribution_and_proof(vi, msg)
                await self.api.post(
                    "/eth/v1/validator/contribution_and_proofs",
                    [to_json(Fields(message=msg, signature=sig))],
                )
        return submitted

    async def run_slot(self, slot: int, head_wait_s: float = 0.0) -> None:
        if self.doppelganger_epochs:
            # no duty signs anything until the observation window clears
            if not await self.check_doppelganger(compute_epoch_at_slot(self.p, slot)):
                logger.info("doppelganger window open — skipping duties for slot %d", slot)
                return
        await self.propose_if_due(slot)
        if self.header_tracker is not None and head_wait_s > 0:
            # attest the moment the slot's block lands (head SSE event)
            # rather than blind at the clock mark; the timeout is the
            # 1/3-slot fallback (chainHeaderTracker.ts semantics)
            on_event = await self.header_tracker.wait_for_slot_head(slot, head_wait_s)
            if on_event:
                self.attested_on_event += 1
        await self.attest(slot)
        await self.aggregate(slot)
        await self.sync_committee_duties(slot)
