"""Validator client: duty services + signing with slashing protection.

Reference surface: packages/validator/src/ (validator.ts:60 orchestrator,
services/attestation.ts:22, services/block.ts, slashingProtection/index.ts:30
with the EIP-3076 interchange format).
"""

from .client import ValidatorClient  # noqa: F401
from .header_tracker import ChainHeaderTracker  # noqa: F401
from .slashing_protection import SlashingProtection, SlashingError  # noqa: F401
from .store import ValidatorStore  # noqa: F401
