"""ValidatorStore: keys + every signing path, gated by slashing protection.

Reference: packages/validator/src/services/validatorStore.ts (signBlock,
signAttestation, signAggregateAndProof, signRandao, signVoluntaryExit) —
every signature a VC can make flows through this object so the slashing
protection gate is unbypassable.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..config.chain_config import ChainConfig
from ..crypto.bls.api import SecretKey
from ..params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    Preset,
)
from ..ssz import Fields, uint64
from ..state_transition import (
    compute_domain,
    compute_epoch_at_slot,
    compute_signing_root,
)
from ..types import get_types
from .slashing_protection import SlashingProtection


class ValidatorStore:
    def __init__(
        self,
        preset: Preset,
        cfg: ChainConfig,
        keys: Dict[int, SecretKey],
        slashing_protection: Optional[SlashingProtection] = None,
        genesis_validators_root: bytes = b"\x00" * 32,
    ):
        self.p = preset
        self.cfg = cfg
        self.keys = keys
        self.t = get_types(preset).phase0
        self.gvr = genesis_validators_root
        self.protection = slashing_protection or SlashingProtection(genesis_validators_root)
        self.pubkeys = {i: sk.to_public_key().to_bytes() for i, sk in keys.items()}

    def _domain(self, domain_type: bytes, epoch: int) -> bytes:
        from ..config.fork_config import ForkConfig

        fork_version = ForkConfig(self.cfg).get_fork_info_at_epoch(epoch).version
        return compute_domain(self.p, domain_type, fork_version, self.gvr)

    # -- signing paths ---------------------------------------------------------

    def sign_randao(self, validator_index: int, epoch: int) -> bytes:
        domain = self._domain(DOMAIN_RANDAO, epoch)
        root = compute_signing_root(self.p, uint64, epoch, domain)
        return self.keys[validator_index].sign(root).to_bytes()

    def sign_block(self, validator_index: int, block) -> bytes:
        from ..state_transition.upgrade import block_types

        epoch = compute_epoch_at_slot(self.p, block.slot)
        domain = self._domain(DOMAIN_BEACON_PROPOSER, epoch)
        root = compute_signing_root(
            self.p, block_types(self.p, block).BeaconBlock, block, domain
        )
        pk = self.pubkeys[validator_index]
        self.protection.check_and_insert_block_proposal(pk, block.slot, root)
        return self.keys[validator_index].sign(root).to_bytes()

    def sign_attestation(self, validator_index: int, data) -> bytes:
        domain = self._domain(DOMAIN_BEACON_ATTESTER, data.target.epoch)
        root = compute_signing_root(self.p, self.t.AttestationData, data, domain)
        pk = self.pubkeys[validator_index]
        self.protection.check_and_insert_attestation(
            pk, data.source.epoch, data.target.epoch, root
        )
        return self.keys[validator_index].sign(root).to_bytes()

    def sign_selection_proof(self, validator_index: int, slot: int) -> bytes:
        epoch = compute_epoch_at_slot(self.p, slot)
        domain = self._domain(DOMAIN_SELECTION_PROOF, epoch)
        root = compute_signing_root(self.p, uint64, slot, domain)
        return self.keys[validator_index].sign(root).to_bytes()

    def sign_aggregate_and_proof(self, validator_index: int, aggregate_and_proof) -> bytes:
        epoch = compute_epoch_at_slot(self.p, aggregate_and_proof.aggregate.data.slot)
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF, epoch)
        root = compute_signing_root(
            self.p, self.t.AggregateAndProof, aggregate_and_proof, domain
        )
        return self.keys[validator_index].sign(root).to_bytes()

    def sign_voluntary_exit(self, validator_index: int, exit_epoch: int) -> Fields:
        msg = Fields(epoch=exit_epoch, validator_index=validator_index)
        domain = self._domain(DOMAIN_VOLUNTARY_EXIT, exit_epoch)
        root = compute_signing_root(self.p, self.t.VoluntaryExit, msg, domain)
        return Fields(
            message=msg, signature=self.keys[validator_index].sign(root).to_bytes()
        )
