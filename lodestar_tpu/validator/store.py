"""ValidatorStore: keys + every signing path, gated by slashing protection.

Reference: packages/validator/src/services/validatorStore.ts (signBlock,
signAttestation, signAggregateAndProof, signRandao, signVoluntaryExit) —
every signature a VC can make flows through this object so the slashing
protection gate is unbypassable.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..config.chain_config import ChainConfig
from ..crypto.bls.api import SecretKey
from ..params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    Preset,
)
from ..ssz import Fields, uint64
from ..state_transition import (
    compute_domain,
    compute_epoch_at_slot,
    compute_signing_root,
)
from ..types import get_types
from .slashing_protection import SlashingProtection


class ValidatorStore:
    def __init__(
        self,
        preset: Preset,
        cfg: ChainConfig,
        keys: Dict[int, SecretKey],
        slashing_protection: Optional[SlashingProtection] = None,
        genesis_validators_root: bytes = b"\x00" * 32,
        remote_signer=None,
        remote_keys: Optional[Dict[int, bytes]] = None,
        dev_signing: bool = False,
    ):
        self.p = preset
        self.cfg = cfg
        self.keys = keys
        # Signing-path discipline: production signing uses the
        # constant-time-safe native ladder (fb_sign_ct — uniform operation
        # sequence, no key-dependent branching).  ``dev_signing=True`` is
        # the explicit dev/interop opt-in for the variable-time
        # double-and-add path (fb_sign): ~2x faster, and its timing
        # leaks the scalar — acceptable ONLY for published interop keys
        # (dev chains, sim fixtures, spec-vector generation).
        self.dev_signing = dev_signing
        self.t = get_types(preset).phase0
        self.gvr = genesis_validators_root
        self.protection = slashing_protection or SlashingProtection(genesis_validators_root)
        self.pubkeys = {i: sk.to_public_key().to_bytes() for i, sk in keys.items()}
        # remote-signer validators (validatorStore.ts SignerType.Remote):
        # we hold only the pubkey; every signing root goes over HTTP.
        # Slashing protection still gates BEFORE the request leaves.
        self.remote_signer = remote_signer
        if remote_keys:
            self.pubkeys.update(remote_keys)

    def _sign(self, validator_index: int, root: bytes) -> bytes:
        sk = self.keys.get(validator_index)
        if sk is not None:
            return sk.sign(root, variable_time=self.dev_signing).to_bytes()
        if self.remote_signer is None:
            raise KeyError(f"no signer for validator {validator_index}")
        return self.remote_signer.sign(self.pubkeys[validator_index], root)

    def _domain(self, domain_type: bytes, epoch: int) -> bytes:
        from ..config.fork_config import ForkConfig

        fork_version = ForkConfig(self.cfg).get_fork_info_at_epoch(epoch).version
        return compute_domain(self.p, domain_type, fork_version, self.gvr)

    # -- signing paths ---------------------------------------------------------

    def sign_randao(self, validator_index: int, epoch: int) -> bytes:
        domain = self._domain(DOMAIN_RANDAO, epoch)
        root = compute_signing_root(self.p, uint64, epoch, domain)
        return self._sign(validator_index, root)

    def sign_block(self, validator_index: int, block) -> bytes:
        from ..state_transition.upgrade import block_types

        epoch = compute_epoch_at_slot(self.p, block.slot)
        domain = self._domain(DOMAIN_BEACON_PROPOSER, epoch)
        t = block_types(self.p, block)
        # a blinded block signs to the SAME root as its full counterpart,
        # but needs its own container type to compute it
        block_type = (
            t.BlindedBeaconBlock
            if "execution_payload_header" in block.body
            else t.BeaconBlock
        )
        root = compute_signing_root(self.p, block_type, block, domain)
        pk = self.pubkeys[validator_index]
        self.protection.check_and_insert_block_proposal(pk, block.slot, root)
        return self._sign(validator_index, root)

    def sign_attestation(self, validator_index: int, data) -> bytes:
        domain = self._domain(DOMAIN_BEACON_ATTESTER, data.target.epoch)
        root = compute_signing_root(self.p, self.t.AttestationData, data, domain)
        pk = self.pubkeys[validator_index]
        self.protection.check_and_insert_attestation(
            pk, data.source.epoch, data.target.epoch, root
        )
        return self._sign(validator_index, root)

    def sign_selection_proof(self, validator_index: int, slot: int) -> bytes:
        epoch = compute_epoch_at_slot(self.p, slot)
        domain = self._domain(DOMAIN_SELECTION_PROOF, epoch)
        root = compute_signing_root(self.p, uint64, slot, domain)
        return self._sign(validator_index, root)

    def sign_aggregate_and_proof(self, validator_index: int, aggregate_and_proof) -> bytes:
        epoch = compute_epoch_at_slot(self.p, aggregate_and_proof.aggregate.data.slot)
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF, epoch)
        root = compute_signing_root(
            self.p, self.t.AggregateAndProof, aggregate_and_proof, domain
        )
        return self._sign(validator_index, root)

    def sign_sync_committee_message(
        self, validator_index: int, slot: int, beacon_block_root: bytes
    ) -> Fields:
        """SyncCommitteeMessage (services/syncCommittee.ts signing path)."""
        from ..params import DOMAIN_SYNC_COMMITTEE

        epoch = compute_epoch_at_slot(self.p, slot)
        domain = self._domain(DOMAIN_SYNC_COMMITTEE, epoch)
        root = self.t.SigningData.hash_tree_root(
            Fields(object_root=beacon_block_root, domain=domain)
        )
        return Fields(
            slot=slot,
            beacon_block_root=beacon_block_root,
            validator_index=validator_index,
            signature=self._sign(validator_index, root),
        )

    def sign_sync_selection_proof(
        self, validator_index: int, slot: int, subcommittee_index: int
    ) -> bytes:
        from ..params import DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF
        from ..types import get_types as _gt

        epoch = compute_epoch_at_slot(self.p, slot)
        domain = self._domain(DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
        t_alt = _gt(self.p).altair
        data = Fields(slot=slot, subcommittee_index=subcommittee_index)
        root = compute_signing_root(self.p, t_alt.SyncAggregatorSelectionData, data, domain)
        return self._sign(validator_index, root)

    def sign_contribution_and_proof(self, validator_index: int, message) -> bytes:
        from ..params import DOMAIN_CONTRIBUTION_AND_PROOF
        from ..types import get_types as _gt

        epoch = compute_epoch_at_slot(self.p, message.contribution.slot)
        domain = self._domain(DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
        t_alt = _gt(self.p).altair
        root = compute_signing_root(self.p, t_alt.ContributionAndProof, message, domain)
        return self._sign(validator_index, root)

    def sign_voluntary_exit(self, validator_index: int, exit_epoch: int) -> Fields:
        msg = Fields(epoch=exit_epoch, validator_index=validator_index)
        domain = self._domain(DOMAIN_VOLUNTARY_EXIT, exit_epoch)
        root = compute_signing_root(self.p, self.t.VoluntaryExit, msg, domain)
        return Fields(
            message=msg, signature=self._sign(validator_index, root)
        )

    def sign_validator_registration(
        self, validator_index: int, fee_recipient: bytes, gas_limit: int, timestamp: int
    ) -> Fields:
        """SignedValidatorRegistration for the MEV builder
        (validatorStore.ts signValidatorRegistration).  The builder domain
        binds the GENESIS fork version over a zero genesis_validators_root
        — registrations are valid across the fork schedule."""
        from ..params import DOMAIN_APPLICATION_BUILDER
        from ..types import get_types as _gt

        t_be = _gt(self.p).bellatrix
        msg = Fields(
            fee_recipient=bytes(fee_recipient),
            gas_limit=int(gas_limit),
            timestamp=int(timestamp),
            pubkey=self.pubkeys[validator_index],
        )
        domain = compute_domain(
            self.p,
            DOMAIN_APPLICATION_BUILDER,
            self.cfg.GENESIS_FORK_VERSION,
            b"\x00" * 32,
        )
        root = compute_signing_root(self.p, t_be.ValidatorRegistrationV1, msg, domain)
        return Fields(message=msg, signature=self._sign(validator_index, root))
