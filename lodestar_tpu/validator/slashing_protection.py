"""Slashing protection: double-vote, surround-vote, and double-proposal
guards with the EIP-3076 interchange format.

Reference: packages/validator/src/slashingProtection/ (index.ts:30;
attestation/ with MinMaxSurround, block/ with proposal uniqueness;
interchange/ for the JSON format).  Model: the min-max-surround espresso
scheme reduced to its observable contract — per validator we keep every
signed (source, target) pair and signed proposal slot, and refuse to sign
anything that is a double vote, surrounds/is surrounded by a prior vote,
or repeats a proposal slot with a different root.  The full interchange
round-trips through `export_interchange` / `import_interchange`.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple


class SlashingError(Exception):
    pass


class SlashingProtection:
    def __init__(self, genesis_validators_root: bytes = b"\x00" * 32):
        self.genesis_validators_root = genesis_validators_root
        # pubkey -> list of (source_epoch, target_epoch, signing_root)
        self._attestations: Dict[bytes, List[Tuple[int, int, bytes]]] = {}
        # pubkey -> {slot: signing_root}
        self._proposals: Dict[bytes, Dict[int, bytes]] = {}

    # -- attestations ----------------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        """Raises SlashingError if signing would be slashable; records the
        attestation otherwise.  Mirrors checkAndInsertAttestation
        (slashingProtection/index.ts:52)."""
        if source_epoch > target_epoch:
            raise SlashingError("source after target")
        hist = self._attestations.setdefault(pubkey, [])
        for s, t, root in hist:
            if t == target_epoch and root != signing_root:
                raise SlashingError(f"double vote at target {target_epoch}")
            if t == target_epoch and root == signing_root:
                return  # identical re-sign is safe
            # new surrounds old
            if source_epoch < s and target_epoch > t:
                raise SlashingError(f"surrounds prior vote ({s}->{t})")
            # old surrounds new
            if s < source_epoch and t > target_epoch:
                raise SlashingError(f"surrounded by prior vote ({s}->{t})")
        hist.append((source_epoch, target_epoch, signing_root))

    # -- proposals -------------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        """Raises SlashingError on a conflicting proposal at `slot`
        (checkAndInsertBlockProposal, block/index.ts)."""
        props = self._proposals.setdefault(pubkey, {})
        prior = props.get(slot)
        if prior is not None and prior != signing_root:
            raise SlashingError(f"double proposal at slot {slot}")
        props[slot] = signing_root

    # -- EIP-3076 interchange --------------------------------------------------

    def export_interchange(self) -> dict:
        data = []
        pubkeys = set(self._attestations) | set(self._proposals)
        for pk in sorted(pubkeys):
            data.append(
                {
                    "pubkey": "0x" + pk.hex(),
                    "signed_blocks": [
                        {"slot": str(slot), "signing_root": "0x" + root.hex()}
                        for slot, root in sorted(self._proposals.get(pk, {}).items())
                    ],
                    "signed_attestations": [
                        {
                            "source_epoch": str(s),
                            "target_epoch": str(t),
                            "signing_root": "0x" + root.hex(),
                        }
                        for s, t, root in self._attestations.get(pk, [])
                    ],
                }
            )
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + self.genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, interchange: dict) -> None:
        meta = interchange.get("metadata", {})
        gvr = meta.get("genesis_validators_root")
        if gvr and bytes.fromhex(gvr[2:]) != self.genesis_validators_root:
            raise SlashingError("interchange genesis_validators_root mismatch")
        for entry in interchange.get("data", []):
            pk = bytes.fromhex(entry["pubkey"][2:])
            for blk in entry.get("signed_blocks", []):
                root = bytes.fromhex(blk.get("signing_root", "0x" + "00" * 32)[2:])
                self._proposals.setdefault(pk, {})[int(blk["slot"])] = root
            for att in entry.get("signed_attestations", []):
                root = bytes.fromhex(att.get("signing_root", "0x" + "00" * 32)[2:])
                self._attestations.setdefault(pk, []).append(
                    (int(att["source_epoch"]), int(att["target_epoch"]), root)
                )

    def export_json(self) -> str:
        return json.dumps(self.export_interchange(), indent=2)

    def import_json(self, raw: str) -> None:
        self.import_interchange(json.loads(raw))
