"""Slashing protection: double-vote, surround-vote, and double-proposal
guards with the EIP-3076 interchange format.

Reference: packages/validator/src/slashingProtection/ (index.ts:30;
attestation/ with MinMaxSurround, block/ with proposal uniqueness;
interchange/ for the JSON format).  Model: the min-max-surround espresso
scheme reduced to its observable contract — per validator we keep every
signed (source, target) pair and signed proposal slot, and refuse to sign
anything that is a double vote, surrounds/is surrounded by a prior vote,
or repeats a proposal slot with a different root.  The full interchange
round-trips through `export_interchange` / `import_interchange`.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple


class SlashingError(Exception):
    pass


class SlashingProtection:
    """In-memory history plus an optional crash-safe store.

    When ``persist_path`` is set, every accepted record is appended to a
    write-ahead log (``<path>.wal``, one JSON line per record, fsync'd)
    BEFORE check_and_insert returns — the reference persists each record to
    its DB before releasing a signature for the same reason: an export only
    at graceful shutdown loses everything signed since startup on a crash,
    and the restarted process would happily double-sign.  ``checkpoint()``
    folds the WAL into the interchange file atomically."""

    def __init__(
        self,
        genesis_validators_root: bytes = b"\x00" * 32,
        persist_path: Optional[str] = None,
    ):
        self.genesis_validators_root = genesis_validators_root
        # pubkey -> list of (source_epoch, target_epoch, signing_root)
        self._attestations: Dict[bytes, List[Tuple[int, int, bytes]]] = {}
        # pubkey -> {slot: signing_root}
        self._proposals: Dict[bytes, Dict[int, bytes]] = {}
        self.persist_path = persist_path
        self._wal = None
        self._wal_records = 0
        # auto-fold threshold: bounds both WAL size and restart replay time
        # on long validator runs (one record per duty per key adds up)
        self.checkpoint_every = 4096
        if persist_path:
            if os.path.exists(persist_path):
                with open(persist_path) as f:
                    self.import_json(f.read())
            wal_path = persist_path + ".wal"
            if os.path.exists(wal_path):
                with open(wal_path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            # torn final line from a crash mid-append: its
                            # signature was never released (we fsync before
                            # returning), so stopping here is safe — dying
                            # at startup is not
                            break
                        self._replay_wal_record(rec)
            self._wal = open(wal_path, "a")

    def _replay_wal_record(self, rec: dict) -> None:
        pk = bytes.fromhex(rec["pubkey"])
        root = bytes.fromhex(rec["signing_root"])
        if rec["kind"] == "attestation":
            self._attestations.setdefault(pk, []).append(
                (int(rec["source_epoch"]), int(rec["target_epoch"]), root)
            )
        else:
            self._proposals.setdefault(pk, {})[int(rec["slot"])] = root

    def _wal_append(self, rec: dict) -> None:
        if self._wal is None:
            return
        self._wal.write(json.dumps(rec) + "\n")
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self._wal_records += 1

    def _maybe_auto_checkpoint(self) -> None:
        """Called by check_and_insert_* AFTER the record is in memory (a
        checkpoint taken before the in-memory insert would drop it)."""
        if self._wal is not None and self._wal_records >= self.checkpoint_every:
            self.checkpoint()

    def checkpoint(self) -> None:
        """Fold the WAL into the interchange file (atomic replace) and
        truncate it.  Called on graceful shutdown and safe to call
        periodically."""
        if not self.persist_path:
            return
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.export_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.persist_path)
        if self._wal is not None:
            self._wal.close()
            self._wal = open(self.persist_path + ".wal", "w")
        self._wal_records = 0

    def close(self) -> None:
        self.checkpoint()
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    # -- attestations ----------------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        """Raises SlashingError if signing would be slashable; records the
        attestation otherwise.  Mirrors checkAndInsertAttestation
        (slashingProtection/index.ts:52)."""
        if source_epoch > target_epoch:
            raise SlashingError("source after target")
        hist = self._attestations.setdefault(pubkey, [])
        for s, t, root in hist:
            if t == target_epoch and root != signing_root:
                raise SlashingError(f"double vote at target {target_epoch}")
            if t == target_epoch and root == signing_root:
                return  # identical re-sign is safe
            # new surrounds old
            if source_epoch < s and target_epoch > t:
                raise SlashingError(f"surrounds prior vote ({s}->{t})")
            # old surrounds new
            if s < source_epoch and t > target_epoch:
                raise SlashingError(f"surrounded by prior vote ({s}->{t})")
        # durable before the caller may release a signature
        self._wal_append(
            {
                "kind": "attestation",
                "pubkey": pubkey.hex(),
                "source_epoch": source_epoch,
                "target_epoch": target_epoch,
                "signing_root": signing_root.hex(),
            }
        )
        hist.append((source_epoch, target_epoch, signing_root))
        self._maybe_auto_checkpoint()

    # -- proposals -------------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        """Raises SlashingError on a conflicting proposal at `slot`
        (checkAndInsertBlockProposal, block/index.ts)."""
        props = self._proposals.setdefault(pubkey, {})
        prior = props.get(slot)
        if prior is not None and prior != signing_root:
            raise SlashingError(f"double proposal at slot {slot}")
        if prior is None:
            self._wal_append(
                {
                    "kind": "proposal",
                    "pubkey": pubkey.hex(),
                    "slot": slot,
                    "signing_root": signing_root.hex(),
                }
            )
        props[slot] = signing_root
        self._maybe_auto_checkpoint()

    # -- EIP-3076 interchange --------------------------------------------------

    def export_interchange(self) -> dict:
        data = []
        pubkeys = set(self._attestations) | set(self._proposals)
        for pk in sorted(pubkeys):
            data.append(
                {
                    "pubkey": "0x" + pk.hex(),
                    "signed_blocks": [
                        {"slot": str(slot), "signing_root": "0x" + root.hex()}
                        for slot, root in sorted(self._proposals.get(pk, {}).items())
                    ],
                    "signed_attestations": [
                        {
                            "source_epoch": str(s),
                            "target_epoch": str(t),
                            "signing_root": "0x" + root.hex(),
                        }
                        for s, t, root in self._attestations.get(pk, [])
                    ],
                }
            )
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + self.genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, interchange: dict) -> None:
        meta = interchange.get("metadata", {})
        gvr = meta.get("genesis_validators_root")
        if gvr and bytes.fromhex(gvr[2:]) != self.genesis_validators_root:
            raise SlashingError("interchange genesis_validators_root mismatch")
        for entry in interchange.get("data", []):
            pk = bytes.fromhex(entry["pubkey"][2:])
            for blk in entry.get("signed_blocks", []):
                root = bytes.fromhex(blk.get("signing_root", "0x" + "00" * 32)[2:])
                self._proposals.setdefault(pk, {})[int(blk["slot"])] = root
            for att in entry.get("signed_attestations", []):
                root = bytes.fromhex(att.get("signing_root", "0x" + "00" * 32)[2:])
                self._attestations.setdefault(pk, []).append(
                    (int(att["source_epoch"]), int(att["target_epoch"]), root)
                )
        # migrated protection history must be durable BEFORE any signature
        # is released: a crash between a keymanager import and the next
        # auto-checkpoint would otherwise re-enable double-signing
        # (advisor round-4 finding)
        if self.persist_path:
            self.checkpoint()

    def export_json(self) -> str:
        return json.dumps(self.export_interchange(), indent=2)

    def import_json(self, raw: str) -> None:
        self.import_interchange(json.loads(raw))
