"""In-flight dispatch table + stall watchdog.

``TpuBlsVerifier.dispatch`` registers every enqueued batch in the
process-wide ``INFLIGHT`` table; the first ``PendingVerdict.result()``
resolves it (the same exactly-once release path that returns the
executor slot).  The table is therefore an always-current answer to
"which batches are on which device right now" — the REST health
endpoint reads it live, every diagnostic bundle snapshots it, and the
``Watchdog`` thread scans it for entries that have been in flight past
a deadline.

A stall is the silent failure mode of an asynchronous device pipeline:
jax dispatch returns immediately, so a wedged Mosaic program (or a hung
device tunnel) produces no exception anywhere — the verdict simply
never resolves and the pool's flusher blocks forever.  The watchdog
turns that silence into evidence: a journal ERROR event, a
``lodestar_bls_watchdog_stalls_total{device}`` increment, and one
automatic diagnostic bundle naming the stalled cid and device.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .journal import JOURNAL, EventJournal


class InflightTable:
    """Registry of dispatched-but-unresolved batches.  All operations are
    O(entries-in-flight) or better; the table is tiny (pipeline_depth x
    n_devices entries) so snapshotting it in a crash path is safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, Dict[str, Any]] = {}
        self._next = 0

    def register(self, cid: Optional[int] = None, device: Optional[str] = None,
                 bucket: Optional[int] = None, sets: Optional[int] = None,
                 deadline_s: Optional[float] = None) -> int:
        """Record one enqueued batch; returns the token ``resolve`` takes.
        ``deadline_s`` is the batch's remaining QoS-deadline headroom at
        dispatch time (negative = already expired) — it rides every
        snapshot so a stall bundle can say whether the wedged work still
        mattered."""
        entry = {
            "cid": cid,
            "device": device,
            "bucket": bucket,
            "sets": sets,
            "deadline_s": deadline_s,
            "t0_ns": time.monotonic_ns(),
            "stalled": False,
        }
        with self._lock:
            token = self._next
            self._next += 1
            self._entries[token] = entry
        return token

    def resolve(self, token: int) -> None:
        with self._lock:
            self._entries.pop(token, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def snapshot(self, now_ns: Optional[int] = None) -> List[Dict[str, Any]]:
        """Current in-flight batches with ages (oldest first)."""
        if now_ns is None:
            now_ns = time.monotonic_ns()
        with self._lock:
            entries = [(tok, dict(e)) for tok, e in self._entries.items()]
        out = []
        for tok, e in sorted(entries, key=lambda te: te[1]["t0_ns"]):
            e["token"] = tok
            e["age_s"] = round((now_ns - e.pop("t0_ns")) / 1e9, 3)
            out.append(e)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- watchdog support ----------------------------------------------------

    def flag_stalled(self, deadline_s: float,
                     now_ns: Optional[int] = None) -> List[Dict[str, Any]]:
        """Mark entries older than ``deadline_s`` as stalled and return
        the NEWLY flagged ones (each entry trips at most once, so one
        wedge yields one stall event + one bundle, not one per scan)."""
        if now_ns is None:
            now_ns = time.monotonic_ns()
        limit_ns = int(deadline_s * 1e9)
        fresh: List[Dict[str, Any]] = []
        with self._lock:
            for tok, e in self._entries.items():
                if not e["stalled"] and now_ns - e["t0_ns"] > limit_ns:
                    e["stalled"] = True
                    snap = dict(e)
                    snap["token"] = tok
                    snap["age_s"] = round((now_ns - snap.pop("t0_ns")) / 1e9, 3)
                    fresh.append(snap)
        return fresh


#: process-wide singleton the verifier registers into
INFLIGHT = InflightTable()


class Watchdog:
    """Daemon thread flagging in-flight batches unresolved past a
    deadline.  ``on_stall(entries)`` is the dump hook (the
    ``FlightRecorder`` passes its bundle writer); metric and journal
    emission happen here so the hook can stay dump-only."""

    def __init__(self, deadline_s: float = 30.0,
                 interval_s: Optional[float] = None,
                 inflight: InflightTable = INFLIGHT,
                 journal: EventJournal = JOURNAL,
                 metrics=None,
                 on_stall: Optional[Callable[[List[Dict[str, Any]]], Any]] = None):
        self.deadline_s = deadline_s
        self.interval_s = interval_s if interval_s is not None else max(
            0.05, deadline_s / 4.0
        )
        self.inflight = inflight
        self.journal = journal
        self.metrics = metrics
        self.on_stall = on_stall
        self.stalls = 0  # cumulative stalled-entry count
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def check_once(self) -> List[Dict[str, Any]]:
        """One scan (the thread loop body, callable directly in tests):
        journal + count + metric every newly stalled entry, then fire the
        dump hook once for the batch of them."""
        stalled = self.inflight.flag_stalled(self.deadline_s)
        if not stalled:
            return stalled
        self.stalls += len(stalled)
        for e in stalled:
            self.journal.record(
                "watchdog.stall", level="ERROR", cid=e.get("cid"),
                device=e.get("device"), bucket=e.get("bucket"),
                sets=e.get("sets"), age_s=e.get("age_s"),
                deadline_s=self.deadline_s,
            )
            if self.metrics is not None:
                self.metrics.bls_watchdog_stalls_total.labels(
                    device=str(e.get("device"))
                ).inc()
        if self.on_stall is not None:
            try:
                self.on_stall(stalled)
            except Exception:  # the dump path must never kill the scanner
                pass
        return stalled

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:
                pass

    def start(self) -> "Watchdog":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="forensics-watchdog"
        )
        self._thread.start()
        self.journal.record(
            "watchdog.start", deadline_s=self.deadline_s,
            interval_s=round(self.interval_s, 3),
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def state(self) -> Dict[str, Any]:
        return {
            "running": self.running,
            "deadline_s": self.deadline_s,
            "interval_s": round(self.interval_s, 3),
            "stalls": self.stalls,
        }
