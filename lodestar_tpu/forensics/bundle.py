"""Diagnostic bundle writer: one directory that answers "what was this
node doing when it died".

A bundle is written on unhandled exception, SIGTERM/SIGUSR2, a watchdog
stall, on demand via ``GET /eth/v1/lodestar/forensics``, and (as a
heartbeat) by bench stage children so a killed child still leaves its
last-known state behind.  Layout (``tools/inspect_bundle.py`` validates
and summarizes it):

    bundle-<reason>-<pid>-<seq>/
      manifest.json    schema, reason, wall time, file list, counts,
                       stalled-batch table (written LAST — a manifest
                       implies every listed file landed)
      journal.jsonl    event-journal tail, one JSON object per line
      trace.json       Chrome trace-event dump of the span tracer
      inflight.json    in-flight batch table + per-device counts +
                       verifier/pool counters
      metrics.prom     Prometheus text exposition (when a registry is wired)
      topology.json    device topology (only when a JAX backend is already
                       initialized — a crash path must never trigger
                       backend init)
      profile.json     mesh-observatory capture state: open/last profile
                       window, attribution summary, measured overhead
      config.json      argv, python/jax versions, LODESTAR*/JAX*/XLA env

Every section is individually fault-isolated: a broken producer records
an error string in the manifest instead of aborting the dump — partial
evidence beats none, and the writer must be safe to call from signal
handlers and excepthooks.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from ..chaos import CHAOS
from ..tracing import TRACER, to_chrome_trace
from .journal import JOURNAL
from .watchdog import INFLIGHT

BUNDLE_SCHEMA = 1
MANIFEST_NAME = "manifest.json"

_SEQ = itertools.count()


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, default=str)


def _pool_stats(pool) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for attr in ("inflight_peak", "pipeline_depth", "batch_retries",
                 "batch_sets_success"):
        if hasattr(pool, attr):
            out[attr] = getattr(pool, attr)
    if hasattr(pool, "pending_sets"):
        out["pending_sets"] = pool.pending_sets()
    return out


def _verifier_stats(verifier) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": type(verifier).__name__}
    for attr in ("dispatches", "sets_verified", "fused_fallbacks",
                 "pack_rejected", "n_devices", "batches_requeued",
                 "native_fallbacks"):
        if hasattr(verifier, attr):
            out[attr] = getattr(verifier, attr)
    if hasattr(verifier, "device_inflight"):
        out["device_inflight"] = verifier.device_inflight()
    if hasattr(verifier, "executor_health"):
        # the self-healing pool's state machine — the chaos triage
        # section of tools/inspect_bundle.py reads this
        out["health"] = verifier.executor_health()
    if hasattr(verifier, "stage_seconds"):
        out["stage_seconds"] = {
            k: round(v, 4) for k, v in dict(verifier.stage_seconds).items()
        }
    return out


def _topology() -> Dict[str, Any]:
    """Device topology WITHOUT forcing backend init: if jax was never
    imported (or no backend is live yet) we report that instead of
    paying — or hanging on — a backend bring-up inside a crash path."""
    out: Dict[str, Any] = {
        "jax_imported": "jax" in sys.modules,
        "env_platforms": os.environ.get("JAX_PLATFORMS"),
    }
    if "jax" not in sys.modules:
        return out
    try:
        import jax

        out["jax_version"] = jax.__version__
        out["default_backend"] = jax.default_backend()
        out["devices"] = [
            {"id": d.id, "platform": d.platform, "kind": getattr(d, "device_kind", "")}
            for d in jax.devices()
        ]
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _profile_state() -> Dict[str, Any]:
    """Mesh-observatory capture state (docs/observability.md §Mesh
    observatory): whether a profile window is open, the last window's
    summary (batch attribution + scaling loss), and the capture's
    measured overhead — lazy import so a crash path never pays for (or
    dies in) the observatory package."""
    from ..observatory.xprof import get_capture

    cap = get_capture()
    if cap is None:
        return {"configured": False}
    out: Dict[str, Any] = {"configured": True}
    out.update(cap.snapshot())
    return out


def _config() -> Dict[str, Any]:
    env = {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith(("LODESTAR", "JAX", "XLA", "BENCH"))
    }
    return {
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "cwd": os.getcwd(),
        "env": env,
    }


def write_bundle(
    base_dir: str,
    reason: str,
    *,
    journal=JOURNAL,
    tracer=TRACER,
    inflight=INFLIGHT,
    metrics_registry=None,
    pool=None,
    verifier=None,
    extra: Optional[Dict[str, Any]] = None,
    journal_tail: int = 2048,
) -> str:
    """Write one diagnostic bundle under ``base_dir`` and return its
    directory path.  Never raises past directory creation — per-section
    failures land in ``manifest["errors"]``."""
    reason_slug = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)
    name = f"bundle-{reason_slug}-{os.getpid()}-{next(_SEQ)}"
    path = os.path.join(base_dir, name)
    os.makedirs(path, exist_ok=True)

    files: List[str] = []
    errors: Dict[str, str] = {}

    def section(fname: str, producer) -> None:
        try:
            # chaos seam: an armed plan can fail any section's IO — the
            # per-section isolation below is exactly what it exercises
            if CHAOS.armed:
                CHAOS.maybe_raise("forensics.io", section=fname)
            producer(os.path.join(path, fname))
            files.append(fname)
        except Exception as e:  # noqa: BLE001
            errors[fname] = f"{type(e).__name__}: {e}"

    section("journal.jsonl",
            lambda p: open(p, "w").write(journal.to_jsonl(journal_tail)))
    section("trace.json", lambda p: _write_json(p, to_chrome_trace(tracer)))
    inflight_snapshot = inflight.snapshot()
    section(
        "inflight.json",
        lambda p: _write_json(p, {
            "inflight": inflight_snapshot,
            "pool": _pool_stats(pool) if pool is not None else None,
            "verifier": _verifier_stats(verifier) if verifier is not None else None,
        }),
    )
    if metrics_registry is not None:
        section("metrics.prom",
                lambda p: open(p, "wb").write(metrics_registry.expose()))
    section("topology.json", lambda p: _write_json(p, _topology()))
    section("profile.json", lambda p: _write_json(p, _profile_state()))
    section("config.json", lambda p: _write_json(p, _config()))

    manifest: Dict[str, Any] = {
        "schema": BUNDLE_SCHEMA,
        "reason": reason,
        "created_unix": round(time.time(), 3),
        "pid": os.getpid(),
        "files": files,
        "journal": {"events": len(journal), "dropped": journal.dropped,
                    "capacity": journal.capacity},
        "trace": {"spans": len(tracer), "dropped": tracer.dropped,
                  "enabled": tracer.enabled},
        "inflight": inflight_snapshot,
        "stalled": [e for e in inflight_snapshot if e.get("stalled")],
    }
    if CHAOS.armed or CHAOS.injected:
        # an armed (or previously-fired) fault plan is evidence: the
        # bundle must say which faults were induced, with which seed
        manifest["chaos"] = CHAOS.state()
    if extra:
        manifest.update(extra)
    if errors:
        manifest["errors"] = errors
    # manifest last: its presence marks the bundle complete/consistent
    _write_json(os.path.join(path, MANIFEST_NAME), manifest)
    return path


def prune_bundles(base_dir: str, keep: int) -> None:
    """Drop the oldest ``bundle-*`` directories beyond ``keep`` (heartbeat
    writers call this so a long run doesn't fill the scratch disk)."""
    try:
        entries = [
            os.path.join(base_dir, n)
            for n in os.listdir(base_dir)
            if n.startswith("bundle-") and os.path.isdir(os.path.join(base_dir, n))
        ]
    except OSError:
        return
    entries.sort(key=lambda p: os.path.getmtime(p), reverse=True)
    for stale in entries[keep:]:
        try:
            for fname in os.listdir(stale):
                os.unlink(os.path.join(stale, fname))
            os.rmdir(stale)
        except OSError:
            pass


def latest_bundle(base_dir: str, pid: Optional[int] = None) -> Optional[str]:
    """Newest bundle under ``base_dir`` that has a complete manifest (the
    salvage reader: heartbeat bundles from a killed child are read by the
    parent through this).  ``pid`` scopes the search to bundles written
    by that process — the bench parent passes its dead child's pid so a
    stale bundle from a PREVIOUS run is never attributed to this
    failure."""
    try:
        candidates = [
            os.path.join(base_dir, n)
            for n in os.listdir(base_dir)
            if n.startswith("bundle-")
        ]
    except OSError:
        return None
    best: Optional[str] = None
    best_mtime = -1.0
    for cand in candidates:
        manifest = os.path.join(cand, MANIFEST_NAME)
        try:
            with open(manifest) as f:
                meta = json.load(f)
            mtime = os.path.getmtime(manifest)
        except (OSError, ValueError):
            continue
        if pid is not None and meta.get("pid") != pid:
            continue
        if mtime > best_mtime:
            best, best_mtime = cand, mtime
    return best
