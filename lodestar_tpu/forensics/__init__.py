"""Flight recorder & failure forensics (docs/observability.md §Failure
forensics).

Three cooperating pieces, all bounded-memory and safe to leave on in
production:

- ``journal``   — the always-on black-box event ring (JAX compiles,
  dispatch placement, pool flushes, degradations, WARNING+ logs) with a
  ``logging.Handler`` bridge and a ``jax.monitoring`` listener.
- ``watchdog``  — the process-wide in-flight dispatch table
  (``INFLIGHT``) plus the stall scanner that turns a silently wedged
  device batch into a metric, a journal ERROR, and an automatic bundle.
- ``bundle`` / ``recorder`` — diagnostic bundle writer and the
  ``RECORDER`` singleton wiring it to signals (SIGTERM/SIGUSR2),
  unhandled exceptions, faulthandler, the watchdog, and the REST
  ``GET /eth/v1/lodestar/forensics`` endpoint.
- ``salvage``   — bench.py stage-child heartbeats, so a timed-out child
  still leaves a last-known bundle for the parent to attach to
  ``extras.stage_errors``.

Inspect any bundle with ``python tools/inspect_bundle.py BUNDLE_DIR``.
"""

from .bundle import BUNDLE_SCHEMA, latest_bundle, prune_bundles, write_bundle
from .journal import (
    JOURNAL,
    EventJournal,
    JournalHandler,
    install_jax_monitoring,
)
from .recorder import RECORDER, FlightRecorder, default_forensics_dir
from .watchdog import INFLIGHT, InflightTable, Watchdog

__all__ = [
    "BUNDLE_SCHEMA",
    "EventJournal",
    "FlightRecorder",
    "INFLIGHT",
    "InflightTable",
    "JOURNAL",
    "JournalHandler",
    "RECORDER",
    "Watchdog",
    "default_forensics_dir",
    "install_jax_monitoring",
    "latest_bundle",
    "prune_bundles",
    "write_bundle",
]
