"""EventJournal: the always-on black-box recorder of the flight recorder.

Where the span tracer (``lodestar_tpu/tracing``) answers "where did batch
N spend its time" and is OFF by default, the journal answers "what was
the node DOING when it died" and is ON by default: a fixed-size ring of
structured events — JAX compile/cache activity, dispatch placement
decisions, pool flush/coalesce choices, fused→XLA degradations, and
every WARNING/ERROR log record — cheap enough to leave running in
production (one dict append under a short lock per *event*, never per
signature set), bounded no matter how long the process lives, and
readable after the fact from a diagnostic bundle (``forensics/bundle``).

The BENCH_r05 incident is the design input: the process died rc=124 with
a truncated stderr tail as the only evidence.  With the journal running,
the last events before death (the Mosaic compile that never returned,
the dispatch that was in flight) survive in the ring and ride out in the
bundle.

Discipline mirrors ``SpanTracer``:

- ``enabled`` is a plain bool read before any work (default True — the
  journal is the always-on half of the observability stack);
- bounded memory via ``collections.deque(maxlen=capacity)``; ``dropped``
  counts evictions so a dump can say how much history it is missing
  (surfaced as ``lodestar_forensics_journal_dropped_total``);
- thread safety via one short lock (events come from the asyncio loop,
  ``asyncio.to_thread`` workers, the warmup daemon, and the watchdog);
- timestamps are ``time.monotonic_ns()`` for ordering against spans,
  PLUS a wall-clock second for post-mortem correlation with external
  logs (the journal is not the tracer: a stepped wall clock in a crash
  artifact beats no wall clock at all).
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..tracing import current_batch_id

#: event fields every consumer may rely on (tools/inspect_bundle.py
#: validates each journal line against this set)
REQUIRED_EVENT_KEYS = ("seq", "ts_ns", "wall", "kind", "level")

_LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")


class EventJournal:
    """Fixed-capacity structured event ring.  Enabled by default."""

    def __init__(self, capacity: int = 4096):
        self.enabled = True
        self._lock = threading.Lock()
        self._buf: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=capacity
        )
        self.dropped = 0
        self._seq = 0

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    @property
    def seq(self) -> int:
        """Next sequence number — a watermark: every event recorded after
        reading this carries ``seq >=`` the returned value (the chaos
        campaign scopes its per-scenario journal scans with it)."""
        with self._lock:
            return self._seq

    def configure(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and capacity != self._buf.maxlen:
                self._buf = collections.deque(self._buf, maxlen=max(1, capacity))

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0
            self._seq = 0

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, level: str = "INFO",
               cid: Optional[int] = None, **fields: Any) -> None:
        """Append one event.  ``cid`` defaults to the merged-batch
        correlation id of the calling context (the same ContextVar the
        span tracer rides), so journal events line up with spans without
        the caller threading ids around."""
        if not self.enabled:
            return
        if cid is None:
            cid = current_batch_id()
        ev: Dict[str, Any] = {
            "ts_ns": time.monotonic_ns(),
            "wall": round(time.time(), 3),
            "kind": kind,
            "level": level if level in _LEVELS else "INFO",
        }
        if cid is not None:
            ev["cid"] = cid
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(ev)

    # -- reading -------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        """Snapshot, oldest first."""
        with self._lock:
            return [dict(e) for e in self._buf]

    def tail(self, n: int) -> List[Dict[str, Any]]:
        with self._lock:
            if n >= len(self._buf):
                return [dict(e) for e in self._buf]
            return [dict(e) for e in list(self._buf)[-n:]]

    def last_error(self) -> Optional[Dict[str, Any]]:
        """Most recent ERROR/CRITICAL event (the health endpoint's 'what
        broke last' answer), or None."""
        with self._lock:
            for ev in reversed(self._buf):
                if ev.get("level") in ("ERROR", "CRITICAL"):
                    return dict(ev)
        return None

    def to_jsonl(self, n: Optional[int] = None) -> str:
        events = self.tail(n) if n is not None else self.events()
        return "".join(json.dumps(e, default=str) + "\n" for e in events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


#: process-wide singleton — the black box every subsystem records into
JOURNAL = EventJournal()


class JournalHandler(logging.Handler):
    """logging.Handler that mirrors WARNING+ records into the journal, so
    'the last errors before death' survive in every diagnostic bundle
    even when stderr was truncated or lost.  Attached to the root
    ``lodestar`` logger by ``utils/logger._configure_root``."""

    def __init__(self, journal: EventJournal = JOURNAL,
                 level: int = logging.WARNING):
        super().__init__(level)
        self.journal = journal

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.journal.record(
                "log",
                level=record.levelname,
                logger=record.name,
                msg=record.getMessage(),
            )
        except Exception:  # a broken journal must never break logging
            pass


# -- JAX compile/cache monitoring -------------------------------------------

_JAX_LISTENER_INSTALLED = False
_JAX_LISTENER_LOCK = threading.Lock()

#: record compile-family durations above this (seconds); tiny throwaway
#: jits would otherwise flood the ring
JAX_COMPILE_MIN_SECS = 0.05

#: downstream consumers of the raw monitoring stream (the observatory's
#: compile ledger registers here): called as fn(event, duration) for
#: duration events and fn(event, None) for plain events, unfiltered —
#: sinks do their own thresholding/classification
_COMPILE_SINKS: List[Any] = []


def add_compile_sink(fn) -> None:
    """Register a callable on the journal's jax.monitoring feed
    (idempotent per function object)."""
    if fn not in _COMPILE_SINKS:
        _COMPILE_SINKS.append(fn)


def _notify_sinks(event: str, duration: Optional[float]) -> None:
    for fn in _COMPILE_SINKS:
        try:
            fn(event, duration)
        except Exception:  # a broken sink must never break compilation
            pass


def install_jax_monitoring(journal: EventJournal = JOURNAL) -> bool:
    """Register a ``jax.monitoring`` duration listener that journals
    compile/cache events (the ``/jax/core/compile/backend_compile_duration``
    hook tests/conftest.py already relies on — it fires for fresh
    compiles AND persistent-cache loads, which is exactly the 'was a
    compile in flight when we died' evidence BENCH_r05 lacked).

    Idempotent; returns True when the listener is (already) installed,
    False when jax is unavailable."""
    global _JAX_LISTENER_INSTALLED
    with _JAX_LISTENER_LOCK:
        if _JAX_LISTENER_INSTALLED:
            return True
        try:
            import jax
        except Exception:
            return False

        def _on_duration(event: str, duration: float = 0.0, **kw: Any) -> None:
            try:
                _notify_sinks(event, duration)
                if "compile" in event and duration >= JAX_COMPILE_MIN_SECS:
                    journal.record(
                        "jax.compile", event=event, seconds=round(duration, 3)
                    )
            except Exception:
                pass

        def _on_event(event: str, **kw: Any) -> None:
            # plain (durationless) events: the persistent-cache hit/miss
            # markers the compile ledger needs to tell a warm load from a
            # cold compile
            try:
                _notify_sinks(event, None)
            except Exception:
                pass

        try:
            jax.monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        # registration is not transactional: once the duration listener is
        # live we MUST mark installed (a retry would double-register it and
        # every compile event would be delivered twice).  The plain-event
        # listener is best-effort on top — without it the ledger just
        # loses the cache-hit markers, never correctness of durations.
        _JAX_LISTENER_INSTALLED = True
        try:
            jax.monitoring.register_event_listener(_on_event)
        except Exception:
            pass
        return True
