"""Bench-stage salvage: heartbeat bundles from spawn children.

bench.py runs every benchmark stage in a spawn subprocess with a hard
wall-clock bound; on timeout the parent SIGKILLs the child and, before
this module existed, all evidence died with it ("timeout after Ns" was
the entire post-mortem — the BENCH_r05 failure mode).  The fix is a
heartbeat: each stage child periodically snapshots a diagnostic bundle
to a scratch directory keyed by stage name, keeping only the newest few,
and the parent attaches the last-known bundle path to
``extras.stage_errors`` when the stage dies.  ``tools/inspect_bundle.py``
then answers what the child was doing — last compile event, in-flight
batches, last errors — instead of nothing.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Optional

from . import bundle as bundle_mod
from .journal import JOURNAL, install_jax_monitoring

BASE_DIR_ENV = "BENCH_FORENSICS_DIR"
INTERVAL_ENV = "BENCH_HEARTBEAT_S"
DEFAULT_INTERVAL_S = 5.0
KEEP_BUNDLES = 2


def base_dir() -> str:
    return os.environ.get(BASE_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), "lodestar-tpu-forensics", "bench"
    )


def stage_dir(stage: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in stage)
    return os.path.join(base_dir(), safe)


class Heartbeat:
    """Daemon thread writing a bundle snapshot for one stage every
    ``interval_s`` (first snapshot immediately, so even a fast-dying
    child leaves evidence)."""

    def __init__(self, stage: str, interval_s: Optional[float] = None):
        self.stage = stage
        self.dir = stage_dir(stage)
        if interval_s is None:
            interval_s = float(os.environ.get(INTERVAL_ENV, DEFAULT_INTERVAL_S))
        self.interval_s = max(0.05, interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> Optional[str]:
        try:
            path = bundle_mod.write_bundle(
                self.dir, "heartbeat", journal=JOURNAL,
                extra={"stage": self.stage},
            )
            bundle_mod.prune_bundles(self.dir, KEEP_BUNDLES)
            return path
        except OSError:
            return None

    def _run(self) -> None:
        self.beat()
        while not self._stop.wait(self.interval_s):
            self.beat()

    def start(self) -> "Heartbeat":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"forensics-heartbeat-{self.stage}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


def start_heartbeat(stage: str, interval_s: Optional[float] = None) -> Heartbeat:
    """Child-side entry (bench._stage_child): journal jax compile events
    and start the snapshot loop.  Never raises — a broken scratch disk
    must not fail the stage it is trying to protect."""
    install_jax_monitoring(JOURNAL)
    JOURNAL.record("bench.stage_start", stage=stage, pid=os.getpid())
    hb = Heartbeat(stage, interval_s)
    try:
        return hb.start()
    except Exception:
        return hb


def latest_stage_bundle(stage: str, pid: Optional[int] = None) -> Optional[str]:
    """Parent-side reader: newest complete bundle the (possibly dead)
    child left for this stage, or None.  Pass the child's ``pid`` so a
    child killed before its first heartbeat (e.g. wedged inside the jax
    import) yields None rather than a stale bundle from a previous run
    being mis-attributed to this failure."""
    return bundle_mod.latest_bundle(stage_dir(stage), pid=pid)
