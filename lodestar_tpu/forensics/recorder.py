"""FlightRecorder: the wiring hub of the forensics subsystem.

One process-wide ``RECORDER`` object owns the configuration (bundle
directory, metrics, pool/verifier references) and the dump triggers:

- ``dump(reason)``            on-demand bundle (REST endpoint, tests)
- watchdog stall              automatic bundle via ``start_watchdog``
- SIGTERM / SIGUSR2           ``install_signal_handlers`` (SIGUSR2 dumps
                              and continues — the classic "what are you
                              doing right now" poke; SIGTERM dumps, then
                              chains to the previous handler / default
                              so shutdown semantics are unchanged)
- unhandled exception         ``install_excepthook`` (bundle named after
                              the exception type, then the previous hook
                              runs so the traceback still prints)
- hard faults                 ``install_faulthandler`` points the stdlib
                              faulthandler at ``<dir>/faulthandler.log``
                              so segfault-class deaths leave stacks next
                              to the bundles

``install()`` is the one-call CLI entry (cli.py); bench stage children
use the lighter ``salvage`` heartbeat instead.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import tempfile
import threading
from typing import Any, Dict, List, Optional

from ..tracing import TRACER
from .bundle import prune_bundles, write_bundle
from .journal import JOURNAL, install_jax_monitoring
from .watchdog import INFLIGHT, Watchdog

log = logging.getLogger("lodestar.forensics")

DEFAULT_DIR_ENV = "LODESTAR_TPU_FORENSICS_DIR"


def default_forensics_dir() -> str:
    return os.environ.get(DEFAULT_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), "lodestar-tpu-forensics"
    )


class FlightRecorder:
    def __init__(self):
        self.journal = JOURNAL
        self.inflight = INFLIGHT
        self._dir: Optional[str] = None
        self.metrics = None
        self.pool = None
        self.verifier = None
        self.watchdog: Optional[Watchdog] = None
        self.bundles_written = 0
        self.keep_bundles = 16  # dump() prunes the dir beyond this
        # reentrant: a SIGTERM arriving while THIS thread is mid-dump
        # (e.g. serving the REST forensics endpoint) runs the handler on
        # the same frame — a plain Lock would deadlock the shutdown
        self._dump_lock = threading.RLock()
        self._prev_handlers: Dict[int, Any] = {}
        self._prev_excepthook = None
        self._faulthandler_file = None

    # -- configuration -------------------------------------------------------

    @property
    def dir(self) -> str:
        return self._dir or default_forensics_dir()

    def configure(self, forensics_dir: Optional[str] = None, metrics=None,
                  pool=None, verifier=None) -> "FlightRecorder":
        if forensics_dir is not None:
            self._dir = forensics_dir
        if metrics is not None:
            self.metrics = metrics
        if pool is not None:
            self.pool = pool
            if verifier is None:
                verifier = getattr(pool, "verifier", None)
        if verifier is not None:
            self.verifier = verifier
        return self

    def publish_metrics(self) -> None:
        """Refresh the drop-visibility gauges (also set at every pool
        flush — this covers nodes whose pool is idle)."""
        if self.metrics is None:
            return
        self.metrics.tracing_spans_dropped_total.set(TRACER.dropped)
        self.metrics.forensics_journal_dropped_total.set(self.journal.dropped)

    # -- dumping -------------------------------------------------------------

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None,
             metric_reason: Optional[str] = None) -> str:
        """Write one bundle and return its path.  Serialized: concurrent
        triggers (watchdog + signal) queue rather than interleave.
        ``metric_reason`` bounds the Prometheus label when ``reason``
        carries caller-controlled text (the REST endpoint passes "api" so
        query strings cannot mint unbounded label values)."""
        with self._dump_lock:
            self.publish_metrics()
            path = write_bundle(
                self.dir, reason,
                journal=self.journal, tracer=TRACER, inflight=self.inflight,
                metrics_registry=getattr(self.metrics, "reg", None),
                pool=self.pool, verifier=self.verifier, extra=extra,
            )
            self.bundles_written += 1
            if self.metrics is not None:
                self.metrics.forensics_bundles_written_total.labels(
                    reason=metric_reason or reason
                ).inc()
            self.journal.record("forensics.bundle", reason=reason, path=path)
            log.warning("forensics bundle (%s) -> %s", reason, path)
            # bounded disk: repeated triggers (watchdog storms, API polls)
            # must never fill the volume the node runs on
            prune_bundles(self.dir, self.keep_bundles)
            return path

    # -- watchdog ------------------------------------------------------------

    def start_watchdog(self, deadline_s: float,
                       interval_s: Optional[float] = None) -> Watchdog:
        if self.watchdog is not None:
            self.watchdog.stop()

        def on_stall(entries: List[Dict[str, Any]]) -> None:
            self.dump("watchdog", extra={"watchdog_stalled": entries})

        self.watchdog = Watchdog(
            deadline_s=deadline_s, interval_s=interval_s,
            inflight=self.inflight, journal=self.journal,
            metrics=self.metrics, on_stall=on_stall,
        )
        return self.watchdog.start()

    def stop_watchdog(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()

    # -- crash triggers ------------------------------------------------------

    def install_signal_handlers(self, signals=(signal.SIGTERM, signal.SIGUSR2)) -> None:
        """Main-thread only (signal module requirement).  SIGUSR2: dump
        and keep running.  Anything else (SIGTERM): dump, then chain to
        the previous disposition so the process still dies."""
        for signum in signals:
            prev = signal.getsignal(signum)
            self._prev_handlers[signum] = prev

            def handler(num, frame, _prev=prev):
                try:
                    self.dump(signal.Signals(num).name.lower())
                except Exception:
                    pass
                if num == signal.SIGUSR2:
                    return
                if _prev is signal.SIG_IGN:
                    # the process ignored this signal before we hooked it;
                    # dumping must not change that survival semantic
                    return
                if callable(_prev) and _prev is not signal.SIG_DFL:
                    _prev(num, frame)
                else:
                    signal.signal(num, signal.SIG_DFL)
                    os.kill(os.getpid(), num)

            signal.signal(signum, handler)

    def uninstall_signal_handlers(self) -> None:
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

    def install_excepthook(self) -> None:
        if self._prev_excepthook is not None:
            return
        self._prev_excepthook = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                self.journal.record(
                    "crash", level="CRITICAL",
                    exc=f"{exc_type.__name__}: {exc}",
                )
                self.dump(f"crash-{exc_type.__name__}")
            except Exception:
                pass
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = hook

    def install_faulthandler(self) -> Optional[str]:
        import faulthandler

        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, "faulthandler.log")
            self._faulthandler_file = open(path, "a")
            faulthandler.enable(file=self._faulthandler_file)
            return path
        except OSError:
            return None

    def install(self, watchdog_deadline_s: Optional[float] = None) -> "FlightRecorder":
        """The CLI's one call: jax compile monitoring, crash hooks,
        signal handlers, faulthandler, and (optionally) the watchdog."""
        install_jax_monitoring(self.journal)
        self.install_excepthook()
        self.install_faulthandler()
        try:
            self.install_signal_handlers()
        except ValueError:
            pass  # not the main thread; crash hooks still active
        if watchdog_deadline_s:
            self.start_watchdog(watchdog_deadline_s)
        self.journal.record("forensics.installed", dir=self.dir,
                            watchdog_deadline_s=watchdog_deadline_s)
        return self


#: process-wide singleton (cli.py installs it; tests configure+restore)
RECORDER = FlightRecorder()
