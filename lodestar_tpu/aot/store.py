"""Durable AOT executable store: crash-safe compile persistence.

ROADMAP item 4 calls compile time "the tax on everything": ~144 s cold
compile per device ordinal, ~25 s for a *warm* persistent-cache load
(trace + lower + deserialize still run), and a fleet doing rolling
restarts cannot pay either.  This store is the tier BELOW the persistent
XLA cache: it persists **fully-compiled executables** (JAX AOT
``lower().compile()`` + ``jax.experimental.serialize_executable``) so a
restart skips trace, lowering, AND backend compile — load is a
deserialize, seconds not minutes.

The materialization ladder the verifier walks becomes::

    _PROGRAM_MEMO (in-process)  ->  AOT store (this module)
        ->  persistent .jax_cache (trace+lower, warm backend load)
        ->  cold compile

Key schema (one entry per fully-resolved program identity)::

    (topology, entry, bucket, device ordinal, jax version, ops hash)

- **topology** — ``{platform}x{device_count}`` of the process that
  compiled (a serialized executable embeds its device assignment; a
  process with a different local topology must miss, not crash);
- **entry** — the compile-ledger entry label (``fused_split`` /
  ``fused_full`` / ``xla_split`` / ``xla_full``);
- **bucket** — the padded batch size (one program per bucket);
- **device** — the executor's pinned ordinal (``cpu:2``) or
  ``default``; executables are per-ordinal, exactly like the
  ``jit(device=d)`` programs they replace;
- **jax version + ops content-hash** — the PR 4 jaxpr-artifact
  fingerprint scheme one level lower: any change to ``lodestar_tpu/ops``
  or the jax install makes every old entry *skew*, evicted on first
  touch rather than trusted.

Crash-consistency discipline (the PR 5 bundle rules, applied to a cache):

- every entry payload is written ``<file>.tmp`` then ``os.replace``d —
  a crash mid-write leaves an orphan temp file the loader never reads;
- the manifest (the only index the loader trusts) is re-read, merged,
  and atomically replaced **last**, so a listed entry always has its
  payload on disk;
- every entry carries a sha256 of its payload file; a mismatch on load
  journals ``aot.corrupt``, quarantines the file (renamed aside, never
  deleted — it is evidence), and falls through to the next tier;
- a jax/ops fingerprint mismatch journals ``aot.skew`` and evicts;
- writers serialize through ``store.lock`` (O_CREAT|O_EXCL, pid+wall
  inside); a contended lock is a **bounded wait then bypass** — the
  save is skipped (journaled ``aot.lock_busy``), never a stall, and the
  loader takes no lock at all.

Every failure path is a journaled degradation.  Nothing in this module
may raise out of ``load``/``save`` — a broken store must cost a
recompile, never a node.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, Optional

from ..chaos import CHAOS
from ..forensics.journal import JOURNAL
from ..utils.logger import get_logger

logger = get_logger("aot-store")

#: env var naming the store directory (conftest / bench / cli all use it)
STORE_ENV = "LODESTAR_TPU_AOT_STORE"

MANIFEST_NAME = "manifest.json"
ENTRIES_DIR = "entries"
LOCK_NAME = "store.lock"
SCHEMA_VERSION = 1

#: bounded writer-lock wait before a save bypasses (seconds)
DEFAULT_LOCK_WAIT_S = 5.0


class AotStoreMiss(RuntimeError):
    """A load-only verifier asked for a program the store does not hold
    (typed so the dispatch degradation ladder can tell a policy refusal
    from an organic compile failure)."""


#: compile-side flag that makes BIG XLA:CPU executables serializable
#: cross-process (see _payload_loadable_cross_process)
CPU_SPLIT_FLAG = "--xla_cpu_parallel_codegen_split_count=1"

#: CPU payloads above this never split at codegen in practice; larger
#: ones are only trusted when the compiling process pinned CPU_SPLIT_FLAG
CPU_SAVE_MAX_BYTES = 8 << 20


def _payload_loadable_cross_process(n_bytes: int) -> bool:
    """Would a NEW process be able to deserialize this payload?

    XLA:CPU's parallel codegen splits large modules across multiple
    object files, and executable serialization keeps only one — such a
    payload deserializes fine IN-process (the jitted symbols are still
    resident) but fails in a fresh process with ``Symbols not found``.
    Persisting it would poison the store: every later restart would pay
    a quarantine + recompile + re-save churn.  Only compile processes
    that pinned ``--xla_cpu_parallel_codegen_split_count=1`` (the
    prewarm farm and the bench aot variant do) produce big CPU payloads
    worth keeping; small programs never split, and TPU executables are
    device binaries, unaffected either way."""
    if n_bytes <= CPU_SAVE_MAX_BYTES:
        return True
    try:
        import jax

        if jax.default_backend() != "cpu":
            return True
    except Exception:
        return True
    return CPU_SPLIT_FLAG in os.environ.get("XLA_FLAGS", "")


def _jax_version() -> str:
    try:
        import jax

        return jax.__version__
    except Exception:  # pragma: no cover - jax is baked into the image
        return "none"


_OPS_HASH_CACHE: Dict[str, str] = {}


def ops_content_hash() -> str:
    """Content hash of ``lodestar_tpu/ops`` — the jaxpr-audit artifact
    fingerprint scheme, one level lower: a serialized executable is only
    trusted while the kernel sources that produced it are byte-identical.
    (jax version is a separate key component; it is NOT folded in here.)
    """
    cached = _OPS_HASH_CACHE.get("ops")
    if cached is not None:
        return cached
    h = hashlib.sha256()
    h.update(f"aot-v{SCHEMA_VERSION}:".encode())
    ops_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "ops")
    for dirpath, dirnames, filenames in os.walk(ops_dir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            h.update(os.path.relpath(full, ops_dir).encode())
            with open(full, "rb") as f:
                h.update(f.read())
    digest = h.hexdigest()[:16]
    _OPS_HASH_CACHE["ops"] = digest
    return digest


def topology_tag() -> str:
    """``{platform}x{device_count}`` of this process's default backend —
    the coarse identity a serialized device assignment depends on."""
    try:
        import jax

        devs = jax.devices()
        return f"{jax.default_backend()}x{len(devs)}"
    except Exception:
        return "nonex0"


def entry_key(topology: str, entry: str, bucket: int, device: str,
              jax_version: Optional[str] = None,
              ops_hash: Optional[str] = None) -> str:
    """The canonical store key string (also the manifest dict key)."""
    return "|".join((
        topology, entry, f"b{bucket}", device,
        f"jax{jax_version or _jax_version()}",
        ops_hash or ops_content_hash(),
    ))


def _key_digest(key: str) -> str:
    return hashlib.sha256(key.encode()).hexdigest()[:24]


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


#: orphaned break-mutexes older than this are reclaimed (a breaker can
#: only crash inside a few syscalls, so seconds of age = dead breaker)
BREAK_MUTEX_STALE_S = 10.0


def _read_lock_holder(lock_path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(lock_path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # mid-write or vanished — NOT evidence of anything


def _holder_is_dead(holder: Optional[Dict[str, Any]]) -> bool:
    """True only on positive evidence the recorded pid is gone.  An
    unreadable lock, a foreign-user pid (kill -> EPERM), or garbage all
    count as alive — breaking on ambiguity would admit two writers."""
    if holder is None:
        return False
    try:
        pid = int(holder.get("pid", -1))
    except (TypeError, ValueError):
        return False
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return False
    except ProcessLookupError:
        return True
    except OSError:  # PermissionError et al: alive, just not ours
        return False


def _try_break_lock(lock_path: str, observed: Dict[str, Any],
                    store: Optional[str]) -> bool:
    """Break a stale lock WITHOUT the unlink TOCTOU: two contenders that
    both observed the dead holder must not both unlink — the second
    would delete the first's freshly re-created (live) lock.  The break
    itself is serialized through a short-lived O_EXCL break-mutex, and
    the breaker RE-reads the lock under it: only a lock still naming the
    same dead holder is removed."""
    bm = lock_path + ".break"
    try:
        if time.time() - os.path.getmtime(bm) > BREAK_MUTEX_STALE_S:
            os.unlink(bm)  # a breaker crashed mid-break; reclaim
    except OSError:
        pass
    try:
        os.close(os.open(bm, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except OSError:
        return False  # another breaker is active: let it do the job
    try:
        current = _read_lock_holder(lock_path)
        if current != observed or not _holder_is_dead(current):
            return False  # the lock changed hands (or came alive): abort
        os.unlink(lock_path)
        JOURNAL.record("aot.lock_broken", level="WARNING", store=store,
                       lock=os.path.basename(lock_path))
        return True
    except OSError:
        return False
    finally:
        release_lockfile(bm)


def acquire_lockfile(lock_path: str, timeout_s: float,
                     store: Optional[str] = None) -> bool:
    """Single-writer lockfile: O_CREAT|O_EXCL with {pid, wall} inside.
    Bounded wait, False on timeout OR on an unwritable store (callers
    bypass, never stall and never see a raise).  A lock whose recorded
    pid is provably DEAD is broken (via ``_try_break_lock``'s
    re-verified, mutex-serialized unlink) — a writer that crashed
    mid-write must not wedge every later one (its orphan temp file is
    already harmless by the temp+rename discipline).  An *unreadable*
    lock is NOT evidence of death: a contender can observe the holder's
    file in the window between its O_EXCL create and its json.dump —
    breaking on that race would admit two live writers."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                json.dump({"pid": os.getpid(), "wall": round(time.time(), 3)}, f)
            return True
        except FileExistsError:
            holder = _read_lock_holder(lock_path)
            if _holder_is_dead(holder) and _try_break_lock(
                lock_path, holder, store
            ):
                continue  # broken: retry the create immediately
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)
        except OSError:
            # unwritable lock path (read-only fs, deleted dir): the
            # caller's contract is bypass, not raise
            return False


def release_lockfile(lock_path: str) -> None:
    try:
        os.unlink(lock_path)
    except OSError:
        pass


class AotExecutableStore:
    """One directory of serialized executables + the manifest indexing
    them.  Thread-safe; cross-process writers serialize via the lockfile,
    readers are lock-free (the manifest is only ever atomically
    replaced)."""

    def __init__(self, path: Optional[str] = None,
                 lock_wait_s: float = DEFAULT_LOCK_WAIT_S):
        self._path = path
        self.lock_wait_s = lock_wait_s
        self._lock = threading.Lock()
        self._manifest: Optional[Dict[str, Any]] = None
        self._manifest_mtime: Optional[float] = None
        #: keys quarantined/evicted by THIS process (loads skip them even
        #: when the best-effort manifest rewrite could not take the lock)
        self._dead_keys: set = set()
        # counters (tier-1 ledger + bench extras + bundles read these)
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.skew = 0
        self.saves = 0
        self.save_errors = 0
        self.save_skipped = 0
        self.lock_bypasses = 0

    # -- configuration -------------------------------------------------------

    @property
    def path(self) -> Optional[str]:
        return self._path

    @property
    def enabled(self) -> bool:
        return bool(self._path)

    def configure(self, path: Optional[str] = None) -> "AotExecutableStore":
        """Point the store at its directory (``path`` wins over the
        ``LODESTAR_TPU_AOT_STORE`` env var).  Idempotent; never touches
        jax."""
        if path is None:
            path = os.environ.get(STORE_ENV) or None
        with self._lock:
            if path != self._path:
                self._path = path
                self._manifest = None
                self._manifest_mtime = None
                self._dead_keys = set()
        return self

    def _manifest_path(self) -> str:
        return os.path.join(self._path, MANIFEST_NAME)

    def _entries_dir(self) -> str:
        return os.path.join(self._path, ENTRIES_DIR)

    # -- manifest ------------------------------------------------------------

    def _read_manifest(self) -> Dict[str, Any]:
        """Parse the on-disk manifest; a corrupt/truncated manifest is a
        survivable, journaled event (the store starts empty)."""
        mpath = self._manifest_path()
        try:
            with open(mpath) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and doc.get("schema") == SCHEMA_VERSION:
                entries = doc.get("entries")
                if isinstance(entries, dict):
                    return entries
            raise ValueError(f"unsupported manifest shape/schema in {mpath}")
        except OSError:
            return {}  # no manifest yet: the normal first-run state
        except ValueError as e:
            self.corrupt += 1
            JOURNAL.record("aot.corrupt", level="WARNING", store=self._path,
                           what="manifest", error=str(e)[:200])
            logger.warning("AOT store manifest unreadable (%s); starting empty", e)
            return {}

    def _entries(self) -> Dict[str, Any]:
        """Cached manifest view, refreshed on mtime change (readers never
        take the file lock)."""
        mpath = self._manifest_path()
        try:
            mtime = os.path.getmtime(mpath)
        except OSError:
            mtime = None
        with self._lock:
            if self._manifest is not None and mtime == self._manifest_mtime:
                return self._manifest
        entries = self._read_manifest() if mtime is not None else {}
        with self._lock:
            self._manifest = entries
            self._manifest_mtime = mtime
            return self._manifest

    def _write_manifest_locked(self, entries: Dict[str, Any]) -> None:
        """Atomic manifest replace — caller holds the writer lockfile.
        The manifest is written LAST in every mutation, so a listed entry
        always has its payload on disk."""
        os.makedirs(self._path, exist_ok=True)
        tmp = f"{self._manifest_path()}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"schema": SCHEMA_VERSION, "entries": entries}, f, indent=0)
        os.replace(tmp, self._manifest_path())
        with self._lock:
            self._manifest = entries
            try:
                self._manifest_mtime = os.path.getmtime(self._manifest_path())
            except OSError:
                self._manifest_mtime = None

    # -- writer lockfile -----------------------------------------------------

    def acquire_writer(self, timeout_s: Optional[float] = None) -> bool:
        """Take the store's single-writer lockfile.  Bounded wait; False
        on timeout OR an unwritable store directory — the caller
        bypasses (skips the save) rather than stalling or raising."""
        if timeout_s is None:
            timeout_s = self.lock_wait_s
        try:
            os.makedirs(self._path, exist_ok=True)
        except OSError:
            return False
        return acquire_lockfile(
            os.path.join(self._path, LOCK_NAME), timeout_s, store=self._path
        )

    def release_writer(self) -> None:
        release_lockfile(os.path.join(self._path, LOCK_NAME))

    # -- save ----------------------------------------------------------------

    def save(self, entry: str, bucket: int, device: str, compiled,
             topology: Optional[str] = None) -> Optional[str]:
        """Serialize one compiled executable into the store.  Best-effort
        by contract: every failure journals and returns None — a store
        that cannot persist must never take warmup down with it."""
        if not self.enabled:
            return None
        try:
            from jax.experimental import serialize_executable as se

            payload = pickle.dumps(se.serialize(compiled))
        except Exception as e:  # noqa: BLE001 — unserializable backend/program
            self.save_errors += 1
            JOURNAL.record("aot.save_failed", level="WARNING", store=self._path,
                           entry=entry, bucket=bucket, device=device,
                           error=str(e)[:200])
            return None
        if not _payload_loadable_cross_process(len(payload)):
            # a payload only THIS process could load is worse than no
            # payload: it would poison every later restart into a
            # quarantine + recompile + re-save churn
            self.save_skipped += 1
            JOURNAL.record("aot.save_skipped", store=self._path, entry=entry,
                           bucket=bucket, device=device, bytes=len(payload),
                           reason="cpu_parallel_codegen")
            return None
        key = entry_key(topology or topology_tag(), entry, bucket, device)
        fname = f"{_key_digest(key)}.aotx"
        if not self.acquire_writer():
            # bounded wait expired: bypass — the program still lives in
            # the persistent cache tier; losing one save is fine
            self.lock_bypasses += 1
            JOURNAL.record("aot.lock_busy", level="WARNING", store=self._path,
                           entry=entry, bucket=bucket, device=device)
            return None
        try:
            os.makedirs(self._entries_dir(), exist_ok=True)
            fpath = os.path.join(self._entries_dir(), fname)
            tmp = f"{fpath}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            # chaos seam: the prewarmer-killed-mid-write campaign class —
            # the temp file exists, the rename and manifest never happen
            if CHAOS.armed:
                CHAOS.maybe_kill("aot.midwrite", entry=entry, bucket=bucket,
                                 device=device)
            os.replace(tmp, fpath)
            entries = dict(self._read_manifest())
            entries[key] = {
                "file": f"{ENTRIES_DIR}/{fname}",
                "sha256": hashlib.sha256(payload).hexdigest(),
                "size": len(payload),
                "topology": topology or topology_tag(),
                "entry": entry,
                "bucket": bucket,
                "device": device,
                "jax": _jax_version(),
                "ops_hash": ops_content_hash(),
                "created_unix": round(time.time(), 3),
            }
            # manifest written LAST: its row is the commit point
            self._write_manifest_locked(entries)
            self.saves += 1
            with self._lock:
                self._dead_keys.discard(key)
            JOURNAL.record("aot.save", store=self._path, entry=entry,
                           bucket=bucket, device=device, bytes=len(payload))
            return key
        except OSError as e:
            self.save_errors += 1
            JOURNAL.record("aot.save_failed", level="WARNING", store=self._path,
                           entry=entry, bucket=bucket, device=device,
                           error=str(e)[:200])
            return None
        finally:
            self.release_writer()

    # -- load ----------------------------------------------------------------

    def _quarantine(self, key: str, rec: Dict[str, Any], what: str,
                    error: str) -> None:
        """Corrupt entry: journal, move the payload aside (evidence, not
        deletion), drop the manifest row best-effort (non-blocking lock —
        contention just leaves the row for the next writer; this
        process's loads skip it via ``_dead_keys`` either way)."""
        self.corrupt += 1
        with self._lock:
            self._dead_keys.add(key)
        JOURNAL.record("aot.corrupt", level="WARNING", store=self._path,
                       what=what, entry=rec.get("entry"),
                       bucket=rec.get("bucket"), device=rec.get("device"),
                       error=error[:200])
        fpath = os.path.join(self._path, rec.get("file", ""))
        try:
            if os.path.exists(fpath):
                os.replace(fpath, fpath + ".quarantined")
        except OSError:
            pass
        self._drop_rows([key])

    def _evict(self, key: str, rec: Dict[str, Any], reason: str) -> None:
        """Version/ops skew: journal ``aot.skew``, delete the payload,
        drop the manifest row best-effort."""
        self.skew += 1
        with self._lock:
            self._dead_keys.add(key)
        JOURNAL.record("aot.skew", level="WARNING", store=self._path,
                       entry=rec.get("entry"), bucket=rec.get("bucket"),
                       device=rec.get("device"), reason=reason,
                       entry_jax=rec.get("jax"), current_jax=_jax_version())
        try:
            fpath = os.path.join(self._path, rec.get("file", ""))
            if os.path.exists(fpath):
                os.unlink(fpath)
        except OSError:
            pass
        self._drop_rows([key])

    def _drop_rows(self, keys) -> None:
        """Best-effort manifest cleanup under a NON-blocking writer lock
        (a loader must never stall on a prewarmer holding the lock)."""
        if not self.acquire_writer(timeout_s=0.0):
            return
        try:
            entries = dict(self._read_manifest())
            changed = False
            for key in keys:
                if key in entries:
                    del entries[key]
                    changed = True
            if changed:
                self._write_manifest_locked(entries)
        except OSError:
            pass
        finally:
            self.release_writer()

    def load(self, entry: str, bucket: int, device: str,
             topology: Optional[str] = None):
        """Load one executable, or None.  Every miss class is distinct
        and journaled: absent (plain miss), checksum/deserialize failure
        (``aot.corrupt`` + quarantine), jax/ops fingerprint mismatch
        (``aot.skew`` + evict).  Never raises; never takes the writer
        lock on the hot path."""
        if not self.enabled:
            return None
        key = entry_key(topology or topology_tag(), entry, bucket, device)
        with self._lock:
            if key in self._dead_keys:
                self.misses += 1
                return None
        rec = self._entries().get(key)
        if rec is None:
            self.misses += 1
            return None
        if rec.get("jax") != _jax_version():
            self._evict(key, rec, reason="jax_version")
            return None
        if rec.get("ops_hash") != ops_content_hash():
            self._evict(key, rec, reason="ops_hash")
            return None
        fpath = os.path.join(self._path, rec.get("file", ""))
        try:
            payload = open(fpath, "rb").read()
        except OSError as e:
            self._quarantine(key, rec, what="payload_missing", error=str(e))
            return None
        if hashlib.sha256(payload).hexdigest() != rec.get("sha256"):
            self._quarantine(key, rec, what="checksum", error="sha256 mismatch")
            return None
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as se

            blob, in_tree, out_tree = pickle.loads(payload)
            fn = se.deserialize_and_load(blob, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — a poisoned pickle/XLA blob
            self._quarantine(key, rec, what="deserialize", error=str(e))
            return None
        self.hits += 1
        JOURNAL.record("aot.load", store=self._path, entry=entry,
                       bucket=bucket, device=device,
                       seconds=round(time.perf_counter() - t0, 3))
        return fn

    # -- introspection -------------------------------------------------------

    def keys(self) -> Dict[str, Dict[str, Any]]:
        """Manifest snapshot (prewarm --verify and tests read this)."""
        return dict(self._entries())

    def verify(self) -> Dict[str, Any]:
        """Integrity sweep: checksum + fingerprint check of every
        manifest entry (no deserialize — a sweep must not need devices).
        Returns {"ok": [...], "corrupt": [...], "skew": [...],
        "orphans": [...]} of keys/filenames."""
        out: Dict[str, Any] = {"ok": [], "corrupt": [], "skew": [], "orphans": []}
        entries = self._entries()
        listed = set()
        for key, rec in entries.items():
            listed.add(os.path.basename(rec.get("file", "")))
            if rec.get("jax") != _jax_version() or rec.get("ops_hash") != ops_content_hash():
                out["skew"].append(key)
                continue
            fpath = os.path.join(self._path, rec.get("file", ""))
            try:
                digest = _sha256_file(fpath)
            except OSError:
                out["corrupt"].append(key)
                continue
            (out["ok"] if digest == rec.get("sha256") else out["corrupt"]).append(key)
        try:
            for name in os.listdir(self._entries_dir()):
                if name not in listed and not name.endswith(".quarantined"):
                    out["orphans"].append(name)
        except OSError:
            pass
        return out

    def sweep_orphans(self) -> int:
        """Delete unlisted temp/entry files (crashed writers leave them;
        they are never loaded, this just reclaims the disk).  Writer-lock
        bounded; 0 when the lock is contended."""
        if not self.enabled or not self.acquire_writer():
            return 0
        try:
            removed = 0
            listed = {
                os.path.basename(rec.get("file", ""))
                for rec in self._read_manifest().values()
            }
            try:
                names = os.listdir(self._entries_dir())
            except OSError:
                return 0
            for name in names:
                if name in listed or name.endswith(".quarantined"):
                    continue
                try:
                    os.unlink(os.path.join(self._entries_dir(), name))
                    removed += 1
                except OSError:
                    pass
            return removed
        finally:
            self.release_writer()

    def stats(self) -> Dict[str, Any]:
        return {
            "path": self._path,
            "entries": len(self._entries()) if self.enabled else 0,
            "hits": self.hits,
            "misses": self.misses,
            "corrupt": self.corrupt,
            "skew": self.skew,
            "saves": self.saves,
            "save_errors": self.save_errors,
            "save_skipped": self.save_skipped,
            "lock_bypasses": self.lock_bypasses,
        }


#: process-wide singleton (``configure_aot_store`` / the env var wire it);
#: tests construct private instances instead
AOT_STORE = AotExecutableStore()


def configure_aot_store(path: Optional[str] = None) -> AotExecutableStore:
    """Point the process-wide store at ``path`` (explicit arg >
    ``LODESTAR_TPU_AOT_STORE`` env > disabled).  Idempotent."""
    return AOT_STORE.configure(path)
