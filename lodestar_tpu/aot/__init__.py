"""Durable AOT executable store (ROADMAP item 4): fully-compiled XLA
executables persisted across processes so a restart loads in seconds
instead of re-paying trace + lower + compile.  See ``store.py`` for the
key schema and crash-consistency discipline, ``tools/prewarm.py`` for
the out-of-band population farm, and ``docs/aot.md`` for the runbook."""

from .store import (  # noqa: F401
    AOT_STORE,
    AotExecutableStore,
    AotStoreMiss,
    STORE_ENV,
    configure_aot_store,
    entry_key,
    ops_content_hash,
    topology_tag,
)
