"""Per-network runtime chain config.

Reference: packages/config/src/chainConfig/{types.ts,presets/mainnet.ts,
presets/minimal.ts,networks/mainnet.ts}.
"""

from __future__ import annotations

import dataclasses

from ..params.presets import UINT64_MAX


@dataclasses.dataclass(frozen=True)
class ChainConfig:
    PRESET_BASE: str

    # Transition (the merge)
    TERMINAL_TOTAL_DIFFICULTY: int = 2**256 - 1
    TERMINAL_BLOCK_HASH: bytes = b"\x00" * 32
    TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH: int = UINT64_MAX

    # Genesis
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT: int = 16384
    MIN_GENESIS_TIME: int = 1606824000
    GENESIS_FORK_VERSION: bytes = bytes.fromhex("00000000")
    GENESIS_DELAY: int = 604800

    # Fork schedule
    ALTAIR_FORK_VERSION: bytes = bytes.fromhex("01000000")
    ALTAIR_FORK_EPOCH: int = UINT64_MAX
    BELLATRIX_FORK_VERSION: bytes = bytes.fromhex("02000000")
    BELLATRIX_FORK_EPOCH: int = UINT64_MAX

    # Time parameters
    SECONDS_PER_SLOT: int = 12
    SECONDS_PER_ETH1_BLOCK: int = 14
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY: int = 256
    SHARD_COMMITTEE_PERIOD: int = 256
    ETH1_FOLLOW_DISTANCE: int = 2048

    # Validator cycle
    INACTIVITY_SCORE_BIAS: int = 4
    INACTIVITY_SCORE_RECOVERY_RATE: int = 16
    EJECTION_BALANCE: int = 16_000_000_000
    MIN_PER_EPOCH_CHURN_LIMIT: int = 4
    CHURN_LIMIT_QUOTIENT: int = 65536
    PROPOSER_SCORE_BOOST: int = 40

    # Deposit contract
    DEPOSIT_CHAIN_ID: int = 1
    DEPOSIT_NETWORK_ID: int = 1
    DEPOSIT_CONTRACT_ADDRESS: bytes = bytes.fromhex("00000000219ab540356cbb839cbe05303d7705fa")


MAINNET_CHAIN_CONFIG = ChainConfig(
    PRESET_BASE="mainnet",
    ALTAIR_FORK_EPOCH=74240,
)

MINIMAL_CHAIN_CONFIG = ChainConfig(
    PRESET_BASE="minimal",
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
    MIN_GENESIS_TIME=1578009600,
    GENESIS_FORK_VERSION=bytes.fromhex("00000001"),
    GENESIS_DELAY=300,
    ALTAIR_FORK_VERSION=bytes.fromhex("01000001"),
    ALTAIR_FORK_EPOCH=74240,
    BELLATRIX_FORK_VERSION=bytes.fromhex("02000001"),
    SECONDS_PER_SLOT=6,
    SHARD_COMMITTEE_PERIOD=64,
    ETH1_FOLLOW_DISTANCE=16,
    CHURN_LIMIT_QUOTIENT=32,
    DEPOSIT_CHAIN_ID=5,
    DEPOSIT_NETWORK_ID=5,
    DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("1234567890123456789012345678901234567890"),
)
