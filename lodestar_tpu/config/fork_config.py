"""Fork schedule helpers and fork digests.

Reference: packages/config/src/forkConfig/index.ts (getForkInfo/getForkName/
getForkSeq) and packages/config/src/beaconConfig.ts (fork digest caches keyed
by genesisValidatorsRoot).
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
from typing import Dict, List

from .chain_config import ChainConfig


class ForkName(str, enum.Enum):
    phase0 = "phase0"
    altair = "altair"
    bellatrix = "bellatrix"


FORK_SEQ = {ForkName.phase0: 0, ForkName.altair: 1, ForkName.bellatrix: 2}


@dataclasses.dataclass(frozen=True)
class ForkInfo:
    name: ForkName
    seq: int
    epoch: int
    version: bytes
    prev_version: bytes
    prev_fork_name: ForkName


class ForkConfig:
    """Fork schedule derived from a ChainConfig.

    Reference: packages/config/src/forkConfig/index.ts:18-104.
    """

    def __init__(self, cfg: ChainConfig):
        self.chain = cfg
        phase0 = ForkInfo(
            name=ForkName.phase0,
            seq=0,
            epoch=0,
            version=cfg.GENESIS_FORK_VERSION,
            prev_version=cfg.GENESIS_FORK_VERSION,
            prev_fork_name=ForkName.phase0,
        )
        altair = ForkInfo(
            name=ForkName.altair,
            seq=1,
            epoch=cfg.ALTAIR_FORK_EPOCH,
            version=cfg.ALTAIR_FORK_VERSION,
            prev_version=cfg.GENESIS_FORK_VERSION,
            prev_fork_name=ForkName.phase0,
        )
        bellatrix = ForkInfo(
            name=ForkName.bellatrix,
            seq=2,
            epoch=cfg.BELLATRIX_FORK_EPOCH,
            version=cfg.BELLATRIX_FORK_VERSION,
            prev_version=cfg.ALTAIR_FORK_VERSION,
            prev_fork_name=ForkName.altair,
        )
        self.forks: Dict[ForkName, ForkInfo] = {
            ForkName.phase0: phase0,
            ForkName.altair: altair,
            ForkName.bellatrix: bellatrix,
        }
        # Scheduled forks only (far-future = unscheduled, never selected —
        # matches the reference's `epoch >= Infinity` always-false semantics),
        # ascending by activation epoch; phase0 (epoch 0) always first.
        from ..params.presets import UINT64_MAX

        self.forks_ascending: List[ForkInfo] = sorted(
            (f for f in self.forks.values() if f.epoch < UINT64_MAX or f.seq == 0),
            key=lambda f: (f.epoch, f.seq),
        )

    def get_fork_info(self, slot: int, slots_per_epoch: int) -> ForkInfo:
        return self.get_fork_info_at_epoch(slot // slots_per_epoch)

    def get_fork_info_at_epoch(self, epoch: int) -> ForkInfo:
        current = self.forks[ForkName.phase0]
        for fork in self.forks_ascending:
            if epoch >= fork.epoch:
                current = fork
        return current

    def get_fork_version(self, epoch: int) -> bytes:
        return self.get_fork_info_at_epoch(epoch).version


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    """hash_tree_root(ForkData(current_version, genesis_validators_root)).

    ForkData is two 32-byte leaves: the 4-byte version right-padded and the
    root; its hash_tree_root is a single sha256 of their concatenation.
    Spec: compute_fork_data_root; reference uses ssz.phase0.ForkData.
    """
    leaf0 = current_version + b"\x00" * 28
    return hashlib.sha256(leaf0 + genesis_validators_root).digest()


def compute_fork_digest(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


class BeaconConfig(ForkConfig):
    """ForkConfig + genesisValidatorsRoot-dependent fork-digest caches.

    Reference: packages/config/src/beaconConfig.ts (createBeaconConfig,
    forkName2ForkDigest / forkDigest2ForkName caches).
    """

    def __init__(self, cfg: ChainConfig, genesis_validators_root: bytes):
        super().__init__(cfg)
        self.genesis_validators_root = genesis_validators_root
        self._digest_by_fork: Dict[ForkName, bytes] = {}
        self._fork_by_digest: Dict[bytes, ForkName] = {}
        for fork in self.forks.values():
            digest = compute_fork_digest(fork.version, genesis_validators_root)
            self._digest_by_fork[fork.name] = digest
            self._fork_by_digest.setdefault(digest, fork.name)

    def fork_name_to_digest(self, fork: ForkName) -> bytes:
        return self._digest_by_fork[fork]

    def digest_to_fork_name(self, digest: bytes) -> ForkName:
        try:
            return self._fork_by_digest[bytes(digest)]
        except KeyError:
            raise ValueError(f"unknown fork digest {bytes(digest).hex()}") from None


def create_beacon_config(cfg: ChainConfig, genesis_validators_root: bytes) -> BeaconConfig:
    return BeaconConfig(cfg, genesis_validators_root)
