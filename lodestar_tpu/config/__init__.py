"""Runtime chain configuration and fork schedule.

Reference: packages/config (src/chainConfig/types.ts, presets/{mainnet,minimal}.ts,
src/forkConfig/index.ts).
"""

from .chain_config import ChainConfig, MAINNET_CHAIN_CONFIG, MINIMAL_CHAIN_CONFIG
from .fork_config import ForkInfo, ForkName, ForkConfig, BeaconConfig, create_beacon_config

__all__ = [
    "ChainConfig",
    "MAINNET_CHAIN_CONFIG",
    "MINIMAL_CHAIN_CONFIG",
    "ForkInfo",
    "ForkName",
    "ForkConfig",
    "BeaconConfig",
    "create_beacon_config",
]
