"""The state transition function: process_slots + per-block transition.

Reference: packages/state-transition/src/stateTransition.ts:19
(eth2fastspec-style: verify-signatures flags so block signature checks can
be deferred to the batched device verifier) and :79 processSlots.
"""

from __future__ import annotations

import copy
from typing import Optional

from ..config.chain_config import ChainConfig
from ..config.fork_config import ForkName
from ..params import Preset
from ..types import get_types
from .block import BlockProcessingError, process_block
from .epoch import process_epoch
from .epoch_context import EpochContext
from .misc import compute_epoch_at_slot
from .upgrade import maybe_upgrade_state, state_fork_name, state_types


class StateTransitionError(Exception):
    pass


def clone_state(p: Preset, state):
    """Deep-copy a state value.  SSZ values are plain python data, so
    copy.deepcopy is correct; columnar caches (EpochContext) are rebuilt,
    not copied — they derive from the state."""
    return copy.deepcopy(state)


def process_slot(p: Preset, state) -> None:
    """Cache state root + block root for the slot (spec process_slot)."""
    t = state_types(p, state)
    prev_state_root = t.BeaconState.hash_tree_root(state)
    state.state_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = prev_state_root
    if state.latest_block_header.state_root == b"\x00" * 32:
        state.latest_block_header.state_root = prev_state_root
    block_root = t.BeaconBlockHeader.hash_tree_root(state.latest_block_header)
    state.block_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = block_root


def process_slots(
    p: Preset,
    cfg: ChainConfig,
    state,
    slot: int,
    ctx: Optional[EpochContext] = None,
) -> EpochContext:
    """Advance state (in place) to `slot`, running epoch transitions at
    boundaries.  Returns a fresh EpochContext for the final epoch."""
    if state.slot > slot:
        raise StateTransitionError(f"cannot rewind state from {state.slot} to {slot}")
    if ctx is None:
        ctx = EpochContext.create_from_state(p, state)
    while state.slot < slot:
        process_slot(p, state)
        if (state.slot + 1) % p.SLOTS_PER_EPOCH == 0:
            if state_fork_name(state) == ForkName.phase0:
                process_epoch(p, cfg, ctx, state)
            else:
                from .altair import process_epoch_altair

                process_epoch_altair(p, cfg, ctx, state)
            state.slot += 1
            ctx = EpochContext.create_from_state(
                p, state, ctx.pubkey2index, ctx.index2pubkey, prev_ctx=ctx
            )
            # fork upgrades fire on the first slot of their epoch
            # (stateTransition.ts:100-144)
            maybe_upgrade_state(p, cfg, ctx, state)
        else:
            state.slot += 1
    return ctx


def state_transition(
    p: Preset,
    cfg: ChainConfig,
    state,
    signed_block,
    ctx: Optional[EpochContext] = None,
    verify_proposer_signature: bool = True,
    verify_signatures: bool = True,
    verify_state_root: bool = True,
    collect_signature_sets: bool = False,
    include_proposer_set: bool = True,
):
    """Full per-block transition on a CLONE of `state`; returns
    (post_state, epoch_context) — or (post, ctx, sets) when
    ``collect_signature_sets`` is set.

    With verify_*=False + collect_signature_sets=True the block's signature
    sets are gathered from THIS single pass (at the slot-advanced pre-block
    state) for one batched verify dispatch — the verifyBlock.ts:152+178
    flow without re-running process_slots (round-2 weak #7).
    """
    block = signed_block.message
    post = clone_state(p, state)
    ctx = process_slots(p, cfg, post, block.slot, ctx)
    t = state_types(p, post)

    sets = None
    if collect_signature_sets:
        from .signature_sets import get_block_signature_sets

        # `post` is the pre-block state advanced to the block's slot; the
        # sets capture signing roots/pubkeys as bytes now, so the in-place
        # block processing below cannot invalidate them
        sets = get_block_signature_sets(
            p, cfg, ctx, post, signed_block, include_proposer=include_proposer_set
        )

    if verify_proposer_signature:
        from ..crypto.bls.verifier import PyBlsVerifier
        from .signature_sets import block_proposer_signature_set

        s = block_proposer_signature_set(p, ctx, post, signed_block)
        if not PyBlsVerifier().verify_signature_sets([s]):
            raise StateTransitionError("invalid block proposer signature")

    try:
        process_block(p, cfg, ctx, post, block, verify_signatures)
    except BlockProcessingError as e:
        raise StateTransitionError(str(e)) from e

    if verify_state_root:
        actual = t.BeaconState.hash_tree_root(post)
        if actual != block.state_root:
            raise StateTransitionError(
                f"state root mismatch: block {block.state_root.hex()} != computed {actual.hex()}"
            )
    if collect_signature_sets:
        return post, ctx, sets
    return post, ctx
