"""Phase0 epoch processing (consensus spec beacon-chain.md, v1.1.10).

Reference: packages/state-transition/src/epoch/ (16 files) with the
beforeProcessEpoch single-pass precompute (src/cache/epochProcess.ts:405).

The precompute (`EpochFlags`) walks the pending attestations once and
leaves per-validator boolean/int numpy columns; every reward/penalty rule
below is then a vectorized expression over those columns — the
array-oriented layout the reference chose for its hot loop, which is also
the one a future device offload consumes unchanged (SURVEY §7 hard part 5).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional

import numpy as np

from ..config.chain_config import ChainConfig
from ..params import (
    BASE_REWARDS_PER_EPOCH,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    JUSTIFICATION_BITS_LENGTH,
    Preset,
)
from ..ssz import Fields
from ..types import get_types
from .epoch_context import EpochContext, compute_epoch_shuffling
from .misc import (
    compute_activation_exit_epoch,
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_active_validator_indices,
    get_randao_mix,
    integer_squareroot,
)
from .validator_ops import get_validator_churn_limit, initiate_validator_exit


@dataclasses.dataclass
class EpochFlags:
    """Columnar per-validator attestation participation (epochProcess.ts)."""

    current_epoch: int
    previous_epoch: int
    total_active_balance: int
    active_prev: np.ndarray  # bool
    active_cur: np.ndarray  # bool
    eligible: np.ndarray  # bool: active_prev or (slashed and not yet withdrawable)
    prev_source: np.ndarray  # bool, unslashed attesters
    prev_target: np.ndarray
    prev_head: np.ndarray
    cur_target: np.ndarray
    inclusion_delay: np.ndarray  # uint64, 0 = none
    proposer_index: np.ndarray  # int64, -1 = none
    effective_balance: np.ndarray  # uint64


def before_process_epoch(p: Preset, ctx: EpochContext, state) -> EpochFlags:
    n = len(state.validators)
    current_epoch = compute_epoch_at_slot(p, state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)

    eb = np.array([v.effective_balance for v in state.validators], dtype=np.uint64)
    slashed = np.array([v.slashed for v in state.validators], dtype=bool)
    activation = np.array([v.activation_epoch for v in state.validators], dtype=np.uint64)
    exit_e = np.array([v.exit_epoch for v in state.validators], dtype=np.uint64)
    withdrawable = np.array([v.withdrawable_epoch for v in state.validators], dtype=np.uint64)

    active_prev = (activation <= previous_epoch) & (previous_epoch < exit_e)
    active_cur = (activation <= current_epoch) & (current_epoch < exit_e)
    eligible = active_prev | (slashed & (previous_epoch + 1 < withdrawable))

    total_active = int(eb[active_cur].sum())
    total_active = max(total_active, p.EFFECTIVE_BALANCE_INCREMENT)

    prev_source = np.zeros(n, dtype=bool)
    prev_target = np.zeros(n, dtype=bool)
    prev_head = np.zeros(n, dtype=bool)
    cur_target = np.zeros(n, dtype=bool)
    inclusion_delay = np.zeros(n, dtype=np.uint64)
    proposer_index = np.full(n, -1, dtype=np.int64)

    def block_root_at_slot(slot: int) -> bytes:
        return state.block_roots[slot % p.SLOTS_PER_HISTORICAL_ROOT]

    def epoch_boundary_root(epoch: int) -> bytes:
        slot = compute_start_slot_at_epoch(p, epoch)
        if slot == state.slot:
            # latest header with possibly-zero state root: matches spec
            # get_block_root semantics only for slot < state.slot; callers
            # only hit this during the epoch transition where slot < state.slot
            raise AssertionError("epoch boundary root queried at current slot")
        return block_root_at_slot(slot)

    prev_boundary = epoch_boundary_root(previous_epoch)
    cur_boundary = epoch_boundary_root(current_epoch) if state.slot > compute_start_slot_at_epoch(p, current_epoch) else None

    for att in state.previous_epoch_attestations:
        data = att.data
        committee = ctx.get_beacon_committee(data.slot, data.index)
        attesters = committee[np.asarray(att.aggregation_bits, dtype=bool)]
        # source match is a precondition of inclusion (process_attestation)
        is_target = data.target.root == prev_boundary
        is_head = data.beacon_block_root == block_root_at_slot(data.slot)
        unslashed = attesters[~slashed[attesters]]
        prev_source[unslashed] = True
        if is_target:
            prev_target[unslashed] = True
            if is_head:
                prev_head[unslashed] = True
        # min inclusion delay + its proposer (for proposer/inclusion rewards)
        for vi in attesters:
            if inclusion_delay[vi] == 0 or att.inclusion_delay < inclusion_delay[vi]:
                inclusion_delay[vi] = att.inclusion_delay
                proposer_index[vi] = att.proposer_index

    for att in state.current_epoch_attestations:
        data = att.data
        if cur_boundary is not None and data.target.root == cur_boundary:
            committee = ctx.get_beacon_committee(data.slot, data.index)
            attesters = committee[np.asarray(att.aggregation_bits, dtype=bool)]
            cur_target[attesters[~slashed[attesters]]] = True

    return EpochFlags(
        current_epoch=current_epoch,
        previous_epoch=previous_epoch,
        total_active_balance=total_active,
        active_prev=active_prev,
        active_cur=active_cur,
        eligible=eligible,
        prev_source=prev_source,
        prev_target=prev_target,
        prev_head=prev_head,
        cur_target=cur_target,
        inclusion_delay=inclusion_delay,
        proposer_index=proposer_index,
        effective_balance=eb,
    )


def process_epoch(p: Preset, cfg: ChainConfig, ctx: EpochContext, state) -> None:
    flags = before_process_epoch(p, ctx, state)
    process_justification_and_finalization(p, state, flags)
    process_rewards_and_penalties(p, cfg, state, flags)
    process_registry_updates(p, cfg, state)
    process_slashings(p, state, flags)
    process_eth1_data_reset(p, state)
    process_effective_balance_updates(p, state)
    process_slashings_reset(p, state)
    process_randao_mixes_reset(p, state)
    process_historical_roots_update(p, state)
    process_participation_record_updates(state)


# -- justification / finalization -------------------------------------------


def process_justification_and_finalization(p: Preset, state, flags: EpochFlags) -> None:
    if flags.current_epoch <= GENESIS_EPOCH + 1:
        return
    prev_target_balance = int(flags.effective_balance[flags.prev_target & flags.active_prev].sum())
    cur_target_balance = int(flags.effective_balance[flags.cur_target & flags.active_cur].sum())
    weigh_justification_and_finalization(p, state, flags, prev_target_balance, cur_target_balance)


def weigh_justification_and_finalization(
    p: Preset, state, flags: EpochFlags, prev_target_balance: int, cur_target_balance: int
) -> None:
    previous_epoch = flags.previous_epoch
    current_epoch = flags.current_epoch
    old_previous_justified = state.previous_justified_checkpoint
    old_current_justified = state.current_justified_checkpoint
    total = flags.total_active_balance

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = list(state.justification_bits)
    bits = [False] + bits[: JUSTIFICATION_BITS_LENGTH - 1]

    def boundary_root(epoch: int) -> bytes:
        return state.block_roots[compute_start_slot_at_epoch(p, epoch) % p.SLOTS_PER_HISTORICAL_ROOT]

    if prev_target_balance * 3 >= total * 2:
        state.current_justified_checkpoint = Fields(epoch=previous_epoch, root=boundary_root(previous_epoch))
        bits[1] = True
    if cur_target_balance * 3 >= total * 2:
        state.current_justified_checkpoint = Fields(epoch=current_epoch, root=boundary_root(current_epoch))
        bits[0] = True
    state.justification_bits = bits

    # finalization rules
    if all(bits[1:4]) and old_previous_justified.epoch + 3 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[1:3]) and old_previous_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[0:3]) and old_current_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_current_justified
    if all(bits[0:2]) and old_current_justified.epoch + 1 == current_epoch:
        state.finalized_checkpoint = old_current_justified


# -- rewards / penalties -----------------------------------------------------


def get_attestation_component_deltas(p: Preset, cfg: ChainConfig, state, flags: EpochFlags):
    """Vectorized phase0 attestation deltas, split into the spec's five
    components (source/target/head, inclusion_delay, inactivity) — the
    shapes the official rewards vectors pin individually (reference
    getAttestationDeltas / spec get_*_deltas)."""
    n = len(flags.effective_balance)

    total = flags.total_active_balance
    sqrt_total = integer_squareroot(total)
    eb = flags.effective_balance.astype(np.int64)
    base_reward = eb * p.BASE_REWARD_FACTOR // sqrt_total // BASE_REWARDS_PER_EPOCH
    proposer_reward = base_reward // p.PROPOSER_REWARD_QUOTIENT

    eligible = flags.eligible
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    finality_delay = flags.previous_epoch - state.finalized_checkpoint.epoch
    is_inactivity_leak = finality_delay > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    components = {}
    for attesting, key in (
        (flags.prev_source, "source"),
        (flags.prev_target, "target"),
        (flags.prev_head, "head"),
    ):
        rewards = np.zeros(n, dtype=np.int64)
        penalties = np.zeros(n, dtype=np.int64)
        unslashed = attesting & eligible
        attesting_balance = int(flags.effective_balance[attesting].sum())
        if is_inactivity_leak:
            # optimal participation assumed: full base reward
            rewards[unslashed] += base_reward[unslashed]
        else:
            reward_numerator = base_reward * (attesting_balance // increment)
            rewards[unslashed] += (reward_numerator // (total // increment))[unslashed]
        penalties[eligible & ~attesting] += base_reward[eligible & ~attesting]
        components[key] = (rewards, penalties)

    # proposer + inclusion delay micro-rewards (for source attesters)
    rewards = np.zeros(n, dtype=np.int64)
    has_delay = (flags.inclusion_delay > 0) & flags.prev_source & eligible
    for vi in np.nonzero(has_delay)[0]:
        pi = int(flags.proposer_index[vi])
        if pi >= 0:
            rewards[pi] += int(proposer_reward[vi])
        max_attester_reward = int(base_reward[vi] - proposer_reward[vi])
        rewards[vi] += max_attester_reward // int(flags.inclusion_delay[vi])
    components["inclusion_delay"] = (rewards, np.zeros(n, dtype=np.int64))

    penalties = np.zeros(n, dtype=np.int64)
    if is_inactivity_leak:
        penalties[eligible] += (BASE_REWARDS_PER_EPOCH * base_reward - proposer_reward)[eligible]
        not_target = eligible & ~flags.prev_target
        penalties[not_target] += (
            eb[not_target] * finality_delay // p.INACTIVITY_PENALTY_QUOTIENT
        )
    components["inactivity"] = (np.zeros(n, dtype=np.int64), penalties)
    return components


def get_attestation_deltas(p: Preset, cfg: ChainConfig, state, flags: EpochFlags):
    """Combined phase0 get_attestation_deltas (sum of the components)."""
    components = get_attestation_component_deltas(p, cfg, state, flags)
    n = len(flags.effective_balance)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    for r, pen in components.values():
        rewards += r
        penalties += pen
    return rewards, penalties


def process_rewards_and_penalties(p: Preset, cfg: ChainConfig, state, flags: EpochFlags) -> None:
    if flags.current_epoch == GENESIS_EPOCH:
        return
    rewards, penalties = get_attestation_deltas(p, cfg, state, flags)
    # one vectorized pass + a C-level tolist(): the 250k-iteration python
    # write loop was the scale bottleneck (VERDICT r3 item 4)
    bal = np.asarray(state.balances, dtype=np.int64)
    new_bal = np.maximum(0, bal + rewards.astype(np.int64) - penalties.astype(np.int64))
    state.balances = new_bal.astype(np.uint64).tolist()


# -- registry ----------------------------------------------------------------


def process_registry_updates(p: Preset, cfg: ChainConfig, state) -> None:
    current_epoch = compute_epoch_at_slot(p, state.slot)
    n = len(state.validators)
    # columnar prefilters: the conditions hit a handful of validators per
    # epoch; only those indices take the python path
    elig_e = np.fromiter(
        (v.activation_eligibility_epoch for v in state.validators), np.uint64, count=n
    )
    act_e = np.fromiter((v.activation_epoch for v in state.validators), np.uint64, count=n)
    exit_e = np.fromiter((v.exit_epoch for v in state.validators), np.uint64, count=n)
    eb = np.fromiter((v.effective_balance for v in state.validators), np.uint64, count=n)

    for i in np.nonzero(
        (elig_e == FAR_FUTURE_EPOCH) & (eb == p.MAX_EFFECTIVE_BALANCE)
    )[0]:
        state.validators[int(i)].activation_eligibility_epoch = current_epoch + 1
    for i in np.nonzero(
        (act_e <= current_epoch) & (current_epoch < exit_e) & (eb <= cfg.EJECTION_BALANCE)
    )[0]:
        initiate_validator_exit(p, cfg, state, int(i))

    # activation queue, FIFO by (eligibility epoch, index); re-read
    # eligibility since the first pass may have set it this epoch
    elig_e = np.fromiter(
        (v.activation_eligibility_epoch for v in state.validators), np.uint64, count=n
    )
    candidates = np.nonzero(
        (elig_e != FAR_FUTURE_EPOCH)
        & (elig_e <= state.finalized_checkpoint.epoch)
        & (act_e == FAR_FUTURE_EPOCH)
    )[0]
    queue = sorted((int(i) for i in candidates), key=lambda i: (int(elig_e[i]), i))
    active_count = int(((act_e <= current_epoch) & (current_epoch < exit_e)).sum())
    churn = get_validator_churn_limit(cfg, active_count)
    for i in queue[:churn]:
        state.validators[i].activation_epoch = compute_activation_exit_epoch(p, current_epoch)


# -- slashings ---------------------------------------------------------------


def process_slashings(p: Preset, state, flags: EpochFlags) -> None:
    epoch = flags.current_epoch
    total = flags.total_active_balance
    total_slashings = sum(state.slashings)
    multiplier = p.PROPORTIONAL_SLASHING_MULTIPLIER
    adjusted = min(total_slashings * multiplier, total)
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    n = len(state.validators)
    slashed = np.fromiter((v.slashed for v in state.validators), bool, count=n)
    withdrawable = np.fromiter(
        (v.withdrawable_epoch for v in state.validators), np.uint64, count=n
    )
    hits = np.nonzero(
        slashed & (withdrawable == epoch + p.EPOCHS_PER_SLASHINGS_VECTOR // 2)
    )[0]
    for i in hits:
        i = int(i)
        v = state.validators[i]
        penalty_numerator = (v.effective_balance // increment) * adjusted
        penalty = penalty_numerator // total * increment
        state.balances[i] = max(0, state.balances[i] - penalty)


# -- housekeeping ------------------------------------------------------------


def process_eth1_data_reset(p: Preset, state) -> None:
    next_epoch = compute_epoch_at_slot(p, state.slot) + 1
    if next_epoch % p.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(p: Preset, state) -> None:
    hysteresis_increment = p.EFFECTIVE_BALANCE_INCREMENT // p.HYSTERESIS_QUOTIENT
    down = hysteresis_increment * p.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis_increment * p.HYSTERESIS_UPWARD_MULTIPLIER
    n = len(state.validators)
    bal = np.asarray(state.balances, dtype=np.uint64)
    eb = np.fromiter((v.effective_balance for v in state.validators), np.uint64, count=n)
    # hysteresis means only validators whose balance drifted get touched
    hits = np.nonzero((bal + down < eb) | (eb + up < bal))[0]
    for i in hits:
        i = int(i)
        balance = state.balances[i]
        state.validators[i].effective_balance = min(
            balance - balance % p.EFFECTIVE_BALANCE_INCREMENT, p.MAX_EFFECTIVE_BALANCE
        )


def process_slashings_reset(p: Preset, state) -> None:
    next_epoch = compute_epoch_at_slot(p, state.slot) + 1
    state.slashings[next_epoch % p.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(p: Preset, state) -> None:
    current_epoch = compute_epoch_at_slot(p, state.slot)
    next_epoch = current_epoch + 1
    state.randao_mixes[next_epoch % p.EPOCHS_PER_HISTORICAL_VECTOR] = get_randao_mix(
        p, state, current_epoch
    )


def process_historical_roots_update(p: Preset, state) -> None:
    next_epoch = compute_epoch_at_slot(p, state.slot) + 1
    if next_epoch % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) == 0:
        t = get_types(p).phase0
        batch = Fields(block_roots=list(state.block_roots), state_roots=list(state.state_roots))
        state.historical_roots.append(t.HistoricalBatch.hash_tree_root(batch))


def process_participation_record_updates(state) -> None:
    state.previous_epoch_attestations = state.current_epoch_attestations
    state.current_epoch_attestations = []
