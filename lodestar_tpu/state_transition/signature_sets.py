"""Signature-set collectors: turn a signed block into the batch of
SignatureSets the device verifier consumes in one dispatch.

Reference: packages/state-transition/src/signatureSets/index.ts:23
(getBlockSignatureSets) and its per-op files.  This is the producer side of
the north-star boundary (chain/blocks/verifyBlock.ts:177-190 collects these
and calls chain.bls.verifySignatureSets once per block).
"""

from __future__ import annotations

from typing import List

from ..config.chain_config import ChainConfig
from ..crypto.bls.verifier import AggregatedSignatureSet, SignatureSet, SingleSignatureSet
from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_VOLUNTARY_EXIT,
    Preset,
)
from ..ssz import uint64
from ..types import get_types
from .domain import compute_signing_root, get_domain
from .epoch_context import EpochContext
from .misc import compute_epoch_at_slot


def block_proposer_signature_set(p: Preset, ctx: EpochContext, state, signed_block) -> SingleSignatureSet:
    from .upgrade import block_types

    block = signed_block.message
    t = block_types(p, block)
    epoch = compute_epoch_at_slot(p, block.slot)
    domain = get_domain(p, state, DOMAIN_BEACON_PROPOSER, epoch)
    return SingleSignatureSet(
        pubkey=ctx.index2pubkey[block.proposer_index],
        signing_root=compute_signing_root(p, t.BeaconBlock, block, domain),
        signature=bytes(signed_block.signature),
    )


def randao_signature_set(p: Preset, ctx: EpochContext, state, block) -> SingleSignatureSet:
    epoch = compute_epoch_at_slot(p, block.slot)
    domain = get_domain(p, state, DOMAIN_RANDAO, epoch)
    return SingleSignatureSet(
        pubkey=ctx.index2pubkey[block.proposer_index],
        signing_root=compute_signing_root(p, uint64, epoch, domain),
        signature=bytes(block.body.randao_reveal),
    )


def indexed_attestation_signature_set(p: Preset, ctx: EpochContext, state, indexed) -> AggregatedSignatureSet:
    t = get_types(p).phase0
    domain = get_domain(p, state, DOMAIN_BEACON_ATTESTER, indexed.data.target.epoch)
    return AggregatedSignatureSet(
        pubkeys=[ctx.index2pubkey[i] for i in indexed.attesting_indices],
        signing_root=compute_signing_root(p, t.AttestationData, indexed.data, domain),
        signature=bytes(indexed.signature),
    )


def attestation_signature_sets(p: Preset, ctx: EpochContext, state, attestations) -> List[SignatureSet]:
    return [
        indexed_attestation_signature_set(p, ctx, state, ctx.get_indexed_attestation(att))
        for att in attestations
    ]


def proposer_slashing_signature_sets(p: Preset, ctx: EpochContext, state, slashing) -> List[SignatureSet]:
    t = get_types(p).phase0
    out = []
    for signed_header in (slashing.signed_header_1, slashing.signed_header_2):
        header = signed_header.message
        epoch = compute_epoch_at_slot(p, header.slot)
        domain = get_domain(p, state, DOMAIN_BEACON_PROPOSER, epoch)
        out.append(
            SingleSignatureSet(
                pubkey=ctx.index2pubkey[header.proposer_index],
                signing_root=compute_signing_root(p, t.BeaconBlockHeader, header, domain),
                signature=bytes(signed_header.signature),
            )
        )
    return out


def attester_slashing_signature_sets(p: Preset, ctx: EpochContext, state, slashing) -> List[SignatureSet]:
    return [
        indexed_attestation_signature_set(p, ctx, state, indexed)
        for indexed in (slashing.attestation_1, slashing.attestation_2)
    ]


def voluntary_exit_signature_set(p: Preset, ctx: EpochContext, state, signed_exit) -> SingleSignatureSet:
    t = get_types(p).phase0
    domain = get_domain(p, state, DOMAIN_VOLUNTARY_EXIT, signed_exit.message.epoch)
    return SingleSignatureSet(
        pubkey=ctx.index2pubkey[signed_exit.message.validator_index],
        signing_root=compute_signing_root(p, t.VoluntaryExit, signed_exit.message, domain),
        signature=bytes(signed_exit.signature),
    )


def sync_aggregate_signature_set(p: Preset, ctx: EpochContext, state, sync_aggregate):
    """Sync-aggregate set (signatureSets/syncCommittee.ts analog).  Returns
    None when there are no participants and the signature is the G2
    infinity point (eth_fast_aggregate_verify's valid-empty case) — nothing
    to batch."""
    from ..crypto.bls.api import PublicKey
    from .altair import sync_aggregate_signing_root

    bits = list(sync_aggregate.sync_committee_bits)
    participant_pubkeys = [
        bytes(pk) for pk, bit in zip(state.current_sync_committee.pubkeys, bits) if bit
    ]
    sig = bytes(sync_aggregate.sync_committee_signature)
    if not participant_pubkeys:
        # the only valid empty aggregate is the G2 infinity signature; the
        # non-infinity case is rejected structurally in
        # altair.process_sync_aggregate, so there is nothing to batch here
        return None
    return AggregatedSignatureSet(
        pubkeys=[PublicKey.from_bytes(pk) for pk in participant_pubkeys],
        signing_root=sync_aggregate_signing_root(p, state),
        signature=sig,
    )


def get_block_signature_sets(
    p: Preset,
    cfg: ChainConfig,
    ctx: EpochContext,
    state,
    signed_block,
    include_proposer: bool = True,
    include_randao: bool = True,
) -> List[SignatureSet]:
    """All of a block's signature sets (getBlockSignatureSets,
    signatureSets/index.ts:23).  Deposits are excluded by design: their
    proof-of-possession check can only skip a deposit, not fail a block, so
    it stays inline in apply_deposit."""
    block = signed_block.message
    body = block.body
    sets: List[SignatureSet] = []
    if include_proposer:
        sets.append(block_proposer_signature_set(p, ctx, state, signed_block))
    if include_randao:
        sets.append(randao_signature_set(p, ctx, state, block))
    for slashing in body.proposer_slashings:
        sets.extend(proposer_slashing_signature_sets(p, ctx, state, slashing))
    for slashing in body.attester_slashings:
        sets.extend(attester_slashing_signature_sets(p, ctx, state, slashing))
    sets.extend(attestation_signature_sets(p, ctx, state, body.attestations))
    for signed_exit in body.voluntary_exits:
        sets.append(voluntary_exit_signature_set(p, ctx, state, signed_exit))
    if hasattr(body, "sync_aggregate"):
        s = sync_aggregate_signature_set(p, ctx, state, body.sync_aggregate)
        if s is not None:
            sets.append(s)
    return sets
