"""Fork upgrade functions + fork detection over plain state/block values.

Reference: packages/state-transition/src/slot/upgradeStateToAltair.ts and
upgradeStateToBellatrix.ts, dispatched from stateTransition.ts:100-144
(processSlots runs the upgrade right after the epoch transition that lands
on the fork epoch).

States/blocks are plain Fields values; the fork is detected structurally
(participation lists => altair+, latest_execution_payload_header =>
bellatrix) so replayed old states keep working without a config lookup.
Upgrades mutate IN PLACE: Fields carries the attributes and the fork-aware
type registry decides how they serialize/merkleize, so adding the new
fields + swapping state.fork is a complete upgrade.
"""

from __future__ import annotations

from ..config.chain_config import ChainConfig
from ..config.fork_config import ForkName
from ..params import Preset
from ..ssz import Fields
from ..types import get_types
from .epoch_context import EpochContext
from .misc import compute_epoch_at_slot


def state_fork_name(state) -> ForkName:
    """Structural fork detection for a BeaconState value."""
    if hasattr(state, "latest_execution_payload_header"):
        return ForkName.bellatrix
    if hasattr(state, "current_epoch_participation"):
        return ForkName.altair
    return ForkName.phase0


def block_fork_name(block) -> ForkName:
    """Structural fork detection for a BeaconBlock value (by body fields)."""
    body = block.body
    if hasattr(body, "execution_payload") or hasattr(body, "execution_payload_header"):
        # blinded bodies (builder flow) carry only the payload header but
        # are the same fork as their full counterpart
        return ForkName.bellatrix
    if hasattr(body, "sync_aggregate"):
        return ForkName.altair
    return ForkName.phase0


def state_types(p: Preset, state):
    """ForkTypes namespace matching a state value's fork."""
    return getattr(get_types(p), state_fork_name(state).value)


def block_types(p: Preset, block):
    return getattr(get_types(p), block_fork_name(block).value)


def translate_participation(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, pending_attestations) -> None:
    """upgradeStateToAltair's pending-attestation -> participation-flag
    translation (spec translate_participation)."""
    from .altair import add_flag, get_attestation_participation_flag_indices

    for att in pending_attestations:
        data = att.data
        inclusion_delay = att.inclusion_delay
        flag_indices = get_attestation_participation_flag_indices(p, state, data, inclusion_delay)
        committee = ctx.get_beacon_committee(data.slot, data.index)
        for vi, bit in zip(committee, att.aggregation_bits):
            if not bit:
                continue
            for flag_index in flag_indices:
                state.previous_epoch_participation[int(vi)] = add_flag(
                    state.previous_epoch_participation[int(vi)], flag_index
                )


def upgrade_state_to_altair(p: Preset, cfg: ChainConfig, ctx: EpochContext, state) -> None:
    """In-place phase0 -> altair upgrade (slot/upgradeStateToAltair.ts)."""
    from .altair import get_next_sync_committee

    epoch = compute_epoch_at_slot(p, state.slot)
    pending = list(state.previous_epoch_attestations)
    n = len(state.validators)
    state.fork = Fields(
        previous_version=bytes(state.fork.current_version),
        current_version=cfg.ALTAIR_FORK_VERSION,
        epoch=epoch,
    )
    state.previous_epoch_participation = [0] * n
    state.current_epoch_participation = [0] * n
    state.inactivity_scores = [0] * n
    del state.previous_epoch_attestations
    del state.current_epoch_attestations
    translate_participation(p, cfg, ctx, state, pending)
    sync_committee = get_next_sync_committee(p, state)
    state.current_sync_committee = sync_committee
    state.next_sync_committee = get_next_sync_committee(p, state)


def upgrade_state_to_bellatrix(p: Preset, cfg: ChainConfig, state) -> None:
    """In-place altair -> bellatrix upgrade (slot/upgradeStateToBellatrix.ts)."""
    from .bellatrix import default_payload_header

    epoch = compute_epoch_at_slot(p, state.slot)
    state.fork = Fields(
        previous_version=bytes(state.fork.current_version),
        current_version=cfg.BELLATRIX_FORK_VERSION,
        epoch=epoch,
    )
    state.latest_execution_payload_header = default_payload_header(p)


def maybe_upgrade_state(p: Preset, cfg: ChainConfig, ctx: EpochContext, state) -> None:
    """Run any fork upgrade scheduled for the state's current epoch
    (stateTransition.ts:100-144 processSlots fork dispatch)."""
    epoch = compute_epoch_at_slot(p, state.slot)
    if epoch == cfg.ALTAIR_FORK_EPOCH and state_fork_name(state) == ForkName.phase0:
        upgrade_state_to_altair(p, cfg, ctx, state)
    if epoch == cfg.BELLATRIX_FORK_EPOCH and state_fork_name(state) == ForkName.altair:
        upgrade_state_to_bellatrix(p, cfg, state)
