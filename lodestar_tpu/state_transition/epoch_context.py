"""EpochContext: the per-epoch derived caches the node hangs off a state.

Reference: packages/state-transition/src/cache/epochContext.ts:78 (pubkey
caches, shufflings, proposers, effectiveBalanceIncrements) and
util/epochShuffling.ts:68.

TPU-first reshaping: shufflings and effective balances are flat numpy
arrays (columnar), committees are contiguous slices of one shuffled index
array — the layout a device kernel consumes directly, and the same one the
reference already chose for its hot loops (Uint32Array-backed).  Pubkeys
are cached deserialized in jacobian form for fast aggregation (mirrors
pubkeyCache.ts:75).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..params import DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER, Preset
from ..crypto.bls.api import PublicKey
from .misc import (
    compute_epoch_at_slot,
    compute_proposer_index,
    get_active_validator_indices,
    get_committee_count_per_slot,
    get_seed,
)
from .shuffle import unshuffle_list


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


@dataclasses.dataclass
class EpochShuffling:
    """One epoch's committee assignment (util/epochShuffling.ts:68)."""

    epoch: int
    active_indices: np.ndarray  # (A,) int64 — active validator indices
    shuffling: np.ndarray  # (A,) int64 — unshuffle-gathered order
    committees_per_slot: int
    slots_per_epoch: int

    def committee(self, slot: int, index: int) -> np.ndarray:
        """Members of committee `index` at `slot` — a contiguous slice."""
        slot_in_epoch = slot % self.slots_per_epoch
        committees_in_epoch = self.committees_per_slot * self.slots_per_epoch
        k = slot_in_epoch * self.committees_per_slot + index
        a = len(self.active_indices)
        start = (a * k) // committees_in_epoch
        end = (a * (k + 1)) // committees_in_epoch
        return self.shuffling[start:end]


def compute_epoch_shuffling(p: Preset, state, epoch: int) -> EpochShuffling:
    active = np.array(get_active_validator_indices(state, epoch), dtype=np.int64)
    seed = get_seed(p, state, epoch, DOMAIN_BEACON_ATTESTER)
    shuffled = unshuffle_list(active, seed, p.SHUFFLE_ROUND_COUNT)
    return EpochShuffling(
        epoch=epoch,
        active_indices=active,
        shuffling=shuffled,
        committees_per_slot=get_committee_count_per_slot(p, len(active)),
        slots_per_epoch=p.SLOTS_PER_EPOCH,
    )


class Index2PubkeyCache:
    """index -> deserialized PublicKey, lazily (pubkeyCache.ts
    Index2PubkeyCache keeps jacobian-deserialized keys; here the
    deserialization itself is deferred until a signature set needs the
    key, then memoized).  Append raw 48-byte pubkeys; read PublicKey."""

    def __init__(self):
        self._raw: List[bytes] = []
        self._cache: dict = {}

    def append(self, pk) -> None:
        # accepts raw bytes or an already-deserialized PublicKey
        if isinstance(pk, (bytes, bytearray)):
            self._raw.append(bytes(pk))
        else:
            self._cache[len(self._raw)] = pk
            self._raw.append(pk.to_bytes())

    def __len__(self) -> int:
        return len(self._raw)

    def __getitem__(self, i: int) -> PublicKey:
        pk = self._cache.get(i)
        if pk is None:
            pk = PublicKey.from_bytes(self._raw[i], validate=True)
            self._cache[i] = pk
        return pk


class PubkeyIndexMap:
    """Globally shared pubkey registry (pubkeyCache.ts:29): serialized
    pubkey bytes -> validator index."""

    def __init__(self):
        self._map: Dict[bytes, int] = {}

    def get(self, pubkey: bytes) -> Optional[int]:
        return self._map.get(bytes(pubkey))

    def set(self, pubkey: bytes, index: int) -> None:
        self._map[bytes(pubkey)] = index

    def __len__(self):
        return len(self._map)


class EpochContext:
    """Derived caches for one (state, epoch) pair.

    v1 builds caches from scratch per epoch (the reference mutates/rotates
    incrementally in afterProcessEpoch — planned optimization; the API
    matches so callers won't change).
    """

    def __init__(
        self,
        preset: Preset,
        pubkey2index: PubkeyIndexMap,
        index2pubkey: List[PublicKey],
        previous_shuffling: EpochShuffling,
        current_shuffling: EpochShuffling,
        next_shuffling: EpochShuffling,
        proposers: List[int],
        effective_balance_increments: np.ndarray,
    ):
        self.preset = preset
        self.pubkey2index = pubkey2index
        self.index2pubkey = index2pubkey
        self.previous_shuffling = previous_shuffling
        self.current_shuffling = current_shuffling
        self.next_shuffling = next_shuffling
        self.proposers = proposers
        self.effective_balance_increments = effective_balance_increments

    # -- construction --------------------------------------------------------

    @classmethod
    def create_from_state(
        cls,
        preset: Preset,
        state,
        pubkey2index: Optional[PubkeyIndexMap] = None,
        index2pubkey: Optional[List[PublicKey]] = None,
        prev_ctx: Optional["EpochContext"] = None,
    ) -> "EpochContext":
        """``prev_ctx``: the context of the immediately-preceding epoch.
        When given, the previous/current shufflings ROTATE out of it
        (epochContext.ts afterProcessEpoch) and only the next-epoch
        shuffling is computed fresh — sound because activations/exits
        scheduled at an epoch boundary take effect >= 1 + MAX_SEED_LOOKAHEAD
        epochs later and the seed mixes they read are already final.  At
        mainnet registry sizes this cuts two of the three O(n·90-round)
        shuffles per boundary."""
        p = preset
        if pubkey2index is None:
            pubkey2index = PubkeyIndexMap()
        if index2pubkey is None:
            index2pubkey = Index2PubkeyCache()
        cls._sync_pubkeys(state, pubkey2index, index2pubkey)

        current_epoch = compute_epoch_at_slot(p, state.slot)
        prev_epoch = max(0, current_epoch - 1)
        if (
            prev_ctx is not None
            and prev_ctx.current_shuffling.epoch == prev_epoch
            and prev_ctx.next_shuffling.epoch == current_epoch
        ):
            prev_shuf = prev_ctx.current_shuffling
            cur_shuf = prev_ctx.next_shuffling
        else:
            cur_shuf = compute_epoch_shuffling(p, state, current_epoch)
            prev_shuf = (
                cur_shuf
                if prev_epoch == current_epoch
                else compute_epoch_shuffling(p, state, prev_epoch)
            )
        next_shuf = compute_epoch_shuffling(p, state, current_epoch + 1)

        proposers = cls._compute_proposers(p, state, current_epoch, cur_shuf.active_indices)

        ebi = np.array(
            [v.effective_balance // p.EFFECTIVE_BALANCE_INCREMENT for v in state.validators],
            dtype=np.uint16,
        )
        return cls(p, pubkey2index, index2pubkey, prev_shuf, cur_shuf, next_shuf, proposers, ebi)

    @staticmethod
    def _sync_pubkeys(state, pubkey2index: PubkeyIndexMap, index2pubkey) -> None:
        """Index new validators (epochContext.ts syncPubkeys).  Pubkey
        deserialization is LAZY (Index2PubkeyCache): a mainnet-scale
        registry (250k-500k keys) would otherwise pay one bigint sqrt +
        subgroup check per key up front — minutes to hours of startup —
        while the node only ever touches the keys that actually sign."""
        for i in range(len(index2pubkey), len(state.validators)):
            pk_bytes = bytes(state.validators[i].pubkey)
            pubkey2index.set(pk_bytes, i)
            index2pubkey.append(pk_bytes)

    @staticmethod
    def _compute_proposers(p: Preset, state, epoch: int, active_indices: Sequence[int]) -> List[int]:
        base_seed = get_seed(p, state, epoch, DOMAIN_BEACON_PROPOSER)
        out = []
        start = epoch * p.SLOTS_PER_EPOCH
        for slot in range(start, start + p.SLOTS_PER_EPOCH):
            seed = _sha(base_seed + slot.to_bytes(8, "little"))
            out.append(compute_proposer_index(p, state, list(active_indices), seed))
        return out

    # -- queries (epochContext.ts public surface) ----------------------------

    def epoch(self) -> int:
        return self.current_shuffling.epoch

    def _shuffling_for_epoch(self, epoch: int) -> EpochShuffling:
        for shuf in (self.previous_shuffling, self.current_shuffling, self.next_shuffling):
            if shuf.epoch == epoch:
                return shuf
        raise ValueError(f"no shuffling cached for epoch {epoch} (have {self.epoch()})")

    def get_committee_count_per_slot(self, epoch: int) -> int:
        return self._shuffling_for_epoch(epoch).committees_per_slot

    def get_beacon_committee(self, slot: int, index: int) -> np.ndarray:
        epoch = compute_epoch_at_slot(self.preset, slot)
        shuf = self._shuffling_for_epoch(epoch)
        if index >= shuf.committees_per_slot:
            raise ValueError("committee index out of range")
        return shuf.committee(slot, index)

    def get_beacon_proposer(self, slot: int) -> int:
        epoch = compute_epoch_at_slot(self.preset, slot)
        if epoch != self.epoch():
            raise ValueError("proposer cache only covers the current epoch")
        return self.proposers[slot % self.preset.SLOTS_PER_EPOCH]

    def get_attesting_indices(self, attestation_data, aggregation_bits: Sequence[bool]) -> List[int]:
        committee = self.get_beacon_committee(attestation_data.slot, attestation_data.index)
        if len(aggregation_bits) != len(committee):
            raise ValueError("aggregation bits length != committee size")
        return [int(v) for v, b in zip(committee, aggregation_bits) if b]

    def get_indexed_attestation(self, attestation):
        from ..ssz import Fields

        indices = self.get_attesting_indices(attestation.data, attestation.aggregation_bits)
        return Fields(
            attesting_indices=sorted(indices),
            data=attestation.data,
            signature=attestation.signature,
        )
