"""Swap-or-not shuffling (consensus spec compute_shuffled_index / the
reference's list-optimized unshuffleList, state-transition/src/util/shuffle.ts:15).

The list form is vectorized with numpy: each of SHUFFLE_ROUND_COUNT rounds
computes every index's flip partner and selection bit from one round of
sha256 draws — columnar, branch-free, and the same shape a device kernel
would use (the reference's per-index bit-twiddling loop becomes three array
ops).
"""

from __future__ import annotations

import hashlib

import numpy as np


def _sha(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(index: int, count: int, seed: bytes, rounds: int) -> int:
    """Spec scalar form (forward permutation)."""
    if not 0 <= index < count:
        raise ValueError("index out of range")
    for r in range(rounds):
        pivot = int.from_bytes(_sha(seed + bytes([r]))[:8], "little") % count
        flip = (pivot + count - index) % count
        pos = max(index, flip)
        src = _sha(seed + bytes([r]) + (pos // 256).to_bytes(4, "little"))
        bit = (src[(pos % 256) // 8] >> (pos % 8)) & 1
        if bit:
            index = flip
    return index


def shuffle_list(values: np.ndarray, seed: bytes, rounds: int) -> np.ndarray:
    """Forward-shuffle a whole array: out[shuffled_index(i)] = values[i].

    Equivalent to applying compute_shuffled_index to every index, done as
    `rounds` vectorized swap-or-not passes (in reverse round order, the
    inverse of unshuffling — matching the reference's unshuffleList with
    the round direction flipped)."""
    return _swap_or_not(values, seed, rounds, forward=True)


def unshuffle_list(values: np.ndarray, seed: bytes, rounds: int) -> np.ndarray:
    """Inverse permutation (the one committee computation uses: the
    reference unshuffles the full index list once per epoch)."""
    return _swap_or_not(values, seed, rounds, forward=False)


def _swap_or_not(values: np.ndarray, seed: bytes, rounds: int, forward: bool) -> np.ndarray:
    count = len(values)
    if count <= 1:
        return values.copy()
    out = values.copy()
    idx = np.arange(count, dtype=np.int64)
    round_order = range(rounds) if forward else reversed(range(rounds))
    for r in round_order:
        pivot = int.from_bytes(_sha(seed + bytes([r]))[:8], "little") % count
        flip = (pivot - idx) % count
        pos = np.maximum(idx, flip)
        # one hash per 256 positions
        n_blocks = (count + 255) // 256
        blocks = [
            _sha(seed + bytes([r]) + blk.to_bytes(4, "little")) for blk in range(n_blocks)
        ]
        src = np.frombuffer(b"".join(blocks), dtype=np.uint8)
        bits = (src[pos // 8] >> (pos % 8).astype(np.uint8)) & 1
        # swap-or-not: where bit set, element moves to its flip position.
        # Perform as a gather: new[i] = old[flip[i]] if bit else old[i]
        gather = np.where(bits.astype(bool), flip, idx)
        out = out[gather]
    return out
