"""Spec misc helpers (consensus spec beacon-chain.md "Helper functions").

Reference: packages/state-transition/src/util/{epoch,seed,validator,math}.ts.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

import numpy as np

from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    FAR_FUTURE_EPOCH,
    Preset,
)
from .shuffle import compute_shuffled_index


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def integer_squareroot(n: int) -> int:
    if n < 0:
        raise ValueError
    x, y = n, (n + 1) // 2
    while y < x:
        x, y = y, (y + n // y) // 2
    return x


def compute_epoch_at_slot(p: Preset, slot: int) -> int:
    return slot // p.SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(p: Preset, epoch: int) -> int:
    return epoch * p.SLOTS_PER_EPOCH


def compute_activation_exit_epoch(p: Preset, epoch: int) -> int:
    return epoch + 1 + p.MAX_SEED_LOOKAHEAD


def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def get_active_validator_indices(state, epoch: int) -> List[int]:
    # columnar: two array pulls + one boolean mask beat 250k+ attribute
    # probes at registry scale
    import numpy as np

    activation = np.fromiter(
        (v.activation_epoch for v in state.validators), dtype=np.uint64,
        count=len(state.validators),
    )
    exit_e = np.fromiter(
        (v.exit_epoch for v in state.validators), dtype=np.uint64,
        count=len(state.validators),
    )
    return np.nonzero((activation <= epoch) & (epoch < exit_e))[0].tolist()


def get_randao_mix(p: Preset, state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % p.EPOCHS_PER_HISTORICAL_VECTOR]


def get_seed(p: Preset, state, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(p, state, epoch + p.EPOCHS_PER_HISTORICAL_VECTOR - p.MIN_SEED_LOOKAHEAD - 1)
    return _sha(domain_type + epoch.to_bytes(8, "little") + mix)


def get_committee_count_per_slot(p: Preset, active_count: int) -> int:
    return max(
        1,
        min(
            p.MAX_COMMITTEES_PER_SLOT,
            active_count // p.SLOTS_PER_EPOCH // p.TARGET_COMMITTEE_SIZE,
        ),
    )


def compute_proposer_index(p: Preset, state, indices: Sequence[int], seed: bytes) -> int:
    """Spec compute_proposer_index (effective-balance weighted)."""
    if not indices:
        raise ValueError("no active validators")
    max_random_byte = 255
    i = 0
    total = len(indices)
    while True:
        candidate = indices[compute_shuffled_index(i % total, total, seed, p.SHUFFLE_ROUND_COUNT)]
        random_byte = _sha(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eb = state.validators[candidate].effective_balance
        if eb * max_random_byte >= p.MAX_EFFECTIVE_BALANCE * random_byte:
            return int(candidate)
        i += 1


def compute_committee_slices(epoch_committee_count: int, active_count: int):
    """Start/end bounds of committee k within the shuffled active set."""
    bounds = [
        (active_count * k) // epoch_committee_count for k in range(epoch_committee_count + 1)
    ]
    return bounds


def xor_bytes(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def increase_balance(state, index: int, delta: int) -> None:
    state.balances[index] += delta


def decrease_balance(state, index: int, delta: int) -> None:
    state.balances[index] = max(0, state.balances[index] - delta)
