"""Validator mutation helpers: exits, slashing, churn.

Reference: packages/state-transition/src/util/validator.ts and
src/block/{initiateValidatorExit,slashValidator}.ts (consensus spec
beacon-chain.md mutators).
"""

from __future__ import annotations

from ..config.chain_config import ChainConfig
from ..params import FAR_FUTURE_EPOCH, Preset
from .misc import (
    compute_activation_exit_epoch,
    compute_epoch_at_slot,
    decrease_balance,
    get_active_validator_indices,
    increase_balance,
)


def get_validator_churn_limit(cfg: ChainConfig, active_count: int) -> int:
    return max(cfg.MIN_PER_EPOCH_CHURN_LIMIT, active_count // cfg.CHURN_LIMIT_QUOTIENT)


def initiate_validator_exit(p: Preset, cfg: ChainConfig, state, index: int) -> None:
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    current_epoch = compute_epoch_at_slot(p, state.slot)
    exit_epochs = [w.exit_epoch for w in state.validators if w.exit_epoch != FAR_FUTURE_EPOCH]
    exit_queue_epoch = max(exit_epochs + [compute_activation_exit_epoch(p, current_epoch)])
    exit_queue_churn = sum(1 for w in state.validators if w.exit_epoch == exit_queue_epoch)
    active_count = len(get_active_validator_indices(state, current_epoch))
    if exit_queue_churn >= get_validator_churn_limit(cfg, active_count):
        exit_queue_epoch += 1
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = exit_queue_epoch + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY


def slash_validator(
    p: Preset,
    cfg: ChainConfig,
    state,
    slashed_index: int,
    proposer_index: int,
    whistleblower_index: int | None = None,
) -> None:
    """Spec slash_validator (phase0 quotients)."""
    epoch = compute_epoch_at_slot(p, state.slot)
    initiate_validator_exit(p, cfg, state, slashed_index)
    v = state.validators[slashed_index]
    v.slashed = True
    v.withdrawable_epoch = max(v.withdrawable_epoch, epoch + p.EPOCHS_PER_SLASHINGS_VECTOR)
    state.slashings[epoch % p.EPOCHS_PER_SLASHINGS_VECTOR] += v.effective_balance
    decrease_balance(state, slashed_index, v.effective_balance // p.MIN_SLASHING_PENALTY_QUOTIENT)

    whistleblower_reward = v.effective_balance // p.WHISTLEBLOWER_REWARD_QUOTIENT
    proposer_reward = whistleblower_reward // p.PROPOSER_REWARD_QUOTIENT
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index, whistleblower_reward - proposer_reward)
