"""Genesis state construction: interop (deterministic keys) + eth1 path.

Reference: packages/state-transition/src/util/genesis.ts
(initializeBeaconStateFromEth1) and util/interop.ts / the dev command's
interop state (cli/src/cmds/dev/).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional

from ..config.chain_config import ChainConfig
from ..crypto.bls.api import interop_secret_key
from ..params import (
    BLS_WITHDRAWAL_PREFIX,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    Preset,
)
from ..ssz import Fields
from ..types import get_types
from .epoch_context import EpochContext
from .misc import is_active_validator


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def _genesis_scaffold(p: Preset, cfg: ChainConfig, genesis_time: int, randao_fill: bytes):
    """The state skeleton both genesis paths share: fork record, default-
    body latest_block_header, filled randao mixes."""
    t = get_types(p).phase0
    state = t.BeaconState.default()
    state.genesis_time = genesis_time
    state.fork = Fields(
        previous_version=cfg.GENESIS_FORK_VERSION,
        current_version=cfg.GENESIS_FORK_VERSION,
        epoch=GENESIS_EPOCH,
    )
    body_root = t.BeaconBlockBody.hash_tree_root(t.BeaconBlockBody.default())
    state.latest_block_header = Fields(
        slot=0,
        proposer_index=0,
        parent_root=b"\x00" * 32,
        state_root=b"\x00" * 32,
        body_root=body_root,
    )
    state.randao_mixes = [randao_fill] * p.EPOCHS_PER_HISTORICAL_VECTOR
    return state


def interop_genesis_state(
    p: Preset,
    cfg: ChainConfig,
    validator_count: int,
    genesis_time: int = 1_578_009_600,
):
    """Deterministic genesis with interop keys, all validators active at
    genesis — the dev-chain / sim-test starting point (reference:
    getDevBeaconNode interop genesis, SURVEY §4.4)."""
    state = _genesis_scaffold(p, cfg, genesis_time, b"\x42" * 32)

    for i in range(validator_count):
        sk = interop_secret_key(i)
        pubkey = sk.to_public_key().to_bytes()
        wc = BLS_WITHDRAWAL_PREFIX + _sha(pubkey)[1:]
        state.validators.append(
            Fields(
                pubkey=pubkey,
                withdrawal_credentials=wc,
                effective_balance=p.MAX_EFFECTIVE_BALANCE,
                slashed=False,
                activation_eligibility_epoch=GENESIS_EPOCH,
                activation_epoch=GENESIS_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        )
        state.balances.append(p.MAX_EFFECTIVE_BALANCE)

    state.genesis_validators_root = _genesis_validators_root(p, state)
    state.eth1_data = Fields(
        deposit_root=b"\x00" * 32,
        deposit_count=validator_count,
        block_hash=b"\x01" * 32,
    )
    state.eth1_deposit_index = validator_count
    return state


def initialize_beacon_state_from_eth1(
    p: Preset,
    cfg: ChainConfig,
    eth1_block_hash: bytes,
    eth1_timestamp: int,
    deposits,
):
    """Spec initialize_beacon_state_from_eth1 (reference
    state-transition/src/util/genesis.ts initializeBeaconStateFromEth1):
    replay the deposit list with full merkle-proof verification against
    an incrementally-updated deposit root, then activate every validator
    that reached MAX_EFFECTIVE_BALANCE."""
    from types import SimpleNamespace

    from ..eth1.tracker import DepositTree
    from .block import process_deposit

    t = get_types(p).phase0
    state = _genesis_scaffold(
        p, cfg, eth1_timestamp + cfg.GENESIS_DELAY, bytes(eth1_block_hash)
    )
    state.eth1_data = Fields(
        deposit_root=b"\x00" * 32,
        deposit_count=len(deposits),
        block_hash=bytes(eth1_block_hash),
    )

    # apply_deposit needs only the pubkey->index map (with .set) and the
    # index2pubkey list of the growing registry — a shim stands in for
    # the full EpochContext during genesis replay
    class _PkMap(dict):
        def set(self, k, v):
            self[k] = v

    ctx = SimpleNamespace(pubkey2index=_PkMap(), index2pubkey=[])
    # per spec, the deposit root for proof-checking deposit i covers the
    # first i+1 leaves; the incremental tree keeps replay O(n log n)
    tree = DepositTree()
    for deposit in deposits:
        tree.push(t.DepositData.hash_tree_root(deposit.data))
        state.eth1_data.deposit_root = tree.root()
        process_deposit(p, cfg, ctx, state, deposit)

    # process activations
    for index, v in enumerate(state.validators):
        balance = state.balances[index]
        eff = min(balance - balance % p.EFFECTIVE_BALANCE_INCREMENT, p.MAX_EFFECTIVE_BALANCE)
        v.effective_balance = eff
        if eff == p.MAX_EFFECTIVE_BALANCE:
            v.activation_eligibility_epoch = GENESIS_EPOCH
            v.activation_epoch = GENESIS_EPOCH
    state.genesis_validators_root = _genesis_validators_root(p, state)
    return state


def _genesis_validators_root(p: Preset, state) -> bytes:
    t = get_types(p).phase0
    from ..ssz import List as SszList

    vtype = SszList(t.Validator, p.VALIDATOR_REGISTRY_LIMIT)
    return vtype.hash_tree_root(list(state.validators))


def is_valid_genesis_state(p: Preset, cfg: ChainConfig, state) -> bool:
    if state.genesis_time < cfg.MIN_GENESIS_TIME:
        return False
    active = sum(1 for v in state.validators if is_active_validator(v, GENESIS_EPOCH))
    return active >= cfg.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
