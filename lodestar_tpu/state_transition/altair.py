"""Altair state transition: participation flags, sync committees,
inactivity scores (consensus spec v1.1.10, altair/beacon-chain.md).

Reference: packages/state-transition/src/block/processAttestationsAltair.ts,
block/processSyncCommittee.ts, epoch/processInactivityUpdates.ts,
epoch/processParticipationFlagUpdates.ts, epoch/processSyncCommitteeUpdates.ts,
epoch/getRewardsAndPenalties.ts, util/syncCommittee.ts, util/attesterStatus.ts.

Layout follows the phase0 modules: columnar numpy precompute for epoch
processing (the array layout a device offload consumes unchanged), scalar
spec-shaped code on the block path.
"""

from __future__ import annotations

from typing import List, Sequence

import hashlib

import numpy as np

from ..config.chain_config import ChainConfig
from ..params import (
    DOMAIN_SYNC_COMMITTEE,
    GENESIS_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    Preset,
)
from ..ssz import Bytes32, Fields
from .block import BlockProcessingError, is_valid_indexed_attestation
from .domain import compute_signing_root, get_domain
from .epoch_context import EpochContext
from .misc import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_active_validator_indices,
    get_seed,
    increase_balance,
    decrease_balance,
    integer_squareroot,
)
from .shuffle import compute_shuffled_index


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


# ---------------------------------------------------------------------------
# participation flags
# ---------------------------------------------------------------------------


def add_flag(flags: int, flag_index: int) -> int:
    return flags | (1 << flag_index)


def has_flag(flags: int, flag_index: int) -> bool:
    return bool(flags & (1 << flag_index))


def get_block_root_at_slot(p: Preset, state, slot: int) -> bytes:
    if not (slot < state.slot <= slot + p.SLOTS_PER_HISTORICAL_ROOT):
        raise BlockProcessingError(f"block root at slot {slot} out of range (state {state.slot})")
    return bytes(state.block_roots[slot % p.SLOTS_PER_HISTORICAL_ROOT])


def get_block_root(p: Preset, state, epoch: int) -> bytes:
    return get_block_root_at_slot(p, state, compute_start_slot_at_epoch(p, epoch))


def get_total_active_balance(p: Preset, state) -> int:
    epoch = compute_epoch_at_slot(p, state.slot)
    total = sum(
        state.validators[i].effective_balance
        for i in get_active_validator_indices(state, epoch)
    )
    return max(p.EFFECTIVE_BALANCE_INCREMENT, total)


def get_base_reward_per_increment(p: Preset, total_active_balance: int) -> int:
    return (
        p.EFFECTIVE_BALANCE_INCREMENT
        * p.BASE_REWARD_FACTOR
        // integer_squareroot(total_active_balance)
    )


def get_base_reward(p: Preset, state, index: int, base_reward_per_increment: int) -> int:
    increments = state.validators[index].effective_balance // p.EFFECTIVE_BALANCE_INCREMENT
    return increments * base_reward_per_increment


def get_attestation_participation_flag_indices(
    p: Preset, state, data, inclusion_delay: int
) -> List[int]:
    """Spec get_attestation_participation_flag_indices (altair)."""
    current_epoch = compute_epoch_at_slot(p, state.slot)
    if data.target.epoch == current_epoch:
        justified_checkpoint = state.current_justified_checkpoint
    else:
        justified_checkpoint = state.previous_justified_checkpoint

    is_matching_source = (
        data.source.epoch == justified_checkpoint.epoch
        and bytes(data.source.root) == bytes(justified_checkpoint.root)
    )
    if not is_matching_source:
        raise BlockProcessingError("attestation source does not match justified checkpoint")
    is_matching_target = is_matching_source and bytes(data.target.root) == get_block_root(
        p, state, data.target.epoch
    )
    is_matching_head = is_matching_target and bytes(
        data.beacon_block_root
    ) == get_block_root_at_slot(p, state, data.slot)

    flags: List[int] = []
    if is_matching_source and inclusion_delay <= integer_squareroot(p.SLOTS_PER_EPOCH):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= p.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == p.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


# ---------------------------------------------------------------------------
# block path
# ---------------------------------------------------------------------------


def process_attestation_altair(
    p: Preset, cfg: ChainConfig, ctx: EpochContext, state, attestation, verify_signatures: bool
) -> None:
    """Spec process_attestation (altair variant): same validity envelope as
    phase0, participation-flag bookkeeping + immediate proposer reward
    instead of pending-attestation accumulation
    (block/processAttestationsAltair.ts)."""
    data = attestation.data
    current_epoch = compute_epoch_at_slot(p, state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)
    if data.target.epoch not in (previous_epoch, current_epoch):
        raise BlockProcessingError("attestation target epoch not current or previous")
    if data.target.epoch != compute_epoch_at_slot(p, data.slot):
        raise BlockProcessingError("attestation target epoch != slot epoch")
    if not (
        data.slot + p.MIN_ATTESTATION_INCLUSION_DELAY
        <= state.slot
        <= data.slot + p.SLOTS_PER_EPOCH
    ):
        raise BlockProcessingError("attestation outside inclusion window")
    if data.index >= ctx.get_committee_count_per_slot(data.target.epoch):
        raise BlockProcessingError("attestation committee index out of range")
    committee = ctx.get_beacon_committee(data.slot, data.index)
    bits = list(attestation.aggregation_bits)
    if len(bits) != len(committee):
        raise BlockProcessingError("aggregation bits length != committee size")

    inclusion_delay = state.slot - data.slot
    participation_flag_indices = get_attestation_participation_flag_indices(
        p, state, data, inclusion_delay
    )

    indexed = ctx.get_indexed_attestation(attestation)
    if not is_valid_indexed_attestation(p, ctx, state, indexed, verify_signatures):
        raise BlockProcessingError("invalid indexed attestation")

    if data.target.epoch == current_epoch:
        epoch_participation = state.current_epoch_participation
    else:
        epoch_participation = state.previous_epoch_participation

    total_active_balance = get_total_active_balance(p, state)
    brpi = get_base_reward_per_increment(p, total_active_balance)
    proposer_reward_numerator = 0
    for index in indexed.attesting_indices:
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index in participation_flag_indices and not has_flag(
                epoch_participation[index], flag_index
            ):
                epoch_participation[index] = add_flag(epoch_participation[index], flag_index)
                proposer_reward_numerator += get_base_reward(p, state, index, brpi) * weight

    proposer_reward_denominator = (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT) * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT
    )
    proposer_reward = proposer_reward_numerator // proposer_reward_denominator
    increase_balance(state, ctx.get_beacon_proposer(state.slot), proposer_reward)


def eth_fast_aggregate_verify(pubkeys, signing_root: bytes, signature: bytes) -> bool:
    """eth_fast_aggregate_verify: the G2 point-at-infinity signature is valid
    for an empty participant set (altair/bls.md)."""
    from ..crypto.bls.api import Signature, fast_aggregate_verify

    G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95
    if not pubkeys and bytes(signature) == G2_POINT_AT_INFINITY:
        return True
    try:
        sig = Signature.from_bytes(bytes(signature))
    except ValueError:
        return False
    return fast_aggregate_verify(pubkeys, signing_root, sig)


def sync_aggregate_signing_root(p: Preset, state) -> bytes:
    """Signing root for a block's sync aggregate: the previous slot's block
    root under DOMAIN_SYNC_COMMITTEE (block/processSyncCommittee.ts)."""
    previous_slot = max(state.slot, 1) - 1
    domain = get_domain(p, state, DOMAIN_SYNC_COMMITTEE, compute_epoch_at_slot(p, previous_slot))
    root = get_block_root_at_slot(p, state, previous_slot)
    return compute_signing_root(p, Bytes32, root, domain)


def process_sync_aggregate(
    p: Preset, cfg: ChainConfig, ctx: EpochContext, state, sync_aggregate, verify_signatures: bool
) -> None:
    """Spec process_sync_aggregate (block/processSyncCommittee.ts).  With
    verify_signatures=False the aggregate signature is collected by
    signature_sets.sync_aggregate_signature_set for the batched dispatch."""
    committee_pubkeys = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    bits = list(sync_aggregate.sync_committee_bits)
    if len(bits) != len(committee_pubkeys):
        raise BlockProcessingError("sync committee bits length mismatch")

    # structural empty-aggregate check, independent of signature deferral:
    # zero participants is only valid with the G2 infinity signature
    # (eth_fast_aggregate_verify, altair/bls.md)
    if not any(bits) and bytes(sync_aggregate.sync_committee_signature) != b"\xc0" + b"\x00" * 95:
        raise BlockProcessingError("empty sync aggregate with non-infinity signature")

    if verify_signatures:
        from ..crypto.bls.api import PublicKey

        participant_pubkeys = [
            PublicKey.from_bytes(pk) for pk, bit in zip(committee_pubkeys, bits) if bit
        ]
        root = sync_aggregate_signing_root(p, state)
        if not eth_fast_aggregate_verify(
            participant_pubkeys, root, bytes(sync_aggregate.sync_committee_signature)
        ):
            raise BlockProcessingError("invalid sync committee signature")

    # rewards (exact integer spec arithmetic)
    total_active_increments = get_total_active_balance(p, state) // p.EFFECTIVE_BALANCE_INCREMENT
    brpi = get_base_reward_per_increment(p, get_total_active_balance(p, state))
    total_base_rewards = brpi * total_active_increments
    max_participant_rewards = (
        total_base_rewards * SYNC_REWARD_WEIGHT // WEIGHT_DENOMINATOR // p.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // p.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )

    proposer_index = ctx.get_beacon_proposer(state.slot)
    committee_indices = [ctx.pubkey2index.get(pk) for pk in committee_pubkeys]
    for participant_index, bit in zip(committee_indices, bits):
        if participant_index is None:
            raise BlockProcessingError("sync committee pubkey unknown")
        if bit:
            increase_balance(state, participant_index, participant_reward)
            increase_balance(state, proposer_index, proposer_reward)
        else:
            decrease_balance(state, participant_index, participant_reward)


# ---------------------------------------------------------------------------
# sync committee selection
# ---------------------------------------------------------------------------


def get_next_sync_committee_indices(p: Preset, state) -> List[int]:
    """Spec get_next_sync_committee_indices: effective-balance-weighted
    sampling over the shuffled active set (util/syncCommittee.ts)."""
    epoch = compute_epoch_at_slot(p, state.slot) + 1
    active = get_active_validator_indices(state, epoch)
    count = len(active)
    seed = get_seed(p, state, epoch, DOMAIN_SYNC_COMMITTEE)
    indices: List[int] = []
    i = 0
    while len(indices) < p.SYNC_COMMITTEE_SIZE:
        shuffled = compute_shuffled_index(i % count, count, seed, p.SHUFFLE_ROUND_COUNT)
        candidate = active[shuffled]
        random_byte = _sha(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eb = state.validators[candidate].effective_balance
        if eb * 255 >= p.MAX_EFFECTIVE_BALANCE * random_byte:
            indices.append(int(candidate))
        i += 1
    return indices


def get_next_sync_committee(p: Preset, state):
    """Spec get_next_sync_committee: member pubkeys + aggregate."""
    from ..crypto.bls.api import PublicKey, aggregate_pubkeys

    indices = get_next_sync_committee_indices(p, state)
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    agg = aggregate_pubkeys([PublicKey.from_bytes(pk) for pk in pubkeys])
    return Fields(pubkeys=pubkeys, aggregate_pubkey=agg.to_bytes())


# ---------------------------------------------------------------------------
# epoch path
# ---------------------------------------------------------------------------


def get_unslashed_participating_mask(p: Preset, state, flag_index: int, epoch: int) -> np.ndarray:
    """Boolean mask of unslashed validators active at `epoch` with the flag."""
    current_epoch = compute_epoch_at_slot(p, state.slot)
    participation = (
        state.current_epoch_participation
        if epoch == current_epoch
        else state.previous_epoch_participation
    )
    n = len(state.validators)
    flags = np.fromiter((int(f) for f in participation), dtype=np.uint8, count=n)
    has = (flags & (1 << flag_index)) != 0
    slashed = np.fromiter((v.slashed for v in state.validators), dtype=bool, count=n)
    activation = np.fromiter(
        (v.activation_epoch for v in state.validators), dtype=np.uint64, count=n
    )
    exit_e = np.fromiter((v.exit_epoch for v in state.validators), dtype=np.uint64, count=n)
    active = (activation <= epoch) & (epoch < exit_e)
    return has & ~slashed & active


def _eligible_mask(p: Preset, state) -> np.ndarray:
    current_epoch = compute_epoch_at_slot(p, state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)
    n = len(state.validators)
    slashed = np.fromiter((v.slashed for v in state.validators), dtype=bool, count=n)
    activation = np.fromiter(
        (v.activation_epoch for v in state.validators), dtype=np.uint64, count=n
    )
    exit_e = np.fromiter((v.exit_epoch for v in state.validators), dtype=np.uint64, count=n)
    withdrawable = np.fromiter(
        (v.withdrawable_epoch for v in state.validators), dtype=np.uint64, count=n
    )
    active_prev = (activation <= previous_epoch) & (previous_epoch < exit_e)
    return active_prev | (slashed & (previous_epoch + 1 < withdrawable))


def process_justification_and_finalization_altair(p: Preset, state) -> None:
    """Altair justification: target balances come from participation flags
    (epoch/processJustificationAndFinalization.ts)."""
    from .epoch import weigh_justification_and_finalization, EpochFlags

    current_epoch = compute_epoch_at_slot(p, state.slot)
    if current_epoch <= GENESIS_EPOCH + 1:
        return
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)
    n = len(state.validators)
    eb = np.fromiter(
        (v.effective_balance for v in state.validators), dtype=np.uint64, count=n
    )
    prev_mask = get_unslashed_participating_mask(
        p, state, TIMELY_TARGET_FLAG_INDEX, previous_epoch
    )
    cur_mask = get_unslashed_participating_mask(p, state, TIMELY_TARGET_FLAG_INDEX, current_epoch)
    prev_target_balance = max(p.EFFECTIVE_BALANCE_INCREMENT, int(eb[prev_mask].sum()))
    cur_target_balance = max(p.EFFECTIVE_BALANCE_INCREMENT, int(eb[cur_mask].sum()))
    flags = EpochFlags(
        current_epoch=current_epoch,
        previous_epoch=previous_epoch,
        total_active_balance=get_total_active_balance(p, state),
        active_prev=np.zeros(n, dtype=bool),
        active_cur=np.zeros(n, dtype=bool),
        eligible=np.zeros(n, dtype=bool),
        prev_source=np.zeros(n, dtype=bool),
        prev_target=np.zeros(n, dtype=bool),
        prev_head=np.zeros(n, dtype=bool),
        cur_target=np.zeros(n, dtype=bool),
        inclusion_delay=np.zeros(n, dtype=np.uint64),
        proposer_index=np.zeros(n, dtype=np.int64),
        effective_balance=eb,
    )
    weigh_justification_and_finalization(p, state, flags, prev_target_balance, cur_target_balance)


def process_inactivity_updates(p: Preset, cfg: ChainConfig, state) -> None:
    """Spec process_inactivity_updates (epoch/processInactivityUpdates.ts)."""
    current_epoch = compute_epoch_at_slot(p, state.slot)
    if current_epoch == GENESIS_EPOCH:
        return
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)
    target_mask = get_unslashed_participating_mask(
        p, state, TIMELY_TARGET_FLAG_INDEX, previous_epoch
    )
    eligible = _eligible_mask(p, state)
    finality_delay = previous_epoch - state.finalized_checkpoint.epoch
    is_leak = finality_delay > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY
    scores = np.asarray(state.inactivity_scores, dtype=np.int64)
    updated = np.where(
        target_mask,
        scores - np.minimum(1, scores),
        scores + cfg.INACTIVITY_SCORE_BIAS,
    )
    if not is_leak:
        updated = updated - np.minimum(cfg.INACTIVITY_SCORE_RECOVERY_RATE, updated)
    state.inactivity_scores = (
        np.where(eligible, updated, scores).astype(np.uint64).tolist()
    )


def get_flag_index_deltas(p: Preset, state, flag_index: int):
    """Vectorized spec get_flag_index_deltas."""
    n = len(state.validators)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    current_epoch = compute_epoch_at_slot(p, state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)
    eb = np.fromiter((v.effective_balance for v in state.validators), dtype=np.int64, count=n)
    increment = p.EFFECTIVE_BALANCE_INCREMENT

    unslashed = get_unslashed_participating_mask(p, state, flag_index, previous_epoch)
    weight = PARTICIPATION_FLAG_WEIGHTS[flag_index]
    total_active = get_total_active_balance(p, state)
    brpi = get_base_reward_per_increment(p, total_active)
    base_reward = (eb // increment) * brpi

    unslashed_balance = max(increment, int(eb[unslashed].sum()))
    unslashed_increments = unslashed_balance // increment
    active_increments = total_active // increment

    eligible = _eligible_mask(p, state)
    finality_delay = previous_epoch - state.finalized_checkpoint.epoch
    is_leak = finality_delay > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    gain = eligible & unslashed
    if not is_leak:
        reward_numerator = base_reward * weight * unslashed_increments
        rewards[gain] += (reward_numerator // (active_increments * WEIGHT_DENOMINATOR))[gain]
    if flag_index != TIMELY_HEAD_FLAG_INDEX:
        lose = eligible & ~unslashed
        penalties[lose] += (base_reward * weight // WEIGHT_DENOMINATOR)[lose]
    return rewards, penalties


def get_inactivity_penalty_deltas(p: Preset, cfg: ChainConfig, state):
    """Spec get_inactivity_penalty_deltas (altair quotient)."""
    n = len(state.validators)
    penalties = np.zeros(n, dtype=np.int64)
    current_epoch = compute_epoch_at_slot(p, state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)
    target_mask = get_unslashed_participating_mask(
        p, state, TIMELY_TARGET_FLAG_INDEX, previous_epoch
    )
    eligible = _eligible_mask(p, state)
    hit = eligible & ~target_mask
    # python-int products: eb * inactivity_score can exceed int64 during
    # long leaks; keep the per-hit loop but bound it to the hit set (tiny
    # outside leaks) instead of iterating the whole registry
    for i in np.nonzero(hit)[0]:
        i = int(i)
        penalty_numerator = state.validators[i].effective_balance * state.inactivity_scores[i]
        penalties[i] += penalty_numerator // (
            cfg.INACTIVITY_SCORE_BIAS * p.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
        )
    return penalties


def process_rewards_and_penalties_altair(p: Preset, cfg: ChainConfig, state) -> None:
    current_epoch = compute_epoch_at_slot(p, state.slot)
    if current_epoch == GENESIS_EPOCH:
        return
    n = len(state.validators)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    for flag_index in range(len(PARTICIPATION_FLAG_WEIGHTS)):
        r, pn = get_flag_index_deltas(p, state, flag_index)
        rewards += r
        penalties += pn
    penalties += get_inactivity_penalty_deltas(p, cfg, state)
    # vectorized write-back (mirrors the phase0 path; mainnet IS altair+,
    # so this loop is the one production actually runs at 250k+ registry
    # sizes — review r4)
    bal = np.asarray(state.balances, dtype=np.int64)
    state.balances = np.maximum(0, bal + rewards - penalties).astype(np.uint64).tolist()


def process_slashings_altair(p: Preset, state) -> None:
    """Phase0 process_slashings with the altair multiplier."""
    epoch = compute_epoch_at_slot(p, state.slot)
    total = get_total_active_balance(p, state)
    total_slashings = sum(state.slashings)
    adjusted = min(total_slashings * p.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR, total)
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    n = len(state.validators)
    slashed = np.fromiter((v.slashed for v in state.validators), bool, count=n)
    withdrawable = np.fromiter(
        (v.withdrawable_epoch for v in state.validators), np.uint64, count=n
    )
    for i in np.nonzero(
        slashed & (withdrawable == epoch + p.EPOCHS_PER_SLASHINGS_VECTOR // 2)
    )[0]:
        i = int(i)
        v = state.validators[i]
        penalty_numerator = (v.effective_balance // increment) * adjusted
        penalty = penalty_numerator // total * increment
        state.balances[i] = max(0, state.balances[i] - penalty)


def process_participation_flag_updates(state) -> None:
    state.previous_epoch_participation = state.current_epoch_participation
    state.current_epoch_participation = [0] * len(state.validators)


def process_sync_committee_updates(p: Preset, state) -> None:
    next_epoch = compute_epoch_at_slot(p, state.slot) + 1
    if next_epoch % p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(p, state)


def process_epoch_altair(p: Preset, cfg: ChainConfig, ctx: EpochContext, state) -> None:
    """Altair epoch transition (stateTransition.ts processEpoch dispatch)."""
    from .epoch import (
        process_effective_balance_updates,
        process_eth1_data_reset,
        process_historical_roots_update,
        process_randao_mixes_reset,
        process_registry_updates,
        process_slashings_reset,
    )

    process_justification_and_finalization_altair(p, state)
    process_inactivity_updates(p, cfg, state)
    process_rewards_and_penalties_altair(p, cfg, state)
    process_registry_updates(p, cfg, state)
    process_slashings_altair(p, state)
    process_eth1_data_reset(p, state)
    process_effective_balance_updates(p, state)
    process_slashings_reset(p, state)
    process_randao_mixes_reset(p, state)
    process_historical_roots_update(p, state)
    process_participation_flag_updates(state)
    process_sync_committee_updates(p, state)
