"""Weak subjectivity period computation.

Reference: packages/state-transition/src/util/weakSubjectivity.ts
(computeWeakSubjectivityPeriod per the consensus specs' weak-subjectivity
guide, with the reference's default safety decay of 10%).
"""

from __future__ import annotations

from ..params import Preset
from .misc import compute_epoch_at_slot

# default safety decay percentage (weakSubjectivity.ts DEFAULT_SAFETY_DECAY)
DEFAULT_SAFETY_DECAY = 10

# churn constants (chain config in the reference; mainnet values)
MIN_PER_EPOCH_CHURN_LIMIT = 4
CHURN_LIMIT_QUOTIENT = 65536
MIN_VALIDATOR_WITHDRAWABILITY_DELAY = 256


def get_churn_limit(p: Preset, active_validator_count: int) -> int:
    return max(MIN_PER_EPOCH_CHURN_LIMIT, active_validator_count // CHURN_LIMIT_QUOTIENT)


def compute_weak_subjectivity_period(
    p: Preset, state, safety_decay: int = DEFAULT_SAFETY_DECAY
) -> int:
    """ws_period in epochs for `state` (weakSubjectivity.ts:38).

    Two-regime formula: the churn branch applies when the average active
    balance is near the 32 ETH cap; otherwise the deposit branch bounds
    the adversary's stake turnover.
    """
    epoch = compute_epoch_at_slot(p, state.slot)
    active = [
        i
        for i, v in enumerate(state.validators)
        if v.activation_epoch <= epoch < v.exit_epoch
    ]
    N = len(active)
    ws_period = MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    if N == 0:
        return ws_period
    # t = average EFFECTIVE balance in ETH, via effective-balance increments
    # (computeWeakSubjectivityPeriodFromConstituents uses
    # totalActiveBalanceIncrements — raw balances above the 32 ETH cap would
    # inflate ws_period beyond the verified formula; ADVICE r3)
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    total_increments = sum(
        int(state.validators[i].effective_balance) // increment for i in active
    )
    eth_per_increment = increment // 10**9  # 1 for mainnet/minimal presets
    t = (total_increments // N) * eth_per_increment
    T = p.MAX_EFFECTIVE_BALANCE // 10**9
    delta = get_churn_limit(p, N)
    Delta = p.MAX_DEPOSITS * p.SLOTS_PER_EPOCH
    D = safety_decay
    if T * (200 + 3 * D) < t * (200 + 12 * D):
        epochs_for_validator_set_churn = (
            N * (t * (200 + 12 * D) - T * (200 + 3 * D))
        ) // (600 * delta * (2 * t + T))
        epochs_for_balance_top_ups = (N * (200 + 3 * D)) // (600 * Delta)
        ws_period += max(epochs_for_validator_set_churn, epochs_for_balance_top_ups)
    elif T != t:
        ws_period += (3 * N * D * t) // (200 * Delta * (T - t))
    return ws_period


def is_within_weak_subjectivity_period(
    p: Preset, ws_state, ws_checkpoint_epoch: int, current_epoch: int
) -> bool:
    """isWithinWeakSubjectivityPeriod (weakSubjectivity.ts:94)."""
    ws_period = compute_weak_subjectivity_period(p, ws_state)
    return current_epoch <= ws_checkpoint_epoch + ws_period
