"""Beacon chain state transition (phase0-first) + caches + signature sets.

Reference: packages/state-transition (src/stateTransition.ts:19 entry,
src/cache/epochContext.ts:78 caches, src/signatureSets/index.ts:23
collectors).  See SURVEY.md §2.2.
"""

from .domain import (  # noqa: F401
    compute_domain,
    compute_fork_data_root,
    compute_fork_digest,
    compute_signing_root,
)
