"""Beacon chain state transition (phase0-first) + caches + signature sets.

Reference: packages/state-transition (src/stateTransition.ts:19 entry,
src/cache/epochContext.ts:78 caches, src/signatureSets/index.ts:23
collectors).  See SURVEY.md §2.2.
"""

from .domain import (  # noqa: F401
    compute_domain,
    compute_fork_data_root,
    compute_fork_digest,
    compute_signing_root,
    get_domain,
)
from .epoch_context import EpochContext, EpochShuffling, PubkeyIndexMap  # noqa: F401
from .genesis import interop_genesis_state, is_valid_genesis_state  # noqa: F401
from .misc import (  # noqa: F401
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_active_validator_indices,
)
from .signature_sets import get_block_signature_sets  # noqa: F401
from .state_transition import (  # noqa: F401
    StateTransitionError,
    clone_state,
    process_slot,
    process_slots,
    state_transition,
)
