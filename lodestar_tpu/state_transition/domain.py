"""Domain and signing-root computation (consensus spec beacon-chain.md).

Reference: packages/state-transition/src/util/domain.ts and
packages/config's fork-digest caching (config/src/beaconConfig.ts).
"""

from __future__ import annotations

from ..params import Preset
from ..ssz import Fields
from ..types import get_types

ZERO_ROOT = b"\x00" * 32


def compute_fork_data_root(preset: Preset, current_version: bytes, genesis_validators_root: bytes) -> bytes:
    t = get_types(preset).phase0
    return t.ForkData.hash_tree_root(
        Fields(current_version=current_version, genesis_validators_root=genesis_validators_root)
    )


def compute_fork_digest(preset: Preset, current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(preset, current_version, genesis_validators_root)[:4]


def compute_domain(
    preset: Preset,
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes = ZERO_ROOT,
) -> bytes:
    """domain = domain_type (4 bytes) || fork_data_root[:28]."""
    fork_data_root = compute_fork_data_root(preset, fork_version, genesis_validators_root)
    return domain_type + fork_data_root[:28]


def compute_signing_root(preset: Preset, ssz_type, obj, domain: bytes) -> bytes:
    t = get_types(preset).phase0
    return t.SigningData.hash_tree_root(
        Fields(object_root=ssz_type.hash_tree_root(obj), domain=domain)
    )


def get_domain(preset: Preset, state, domain_type: bytes, epoch: int) -> bytes:
    """Spec get_domain over a BeaconState value (fork-aware version pick)."""
    fork = state.fork
    fork_version = fork.previous_version if epoch < fork.epoch else fork.current_version
    return compute_domain(preset, domain_type, fork_version, state.genesis_validators_root)
