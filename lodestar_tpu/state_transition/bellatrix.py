"""Bellatrix (the merge) state transition: execution payloads.

Reference: packages/state-transition/src/block/processExecutionPayload.ts,
util/execution.ts (isMergeTransitionComplete/isMergeTransitionBlock/
isExecutionEnabled), and the execution-engine seam consumed by
chain/blocks/verifyBlock.ts:195 (notifyNewPayload).

The engine here is the in-STF interface only; the HTTP Engine-API client
lives in lodestar_tpu.execution (ExecutionEngineHttp analog), with mock and
disabled doubles mirroring execution/engine/{mock,disabled}.ts.
"""

from __future__ import annotations

from typing import Optional, Protocol

from ..config.chain_config import ChainConfig
from ..params import Preset
from ..ssz import Fields
from ..types import get_types
from .block import BlockProcessingError
from .misc import compute_epoch_at_slot, get_randao_mix


class ExecutionEngine(Protocol):
    """notifyNewPayload seam (execution/engine/interface.ts)."""

    def notify_new_payload(self, payload) -> bool: ...


class NoopExecutionEngine:
    """Accept-everything engine for pre-merge dev chains and tests
    (execution/engine/mock.ts:23 analog)."""

    def notify_new_payload(self, payload) -> bool:
        return True


def default_payload_header(p: Preset) -> Fields:
    return Fields(
        parent_hash=b"\x00" * 32,
        fee_recipient=b"\x00" * 20,
        state_root=b"\x00" * 32,
        receipts_root=b"\x00" * 32,
        logs_bloom=b"\x00" * p.BYTES_PER_LOGS_BLOOM,
        prev_randao=b"\x00" * 32,
        block_number=0,
        gas_limit=0,
        gas_used=0,
        timestamp=0,
        extra_data=b"",
        base_fee_per_gas=0,
        block_hash=b"\x00" * 32,
        transactions_root=b"\x00" * 32,
    )


def is_merge_transition_complete(p: Preset, state) -> bool:
    t = get_types(p).bellatrix
    default = default_payload_header(p)
    return t.ExecutionPayloadHeader.serialize(
        state.latest_execution_payload_header
    ) != t.ExecutionPayloadHeader.serialize(default)


def _is_default_payload(p: Preset, payload) -> bool:
    t = get_types(p).bellatrix
    default = Fields(
        **{k: getattr(default_payload_header(p), k) for k in (
            "parent_hash", "fee_recipient", "state_root", "receipts_root",
            "logs_bloom", "prev_randao", "block_number", "gas_limit",
            "gas_used", "timestamp", "extra_data", "base_fee_per_gas",
            "block_hash",
        )},
        transactions=[],
    )
    return t.ExecutionPayload.serialize(payload) == t.ExecutionPayload.serialize(default)


def _is_default_payload_header(p: Preset, header) -> bool:
    t = get_types(p).bellatrix
    return t.ExecutionPayloadHeader.serialize(header) == t.ExecutionPayloadHeader.serialize(
        default_payload_header(p)
    )


def is_merge_transition_block(p: Preset, state, body) -> bool:
    if is_merge_transition_complete(p, state):
        return False
    if "execution_payload_header" in body:
        # blinded body (spec blinded-beacon-block variant): judge by header
        return not _is_default_payload_header(p, body.execution_payload_header)
    return not _is_default_payload(p, body.execution_payload)


def is_execution_enabled(p: Preset, state, body) -> bool:
    return is_merge_transition_block(p, state, body) or is_merge_transition_complete(p, state)


def compute_timestamp_at_slot(p: Preset, cfg: ChainConfig, state, slot: int) -> int:
    slots_since_genesis = slot - 0  # GENESIS_SLOT
    return state.genesis_time + slots_since_genesis * cfg.SECONDS_PER_SLOT


def process_execution_payload(
    p: Preset,
    cfg: ChainConfig,
    state,
    body,
    execution_engine: Optional[ExecutionEngine] = None,
) -> None:
    """Spec process_execution_payload (block/processExecutionPayload.ts).

    Accepts either a full body (``execution_payload``) or a blinded one
    (``execution_payload_header``): the builder flow signs over the
    header alone, so the header-only transition must produce the exact
    state root the full-payload transition would (the installed header
    is identical either way).  Reference: the `blinded` type param
    threading through processExecutionPayload.ts."""
    t = get_types(p).bellatrix
    blinded = "execution_payload_header" in body
    payload = body.execution_payload_header if blinded else body.execution_payload
    if is_merge_transition_complete(p, state):
        if bytes(payload.parent_hash) != bytes(state.latest_execution_payload_header.block_hash):
            raise BlockProcessingError("execution payload parent hash mismatch")
    epoch = compute_epoch_at_slot(p, state.slot)
    if bytes(payload.prev_randao) != bytes(get_randao_mix(p, state, epoch)):
        raise BlockProcessingError("execution payload prev_randao mismatch")
    if payload.timestamp != compute_timestamp_at_slot(p, cfg, state, state.slot):
        raise BlockProcessingError("execution payload timestamp mismatch")
    if not blinded and execution_engine is not None and not execution_engine.notify_new_payload(payload):
        raise BlockProcessingError("execution payload rejected by engine")

    if blinded:
        transactions_root = bytes(payload.transactions_root)
    else:
        tx_list_type = dict(t.ExecutionPayload.fields)["transactions"]
        transactions_root = tx_list_type.hash_tree_root(payload.transactions)
    state.latest_execution_payload_header = Fields(
        parent_hash=bytes(payload.parent_hash),
        fee_recipient=bytes(payload.fee_recipient),
        state_root=bytes(payload.state_root),
        receipts_root=bytes(payload.receipts_root),
        logs_bloom=bytes(payload.logs_bloom),
        prev_randao=bytes(payload.prev_randao),
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=bytes(payload.extra_data),
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=bytes(payload.block_hash),
        transactions_root=transactions_root,
    )
