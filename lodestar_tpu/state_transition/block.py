"""Phase0 block processing (consensus spec beacon-chain.md, v1.1.10).

Reference: packages/state-transition/src/block/ (18 files, SURVEY §2.2).
Signature policy mirrors the reference's eth2fastspec style
(stateTransition.ts:19): with ``verify_signatures=False`` every BLS check is
DEFERRED — collectors (signature_sets.py) later produce the whole block's
sets for one batched device dispatch (chain/blocks/verifyBlock.ts:177-190).
Deposit signatures are the exception: an invalid deposit signature skips
the deposit (it can never fail the block), so it is checked inline.
"""

from __future__ import annotations

import hashlib

from ..config.chain_config import ChainConfig
from ..params import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
    Preset,
)
from ..ssz import Fields
from ..types import get_types
from .domain import compute_domain, compute_signing_root, get_domain
from .epoch_context import EpochContext
from .misc import (
    compute_epoch_at_slot,
    get_randao_mix,
    increase_balance,
    is_active_validator,
    xor_bytes,
)
from .validator_ops import initiate_validator_exit, slash_validator


class BlockProcessingError(Exception):
    pass


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def process_block(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, block, verify_signatures: bool = True, execution_engine=None) -> None:
    """Fork-dispatching per-block processing (stateTransition.ts processBlock
    + block/index.ts per-fork tables)."""
    from .upgrade import ForkName, block_fork_name

    fork = block_fork_name(block)
    process_block_header(p, ctx, state, block)
    if fork == ForkName.bellatrix:
        from .bellatrix import is_execution_enabled, process_execution_payload

        if is_execution_enabled(p, state, block.body):
            process_execution_payload(p, cfg, state, block.body, execution_engine)
    process_randao(p, cfg, ctx, state, block.body, verify_signatures)
    process_eth1_data(p, state, block.body)
    process_operations(p, cfg, ctx, state, block.body, verify_signatures, fork=fork)
    if fork != ForkName.phase0:
        from .altair import process_sync_aggregate

        process_sync_aggregate(p, cfg, ctx, state, block.body.sync_aggregate, verify_signatures)


def process_block_header(p: Preset, ctx: EpochContext, state, block) -> None:
    from .upgrade import block_types

    t = block_types(p, block)
    if block.slot != state.slot:
        raise BlockProcessingError("block slot != state slot")
    if block.slot <= state.latest_block_header.slot:
        raise BlockProcessingError("block slot not newer than latest header")
    if block.proposer_index != ctx.get_beacon_proposer(block.slot):
        raise BlockProcessingError("wrong proposer index")
    if block.parent_root != t.BeaconBlockHeader.hash_tree_root(state.latest_block_header):
        raise BlockProcessingError("parent root mismatch")
    # a blinded body merkleizes to the SAME root as its full counterpart
    # (transactions_root == htr(transactions) by construction) but needs
    # its own container type to compute it
    body_type = (
        t.BlindedBeaconBlockBody
        if "execution_payload_header" in block.body
        else t.BeaconBlockBody
    )
    state.latest_block_header = Fields(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=b"\x00" * 32,  # set on the next process_slot
        body_root=body_type.hash_tree_root(block.body),
    )
    if state.validators[block.proposer_index].slashed:
        raise BlockProcessingError("proposer is slashed")


def process_randao(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, body, verify_signatures: bool) -> None:
    epoch = compute_epoch_at_slot(p, state.slot)
    if verify_signatures:
        from ..crypto.bls.api import Signature, verify
        from ..ssz import uint64

        proposer = ctx.get_beacon_proposer(state.slot)
        domain = get_domain(p, state, DOMAIN_RANDAO, epoch)
        root = compute_signing_root(p, uint64, epoch, domain)
        try:
            sig = Signature.from_bytes(body.randao_reveal)
        except ValueError as e:
            raise BlockProcessingError(f"malformed randao reveal: {e}") from None
        if not verify(ctx.index2pubkey[proposer], root, sig):
            raise BlockProcessingError("invalid randao reveal")
    mix = xor_bytes(get_randao_mix(p, state, epoch), _sha(bytes(body.randao_reveal)))
    state.randao_mixes[epoch % p.EPOCHS_PER_HISTORICAL_VECTOR] = mix


def process_eth1_data(p: Preset, state, body) -> None:
    state.eth1_data_votes.append(body.eth1_data)
    t = get_types(p).phase0
    vote_bytes = t.Eth1Data.serialize(body.eth1_data)
    count = sum(1 for v in state.eth1_data_votes if t.Eth1Data.serialize(v) == vote_bytes)
    if count * 2 > p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH:
        state.eth1_data = body.eth1_data


def process_operations(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, body, verify_signatures: bool, fork=None) -> None:
    from .upgrade import ForkName

    expected_deposits = min(p.MAX_DEPOSITS, state.eth1_data.deposit_count - state.eth1_deposit_index)
    if len(body.deposits) != expected_deposits:
        raise BlockProcessingError("wrong deposit count in block")
    for op in body.proposer_slashings:
        process_proposer_slashing(p, cfg, ctx, state, op, verify_signatures)
    for op in body.attester_slashings:
        process_attester_slashing(p, cfg, ctx, state, op, verify_signatures)
    for op in body.attestations:
        if fork is None or fork == ForkName.phase0:
            process_attestation(p, ctx, state, op, verify_signatures)
        else:
            from .altair import process_attestation_altair

            process_attestation_altair(p, cfg, ctx, state, op, verify_signatures)
    for op in body.deposits:
        process_deposit(p, cfg, ctx, state, op)
    for op in body.voluntary_exits:
        process_voluntary_exit(p, cfg, ctx, state, op, verify_signatures)


# -- slashings ---------------------------------------------------------------


def is_slashable_validator(v, epoch: int) -> bool:
    return (not v.slashed) and v.activation_epoch <= epoch < v.withdrawable_epoch


def is_slashable_attestation_data(d1, d2) -> bool:
    """Double vote or surround vote."""
    double = (d1.target.epoch == d2.target.epoch) and not _att_data_eq(d1, d2)
    surround = d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch
    return double or surround


def _att_data_eq(d1, d2) -> bool:
    return (
        d1.slot == d2.slot
        and d1.index == d2.index
        and d1.beacon_block_root == d2.beacon_block_root
        and d1.source.epoch == d2.source.epoch
        and d1.source.root == d2.source.root
        and d1.target.epoch == d2.target.epoch
        and d1.target.root == d2.target.root
    )


def is_valid_indexed_attestation(p: Preset, ctx: EpochContext, state, indexed, verify_signature: bool) -> bool:
    indices = list(indexed.attesting_indices)
    if not indices or indices != sorted(set(indices)):
        return False
    if len(indices) > p.MAX_VALIDATORS_PER_COMMITTEE:
        return False
    if any(i >= len(state.validators) for i in indices):
        return False
    if verify_signature:
        from .signature_sets import indexed_attestation_signature_set
        from ..crypto.bls.verifier import PyBlsVerifier

        s = indexed_attestation_signature_set(p, ctx, state, indexed)
        return PyBlsVerifier().verify_signature_sets([s])
    return True


def process_proposer_slashing(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, slashing, verify_signatures: bool) -> None:
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    t = get_types(p).phase0
    if h1.slot != h2.slot:
        raise BlockProcessingError("proposer slashing: slots differ")
    if h1.proposer_index != h2.proposer_index:
        raise BlockProcessingError("proposer slashing: proposer differs")
    if t.BeaconBlockHeader.serialize(h1) == t.BeaconBlockHeader.serialize(h2):
        raise BlockProcessingError("proposer slashing: identical headers")
    proposer = state.validators[h1.proposer_index]
    if not is_slashable_validator(proposer, compute_epoch_at_slot(p, state.slot)):
        raise BlockProcessingError("proposer slashing: not slashable")
    if verify_signatures:
        from .signature_sets import proposer_slashing_signature_sets
        from ..crypto.bls.verifier import PyBlsVerifier

        if not PyBlsVerifier().verify_signature_sets(
            proposer_slashing_signature_sets(p, ctx, state, slashing)
        ):
            raise BlockProcessingError("proposer slashing: bad signature")
    slash_validator(p, cfg, state, h1.proposer_index, ctx.get_beacon_proposer(state.slot))


def process_attester_slashing(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, slashing, verify_signatures: bool) -> None:
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise BlockProcessingError("attester slashing: data not slashable")
    if not is_valid_indexed_attestation(p, ctx, state, a1, verify_signatures):
        raise BlockProcessingError("attester slashing: attestation 1 invalid")
    if not is_valid_indexed_attestation(p, ctx, state, a2, verify_signatures):
        raise BlockProcessingError("attester slashing: attestation 2 invalid")
    epoch = compute_epoch_at_slot(p, state.slot)
    slashed_any = False
    proposer = ctx.get_beacon_proposer(state.slot)
    for index in sorted(set(a1.attesting_indices) & set(a2.attesting_indices)):
        if is_slashable_validator(state.validators[index], epoch):
            slash_validator(p, cfg, state, index, proposer)
            slashed_any = True
    if not slashed_any:
        raise BlockProcessingError("attester slashing: no one slashed")


# -- attestations ------------------------------------------------------------


def process_attestation(p: Preset, ctx: EpochContext, state, attestation, verify_signatures: bool) -> None:
    data = attestation.data
    current_epoch = compute_epoch_at_slot(p, state.slot)
    previous_epoch = max(0, current_epoch - 1)
    if data.target.epoch not in (previous_epoch, current_epoch):
        raise BlockProcessingError("attestation: target epoch not current/previous")
    if data.target.epoch != compute_epoch_at_slot(p, data.slot):
        raise BlockProcessingError("attestation: target epoch != slot epoch")
    if not (data.slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot <= data.slot + p.SLOTS_PER_EPOCH):
        raise BlockProcessingError("attestation: outside inclusion window")
    if data.index >= ctx.get_committee_count_per_slot(data.target.epoch):
        raise BlockProcessingError("attestation: committee index out of range")
    committee = ctx.get_beacon_committee(data.slot, data.index)
    if len(attestation.aggregation_bits) != len(committee):
        raise BlockProcessingError("attestation: bits/committee length mismatch")

    pending = Fields(
        aggregation_bits=list(attestation.aggregation_bits),
        data=data,
        inclusion_delay=state.slot - data.slot,
        proposer_index=ctx.get_beacon_proposer(state.slot),
    )
    if data.target.epoch == current_epoch:
        if not _checkpoint_eq(data.source, state.current_justified_checkpoint):
            raise BlockProcessingError("attestation: wrong source (current)")
        state.current_epoch_attestations.append(pending)
    else:
        if not _checkpoint_eq(data.source, state.previous_justified_checkpoint):
            raise BlockProcessingError("attestation: wrong source (previous)")
        state.previous_epoch_attestations.append(pending)

    indexed = ctx.get_indexed_attestation(attestation)
    if not is_valid_indexed_attestation(p, ctx, state, indexed, verify_signatures):
        raise BlockProcessingError("attestation: invalid indexed attestation")


def _checkpoint_eq(a, b) -> bool:
    return a.epoch == b.epoch and a.root == b.root


# -- deposits ----------------------------------------------------------------


def is_valid_merkle_branch(leaf: bytes, branch, depth: int, index: int, root: bytes) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = _sha(bytes(branch[i]) + value)
        else:
            value = _sha(value + bytes(branch[i]))
    return value == root


def process_deposit(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, deposit) -> None:
    t = get_types(p).phase0
    leaf = t.DepositData.hash_tree_root(deposit.data)
    if not is_valid_merkle_branch(
        leaf,
        deposit.proof,
        DEPOSIT_CONTRACT_TREE_DEPTH + 1,  # +1 for the length mix-in
        state.eth1_deposit_index,
        state.eth1_data.deposit_root,
    ):
        raise BlockProcessingError("deposit: invalid merkle proof")
    state.eth1_deposit_index += 1
    apply_deposit(p, cfg, ctx, state, deposit.data)


def apply_deposit(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, data) -> None:
    """Add validator or top-up.  Invalid-signature deposits are skipped,
    never a block failure (spec); so the check is inline, not collected."""
    pubkey = bytes(data.pubkey)
    amount = data.amount
    index = ctx.pubkey2index.get(pubkey)
    if index is not None:
        increase_balance(state, index, amount)
        return
    # new validator: proof of possession with GENESIS_FORK_VERSION domain
    from ..crypto.bls.api import PublicKey, Signature, verify

    domain = compute_domain(p, DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION)
    msg = Fields(pubkey=data.pubkey, withdrawal_credentials=data.withdrawal_credentials, amount=amount)
    t = get_types(p).phase0
    root = compute_signing_root(p, t.DepositMessage, msg, domain)
    try:
        pk = PublicKey.from_bytes(pubkey)
        sig = Signature.from_bytes(bytes(data.signature))
    except ValueError:
        return  # malformed -> skip deposit
    if not verify(pk, root, sig):
        return
    eff = min(amount - amount % p.EFFECTIVE_BALANCE_INCREMENT, p.MAX_EFFECTIVE_BALANCE)
    state.validators.append(
        Fields(
            pubkey=pubkey,
            withdrawal_credentials=bytes(data.withdrawal_credentials),
            effective_balance=eff,
            slashed=False,
            activation_eligibility_epoch=FAR_FUTURE_EPOCH,
            activation_epoch=FAR_FUTURE_EPOCH,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
    )
    state.balances.append(amount)
    new_index = len(state.validators) - 1
    ctx.pubkey2index.set(pubkey, new_index)
    ctx.index2pubkey.append(pk)


# -- exits -------------------------------------------------------------------


def process_voluntary_exit(p: Preset, cfg: ChainConfig, ctx: EpochContext, state, signed_exit, verify_signatures: bool) -> None:
    exit_msg = signed_exit.message
    if exit_msg.validator_index >= len(state.validators):
        raise BlockProcessingError("exit: unknown validator")
    v = state.validators[exit_msg.validator_index]
    current_epoch = compute_epoch_at_slot(p, state.slot)
    if not is_active_validator(v, current_epoch):
        raise BlockProcessingError("exit: validator not active")
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        raise BlockProcessingError("exit: already exiting")
    if current_epoch < exit_msg.epoch:
        raise BlockProcessingError("exit: epoch in the future")
    if current_epoch < v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD:
        raise BlockProcessingError("exit: too early after activation")
    if verify_signatures:
        from .signature_sets import voluntary_exit_signature_set
        from ..crypto.bls.verifier import PyBlsVerifier

        if not PyBlsVerifier().verify_signature_sets(
            [voluntary_exit_signature_set(p, ctx, state, signed_exit)]
        ):
            raise BlockProcessingError("exit: bad signature")
    initiate_validator_exit(p, cfg, state, exit_msg.validator_index)
