"""Req/resp protocol: method registry + server dispatch + client calls.

Reference: packages/beacon-node/src/network/reqresp/reqResp.ts:45 (method
set + rate limits) and reqresp/handlers/index.ts (server side answering
from chain/db).  Methods carried over: status, goodbye, ping, metadata,
beaconBlocksByRange, beaconBlocksByRoot — the set range sync and peering
need (SURVEY §2.5).
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Awaitable, Callable, Dict, List, Optional

from ..params import Preset
from ..types import get_types
from ..utils.logger import get_logger
from .wire import (
    KIND_RESPONSE_CHUNK,
    KIND_RESPONSE_END,
    RESULT_INVALID_REQUEST,
    RESULT_RATE_LIMITED,
    RESULT_SERVER_ERROR,
    RESULT_SUCCESS,
    Wire,
)

logger = get_logger("reqresp")

METHOD_STATUS = 0
METHOD_GOODBYE = 1
METHOD_PING = 2
METHOD_METADATA = 3
METHOD_BLOCKS_BY_RANGE = 4
METHOD_BLOCKS_BY_ROOT = 5

MAX_REQUEST_BLOCKS = 1024

# Per-method response-chunk ceilings (reference: maxResponseChunks wired
# into each protocol def, reqResp.ts:94-127).  Single-chunk methods get 1;
# block methods get MAX_REQUEST_BLOCKS.  A malicious server streaming more
# chunks than its method allows is cut off instead of OOM-ing the client.
MAX_RESPONSE_CHUNKS = {
    METHOD_STATUS: 1,
    METHOD_GOODBYE: 1,
    METHOD_PING: 1,
    METHOD_METADATA: 1,
    METHOD_BLOCKS_BY_RANGE: MAX_REQUEST_BLOCKS,
    METHOD_BLOCKS_BY_ROOT: MAX_REQUEST_BLOCKS,
}
# total decompressed bytes a single request may accumulate client-side
MAX_RESPONSE_TOTAL_BYTES = 128 * 1024 * 1024


class RateTracker:
    """Sliding-window quota (reqresp/rateTracker.ts:14): N units per
    60-second window.  requestCount and objectCount (blocks served) are
    tracked separately per peer connection."""

    def __init__(self, limit: int, window_s: float = 60.0):
        self.limit = limit
        self.window_s = window_s
        self._events: List[tuple] = []  # (monotonic_time, units)

    def request_units(self, units: int = 1) -> bool:
        """True if the quota admits `units` more; records them if so."""
        import time as _t

        now = _t.monotonic()
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.pop(0)
        used = sum(u for _, u in self._events)
        if used + units > self.limit:
            return False
        self._events.append((now, units))
        return True


# per-peer-connection quotas (reference requestCountPeerLimit=50/min,
# blockCountPeerLimit=500/min)
REQUEST_COUNT_PER_MINUTE = 50
BLOCK_COUNT_PER_MINUTE = 500


class RequestError(Exception):
    def __init__(self, result: int, message: str = ""):
        super().__init__(f"reqresp error {result}: {message}")
        self.result = result


class ReqRespNode:
    """Per-connection req/resp endpoint: issues requests, answers peers'.

    The server side answers from the chain: status from fork choice/head,
    blocks from the hot db + archive (handlers/beaconBlocksByRange.ts).
    """

    def __init__(self, preset: Preset, chain, wire: Wire, metadata=None, metrics=None):
        self.p = preset
        self.chain = chain
        self.metrics = metrics
        self.t = get_types(preset).phase0
        self.wire = wire
        self.metadata_controller = metadata  # network/metadata.ts source
        self._req_ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Queue] = {}
        # server-side quotas for THIS peer (rateTracker.ts)
        self.request_rate = RateTracker(REQUEST_COUNT_PER_MINUTE)
        self.block_rate = RateTracker(BLOCK_COUNT_PER_MINUTE)
        self.rate_limited_count = 0

    # -- client side -----------------------------------------------------------

    async def _request(self, method: int, ssz_bytes: bytes, timeout: float = 10.0) -> List[bytes]:
        import time as _time

        _t0 = _time.monotonic()
        try:
            return await self._request_inner(method, ssz_bytes, timeout)
        except (RequestError, asyncio.TimeoutError) as e:
            if self.metrics:
                reason = "timeout" if isinstance(e, asyncio.TimeoutError) else "error"
                self.metrics.reqresp_errors_total.labels(
                    method=str(method), reason=reason
                ).inc()
            raise
        finally:
            if self.metrics:
                self.metrics.reqresp_request_seconds.labels(method=str(method)).observe(
                    _time.monotonic() - _t0
                )

    async def _request_inner(self, method: int, ssz_bytes: bytes, timeout: float = 10.0) -> List[bytes]:
        req_id = next(self._req_ids)
        q: asyncio.Queue = asyncio.Queue()
        self._pending[req_id] = q
        # overall deadline, not per-chunk: a malicious peer must not keep a
        # request alive forever by trickling chunks (ADVICE r3 — the
        # per-chunk wait_for reset the timeout on every chunk)
        deadline = asyncio.get_event_loop().time() + timeout
        max_chunks = MAX_RESPONSE_CHUNKS.get(method, 1)
        total = 0
        try:
            from .wire import KIND_REQUEST

            await self.wire.send_frame(KIND_REQUEST, Wire.encode_request(method, req_id, ssz_bytes))
            chunks: List[bytes] = []
            while True:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    raise asyncio.TimeoutError()
                kind, result, body = await asyncio.wait_for(q.get(), remaining)
                if kind == KIND_RESPONSE_END:
                    return chunks
                if result != RESULT_SUCCESS:
                    raise RequestError(result, body.decode(errors="replace"))
                total += len(body)
                if len(chunks) >= max_chunks:
                    raise RequestError(
                        RESULT_INVALID_REQUEST, f"method {method} sent >{max_chunks} chunks"
                    )
                if total > MAX_RESPONSE_TOTAL_BYTES:
                    raise RequestError(RESULT_INVALID_REQUEST, "response exceeds byte budget")
                chunks.append(body)
        finally:
            self._pending.pop(req_id, None)

    async def status(self, local_status) -> object:
        chunks = await self._request(METHOD_STATUS, self.t.Status.serialize(local_status))
        if not chunks:
            raise RequestError(RESULT_SERVER_ERROR, "empty status response")
        return self.t.Status.deserialize(chunks[0])

    async def goodbye(self, reason: int = 0) -> None:
        try:
            await self._request(METHOD_GOODBYE, self.t.Goodbye.serialize(reason), timeout=2.0)
        except Exception:
            pass

    async def ping(self, seq: int = 0) -> int:
        chunks = await self._request(METHOD_PING, self.t.Ping.serialize(seq))
        return self.t.Ping.deserialize(chunks[0]) if chunks else 0

    async def metadata(self) -> object:
        chunks = await self._request(METHOD_METADATA, b"")
        if not chunks:
            raise RequestError(RESULT_SERVER_ERROR, "empty metadata response")
        return self.t.Metadata.deserialize(chunks[0])

    async def blocks_by_range(self, start_slot: int, count: int, step: int = 1) -> List[object]:
        req = self.t.BeaconBlocksByRangeRequest.serialize(
            _fields(start_slot=start_slot, count=count, step=step)
        )
        chunks = await self._request(METHOD_BLOCKS_BY_RANGE, req, timeout=30.0)
        return [self._decode_block(c) for c in chunks]

    async def blocks_by_root(self, roots: List[bytes]) -> List[object]:
        req = self.t.BeaconBlocksByRootRequest.serialize(_fields(roots=list(roots)))
        chunks = await self._request(METHOD_BLOCKS_BY_ROOT, req, timeout=30.0)
        return [self._decode_block(c) for c in chunks]

    def _decode_block(self, b: bytes):
        # fork-tagged on the wire (mirrors the db codec): 1 tag byte + SSZ
        from ..db.beacon import _FORK_ORDER

        all_t = get_types(self.p)
        t = getattr(all_t, _FORK_ORDER[b[0]])
        return t.SignedBeaconBlock.deserialize(b[1:])

    def _encode_block(self, signed_block) -> bytes:
        from ..db.beacon import _FORK_ORDER
        from ..state_transition.upgrade import block_fork_name

        fork = block_fork_name(signed_block.message).value
        all_t = get_types(self.p)
        t = getattr(all_t, fork)
        return bytes([_FORK_ORDER.index(fork)]) + t.SignedBeaconBlock.serialize(signed_block)

    # -- dispatch --------------------------------------------------------------

    def on_response_frame(self, kind: int, payload: bytes) -> None:
        if kind == KIND_RESPONSE_CHUNK:
            req_id, result, body = Wire.decode_response_chunk(payload)
            q = self._pending.get(req_id)
            if q is not None:
                q.put_nowait((kind, result, body))
        elif kind == KIND_RESPONSE_END:
            req_id = Wire.decode_response_end(payload)
            q = self._pending.get(req_id)
            if q is not None:
                q.put_nowait((kind, RESULT_SUCCESS, b""))

    async def on_request_frame(self, payload: bytes) -> None:
        try:
            method, req_id, body = Wire.decode_request(payload)
        except Exception:
            return  # malformed; drop
        try:
            chunks = await self._serve(method, body)
            for c in chunks:
                await self.wire.send_frame(
                    KIND_RESPONSE_CHUNK, Wire.encode_response_chunk(req_id, RESULT_SUCCESS, c)
                )
        except RequestError as e:
            await self.wire.send_frame(
                KIND_RESPONSE_CHUNK,
                Wire.encode_response_chunk(req_id, e.result, str(e).encode()),
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("reqresp server error: %s", e)
            await self.wire.send_frame(
                KIND_RESPONSE_CHUNK,
                Wire.encode_response_chunk(req_id, RESULT_SERVER_ERROR, str(e).encode()),
            )
        await self.wire.send_frame(KIND_RESPONSE_END, Wire.encode_response_end(req_id))

    async def _serve(self, method: int, body: bytes) -> List[bytes]:
        if not self.request_rate.request_units(1):
            self.rate_limited_count += 1
            raise RequestError(RESULT_RATE_LIMITED, "request quota exceeded")
        if method == METHOD_STATUS:
            return [self.t.Status.serialize(self.local_status())]
        if method == METHOD_GOODBYE:
            return [self.t.Goodbye.serialize(0)]
        if method == METHOD_PING:
            seq = self.t.Ping.deserialize(body)
            return [self.t.Ping.serialize(seq)]
        if method == METHOD_METADATA:
            mc = self.metadata_controller
            return [
                self.t.Metadata.serialize(
                    _fields(
                        seq_number=mc.seq_number if mc else 0,
                        attnets=list(mc.attnets) if mc else [False] * 64,
                    )
                )
            ]
        if method == METHOD_BLOCKS_BY_RANGE:
            req = self.t.BeaconBlocksByRangeRequest.deserialize(body)
            if req.count > MAX_REQUEST_BLOCKS or req.step < 1:
                raise RequestError(RESULT_INVALID_REQUEST, "bad range request")
            # block quota charges objects served, not requests (rateTracker.ts)
            if not self.block_rate.request_units(max(1, int(req.count))):
                self.rate_limited_count += 1
                raise RequestError(RESULT_RATE_LIMITED, "block quota exceeded")
            return [
                self._encode_block(b)
                for b in self._blocks_in_range(req.start_slot, req.count, req.step)
            ]
        if method == METHOD_BLOCKS_BY_ROOT:
            req = self.t.BeaconBlocksByRootRequest.deserialize(body)
            if not self.block_rate.request_units(max(1, len(req.roots))):
                self.rate_limited_count += 1
                raise RequestError(RESULT_RATE_LIMITED, "block quota exceeded")
            out = []
            for root in req.roots[:MAX_REQUEST_BLOCKS]:
                blk = self.chain.get_block_by_root(bytes(root))
                if blk is not None:
                    out.append(self._encode_block(blk))
            return out
        raise RequestError(RESULT_INVALID_REQUEST, f"unknown method {method}")

    def local_status(self):
        chain = self.chain
        head_state = chain.head_state()
        from ..state_transition import compute_fork_digest

        digest = compute_fork_digest(
            self.p,
            bytes(head_state.fork.current_version),
            bytes(head_state.genesis_validators_root),
        )
        fc = chain.fork_choice.store
        return _fields(
            fork_digest=digest,
            finalized_root=fc.finalized_checkpoint.root,
            finalized_epoch=fc.finalized_checkpoint.epoch,
            head_root=chain.head_root,
            head_slot=head_state.slot,
        )

    def _blocks_in_range(self, start_slot: int, count: int, step: int) -> List[object]:
        """Canonical blocks in [start_slot, start_slot + count*step): walk
        the canonical chain via fork choice ancestors + archive."""
        chain = self.chain
        wanted = range(start_slot, start_slot + count * step, step)
        out = []
        # archived (finalized) portion, slot-ordered
        for blk in chain.db.archived_blocks_by_slot_range(start_slot, wanted[-1] + 1):
            if blk.message.slot in wanted:
                out.append(blk)
        have = {b.message.slot for b in out}
        # hot portion: canonical ancestors of the head
        root = chain.head_root
        hot = []
        while root is not None:
            blk = chain.db.block.get(root)
            if blk is None:
                break
            if blk.message.slot < start_slot:
                break
            if blk.message.slot in wanted and blk.message.slot not in have:
                hot.append(blk)
            root = bytes(blk.message.parent_root)
        out.extend(reversed(hot))
        out.sort(key=lambda b: b.message.slot)
        return out


def _fields(**kw):
    from ..ssz import Fields

    return Fields(**kw)
