"""Wire framing: multiplexed req/resp + gossip frames over one stream.

Frame layout (all integers unsigned LEB128 varints):
    [kind: 1 byte][payload_len: uvarint][payload]

kinds:
    0x01 REQUEST        payload = [method: uvarint][req_id: uvarint][ssz_snappy]
    0x02 RESPONSE_CHUNK payload = [req_id: uvarint][result: 1 byte][ssz_snappy]
    0x03 RESPONSE_END   payload = [req_id: uvarint]
    0x04 GOSSIP         payload = [topic_len: uvarint][topic utf8][ssz_snappy]
    0x05 GOSSIP_CTRL    payload = gossipsub control record (see
         encode_gossip_ctrl): SUB/UNSUB/GRAFT/PRUNE topic lists + IHAVE
         (topic, message-id list) + IWANT (message-id list)

ssz_snappy = snappy *frame* compression of the SSZ bytes, matching the
reference's req/resp encoding (network/reqresp/encodingStrategies) via the
pure-Python frame codec in utils/snappy.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple

from ..utils.snappy import frame_compress, frame_uncompress

KIND_REQUEST = 0x01
KIND_RESPONSE_CHUNK = 0x02
KIND_RESPONSE_END = 0x03
KIND_GOSSIP = 0x04
KIND_GOSSIP_CTRL = 0x05

MSG_ID_LEN = 20

RESULT_SUCCESS = 0
RESULT_INVALID_REQUEST = 1
RESULT_SERVER_ERROR = 2
RESULT_RATE_LIMITED = 3  # spec ResourceUnavailable class

MAX_PAYLOAD = 32 * 1024 * 1024
# decompressed-size bound for any single wire message: matches the spec's
# MAX_CHUNK_SIZE/GOSSIP_MAX_SIZE class of limits and stops a 32MB frame
# from expanding into hundreds of MB host-side (decompression bomb)
MAX_UNCOMPRESSED = 32 * 1024 * 1024


def write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(buf: bytes, offset: int = 0) -> Tuple[int, int]:
    """-> (value, next_offset); raises ValueError on truncation."""
    shift = 0
    val = 0
    while True:
        if offset >= len(buf):
            raise ValueError("truncated uvarint")
        b = buf[offset]
        offset += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, offset
        shift += 7
        if shift > 63:
            raise ValueError("uvarint too long")


async def read_uvarint_stream(reader: asyncio.StreamReader) -> int:
    shift = 0
    val = 0
    while True:
        b = (await reader.readexactly(1))[0]
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val
        shift += 7
        if shift > 63:
            raise ValueError("uvarint too long")


class Wire:
    """One framed duplex connection."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._wlock = asyncio.Lock()

    async def send_frame(self, kind: int, payload: bytes) -> None:
        if len(payload) > MAX_PAYLOAD:
            raise ValueError("payload too large")
        async with self._wlock:
            self.writer.write(bytes([kind]) + write_uvarint(len(payload)) + payload)
            await self.writer.drain()

    async def recv_frame(self) -> Tuple[int, bytes]:
        kind = (await self.reader.readexactly(1))[0]
        length = await read_uvarint_stream(self.reader)
        if length > MAX_PAYLOAD:
            raise ValueError("payload too large")
        payload = await self.reader.readexactly(length)
        return kind, payload

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass

    # -- payload builders ------------------------------------------------------

    @staticmethod
    def encode_request(method: int, req_id: int, ssz_bytes: bytes) -> bytes:
        return write_uvarint(method) + write_uvarint(req_id) + frame_compress(ssz_bytes)

    @staticmethod
    def decode_request(payload: bytes) -> Tuple[int, int, bytes]:
        method, off = read_uvarint(payload)
        req_id, off = read_uvarint(payload, off)
        return method, req_id, frame_uncompress(payload[off:], max_output=MAX_UNCOMPRESSED)

    @staticmethod
    def encode_response_chunk(req_id: int, result: int, ssz_bytes: bytes) -> bytes:
        return write_uvarint(req_id) + bytes([result]) + frame_compress(ssz_bytes)

    @staticmethod
    def decode_response_chunk(payload: bytes) -> Tuple[int, int, bytes]:
        req_id, off = read_uvarint(payload)
        if off >= len(payload):
            raise ValueError("truncated response chunk")
        result = payload[off]
        return req_id, result, frame_uncompress(payload[off + 1 :], max_output=MAX_UNCOMPRESSED)

    @staticmethod
    def encode_response_end(req_id: int) -> bytes:
        return write_uvarint(req_id)

    @staticmethod
    def decode_response_end(payload: bytes) -> int:
        req_id, _ = read_uvarint(payload)
        return req_id

    @staticmethod
    def encode_gossip(topic: str, ssz_bytes: bytes) -> bytes:
        t = topic.encode()
        return write_uvarint(len(t)) + t + frame_compress(ssz_bytes)

    @staticmethod
    def decode_gossip(payload: bytes) -> Tuple[str, bytes]:
        tlen, off = read_uvarint(payload)
        topic = payload[off : off + tlen].decode()
        return topic, frame_uncompress(payload[off + tlen :], max_output=MAX_UNCOMPRESSED)

    # -- gossipsub control records ---------------------------------------------

    @staticmethod
    def _enc_topics(topics) -> bytes:
        out = write_uvarint(len(topics))
        for t in topics:
            tb = t.encode()
            out += write_uvarint(len(tb)) + tb
        return out

    @staticmethod
    def _dec_topics(payload: bytes, off: int):
        n, off = read_uvarint(payload, off)
        if n > 4096:
            raise ValueError("too many topics")
        topics = []
        for _ in range(n):
            tlen, off = read_uvarint(payload, off)
            topics.append(payload[off : off + tlen].decode())
            off += tlen
        return topics, off

    @staticmethod
    def encode_gossip_ctrl(ctrl: dict) -> bytes:
        """ctrl keys: sub/unsub/graft/prune (topic lists), ihave (list of
        (topic, [20-byte ids])), iwant ([20-byte ids])."""
        out = b""
        for key in ("sub", "unsub", "graft", "prune"):
            out += Wire._enc_topics(ctrl.get(key, []))
        ihave = ctrl.get("ihave", [])
        out += write_uvarint(len(ihave))
        for topic, ids in ihave:
            tb = topic.encode()
            out += write_uvarint(len(tb)) + tb + write_uvarint(len(ids))
            for mid in ids:
                out += bytes(mid[:MSG_ID_LEN]).ljust(MSG_ID_LEN, b"\x00")
        iwant = ctrl.get("iwant", [])
        out += write_uvarint(len(iwant))
        for mid in iwant:
            out += bytes(mid[:MSG_ID_LEN]).ljust(MSG_ID_LEN, b"\x00")
        return out

    @staticmethod
    def decode_gossip_ctrl(payload: bytes) -> dict:
        ctrl: dict = {}
        off = 0
        for key in ("sub", "unsub", "graft", "prune"):
            topics, off = Wire._dec_topics(payload, off)
            if topics:
                ctrl[key] = topics
        n, off = read_uvarint(payload, off)
        if n > 4096:
            raise ValueError("too many ihave entries")
        ihave = []
        for _ in range(n):
            tlen, off = read_uvarint(payload, off)
            topic = payload[off : off + tlen].decode()
            off += tlen
            k, off = read_uvarint(payload, off)
            if k > 16384:
                raise ValueError("too many ihave ids")
            ids = []
            for _ in range(k):
                ids.append(payload[off : off + MSG_ID_LEN])
                off += MSG_ID_LEN
            ihave.append((topic, ids))
        if ihave:
            ctrl["ihave"] = ihave
        k, off = read_uvarint(payload, off)
        if k > 16384:
            raise ValueError("too many iwant ids")
        iwant = []
        for _ in range(k):
            iwant.append(payload[off : off + MSG_ID_LEN])
            off += MSG_ID_LEN
        if iwant:
            ctrl["iwant"] = iwant
        return ctrl
