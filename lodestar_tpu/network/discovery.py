"""Peer discovery: signed node records + a UDP FINDNODE protocol.

Reference: packages/beacon-node/src/network/peers/discover.ts:78 +
@chainsafe/discv5 (UDP ENR DHT).  The reference's discovery is a
dependency stack (discv5 handshake crypto, secp256k1/keccak ENRs —
SURVEY §2.9); this framework implements the same capability natively:

- ``NodeRecord``: an ENR-equivalent signed record (seq number, identity
  pubkey, ip/tcp/udp, attnets/syncnets bitfields) — BLS-signed with
  sha256 digests instead of secp256k1/keccak, since the node identity
  key here IS a BLS key and the wire is framework-native either way.
- ``DiscoveryService``: PING/PONG/FINDNODE/NODES over UDP with a
  last-seen routing table, bootstrap list, periodic random lookups, and
  a found-peer callback the Network uses to dial new peers (subnet-aware
  preference like discover.ts's subnet queries).

Record encoding is SSZ-style length-prefixed fields; every record is
verified (signature over its content) before entering the table, so a
hostile peer cannot forge records for identities it does not hold.
"""

from __future__ import annotations

import asyncio
import hashlib
import secrets as _secrets
import struct
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto.bls.api import PublicKey, SecretKey, Signature, verify
from ..utils.logger import get_logger

logger = get_logger("discovery")

MSG_PING = 1
MSG_PONG = 2
MSG_FINDNODE = 3
MSG_NODES = 4

MAX_RECORDS_PER_RESPONSE = 16
TABLE_SIZE = 256
RECORD_SIGN_DOMAIN = b"lodestar-tpu-node-record-v1"


def _pack_bytes(b: bytes) -> bytes:
    return struct.pack("<H", len(b)) + b


def _unpack_bytes(buf: bytes, off: int) -> Tuple[bytes, int]:
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    return buf[off : off + n], off + n


@dataclass
class NodeRecord:
    """ENR-equivalent signed node record."""

    seq: int
    pubkey: bytes  # 48-byte BLS identity key
    ip: str
    tcp_port: int
    udp_port: int
    attnets: bytes = b"\x00" * 8  # 64-bit bitfield
    syncnets: bytes = b"\x00"
    signature: bytes = b""

    @property
    def node_id(self) -> bytes:
        return hashlib.sha256(self.pubkey).digest()

    def _signed_content(self) -> bytes:
        return (
            RECORD_SIGN_DOMAIN
            + struct.pack("<Q", self.seq)
            + self.pubkey
            + _pack_bytes(self.ip.encode())
            + struct.pack("<HH", self.tcp_port, self.udp_port)
            + self.attnets
            + self.syncnets
        )

    def sign(self, sk: SecretKey) -> "NodeRecord":
        self.signature = sk.sign(hashlib.sha256(self._signed_content()).digest()).to_bytes()
        return self

    def verify_signature(self) -> bool:
        try:
            return verify(
                PublicKey.from_bytes(self.pubkey),
                hashlib.sha256(self._signed_content()).digest(),
                Signature.from_bytes(self.signature),
            )
        except ValueError:
            return False

    def encode(self) -> bytes:
        return (
            struct.pack("<Q", self.seq)
            + self.pubkey
            + _pack_bytes(self.ip.encode())
            + struct.pack("<HH", self.tcp_port, self.udp_port)
            + self.attnets
            + self.syncnets
            + _pack_bytes(self.signature)
        )

    @classmethod
    def decode(cls, buf: bytes) -> "NodeRecord":
        off = 0
        (seq,) = struct.unpack_from("<Q", buf, off)
        off += 8
        pubkey = buf[off : off + 48]
        off += 48
        ip, off = _unpack_bytes(buf, off)
        tcp_port, udp_port = struct.unpack_from("<HH", buf, off)
        off += 4
        attnets = buf[off : off + 8]
        off += 8
        syncnets = buf[off : off + 1]
        off += 1
        sig, off = _unpack_bytes(buf, off)
        return cls(
            seq=seq, pubkey=pubkey, ip=ip.decode(), tcp_port=tcp_port,
            udp_port=udp_port, attnets=attnets, syncnets=syncnets, signature=sig,
        )


@dataclass
class _Entry:
    record: NodeRecord
    last_seen: float = field(default_factory=time.monotonic)


class DiscoveryService(asyncio.DatagramProtocol):
    """UDP discovery endpoint + routing table (peers/discover.ts role)."""

    def __init__(
        self,
        identity: SecretKey,
        *,
        tcp_port: int,
        host: str = "127.0.0.1",
        on_peer: Optional[Callable[[NodeRecord], None]] = None,
    ):
        self.identity = identity
        self.host = host
        self.tcp_port = tcp_port
        self.udp_port: Optional[int] = None
        self.on_peer = on_peer
        self.table: Dict[bytes, _Entry] = {}
        self.record = NodeRecord(
            seq=1, pubkey=identity.to_public_key().to_bytes(), ip=host,
            tcp_port=tcp_port, udp_port=0,
        ).sign(identity)
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._task: Optional[asyncio.Task] = None
        self.lookups = 0

    # -- lifecycle ------------------------------------------------------------

    async def listen(self, udp_port: int = 0) -> int:
        loop = asyncio.get_event_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, udp_port)
        )
        self.udp_port = self._transport.get_extra_info("sockname")[1]
        self.record.udp_port = self.udp_port
        self.record.seq += 1
        self.record.sign(self.identity)
        logger.info("discovery on udp %s:%d", self.host, self.udp_port)
        return self.udp_port

    def start_lookups(self, interval: float = 5.0) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._lookup_loop(interval))

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._transport is not None:
            self._transport.close()

    # -- bootstrap / lookups --------------------------------------------------

    def add_bootstrap(self, host: str, udp_port: int) -> None:
        self._send(MSG_PING, self.record.encode(), (host, udp_port))

    async def _lookup_loop(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            self.lookups += 1
            for entry in list(self.table.values())[:8]:
                rec = entry.record
                self._send(MSG_FINDNODE, b"", (rec.ip, rec.udp_port))

    def find_nodes(self) -> None:
        """One immediate FINDNODE round to everyone we know."""
        self.lookups += 1
        for entry in list(self.table.values()):
            rec = entry.record
            self._send(MSG_FINDNODE, b"", (rec.ip, rec.udp_port))

    def update_subnets(self, attnets: List[bool], syncnets: List[bool]) -> None:
        """ENR attnets/syncnets refresh (attnetsService ENR updates)."""
        att = bytearray(8)
        for i, bit in enumerate(attnets[:64]):
            if bit:
                att[i // 8] |= 1 << (i % 8)
        syn = bytearray(1)
        for i, bit in enumerate(syncnets[:4]):
            if bit:
                syn[0] |= 1 << i
        self.record.attnets = bytes(att)
        self.record.syncnets = bytes(syn)
        self.record.seq += 1
        self.record.sign(self.identity)

    # -- datagram plumbing ----------------------------------------------------

    def _send(self, msg: int, payload: bytes, addr) -> None:
        if self._transport is None:
            return
        try:
            self._transport.sendto(bytes([msg]) + payload, addr)
        except Exception:  # pragma: no cover - fire and forget
            pass

    def datagram_received(self, data: bytes, addr) -> None:
        if not data:
            return
        msg, payload = data[0], data[1:]
        try:
            if msg == MSG_PING:
                self._accept_record(payload)
                self._send(MSG_PONG, self.record.encode(), addr)
            elif msg == MSG_PONG:
                self._accept_record(payload)
            elif msg == MSG_FINDNODE:
                records = [self.record.encode()]  # own record always first
                records += [e.record.encode() for e in list(self.table.values())]
                blob = b"".join(_pack_bytes(r) for r in records[:MAX_RECORDS_PER_RESPONSE])
                self._send(MSG_NODES, blob, addr)
            elif msg == MSG_NODES:
                off = 0
                while off < len(payload):
                    raw, off = _unpack_bytes(payload, off)
                    self._accept_record(raw)
        except Exception as e:  # noqa: BLE001 - hostile datagrams must not kill us
            logger.debug("bad discovery datagram from %s: %s", addr, e)

    def _accept_record(self, raw: bytes) -> None:
        rec = NodeRecord.decode(raw)
        if rec.pubkey == self.record.pubkey:
            return  # ourselves
        if not rec.verify_signature():
            logger.debug("discovery record with bad signature dropped")
            return
        existing = self.table.get(rec.node_id)
        if existing is not None and existing.record.seq >= rec.seq:
            existing.last_seen = time.monotonic()
            return
        is_new = existing is None
        if len(self.table) >= TABLE_SIZE and is_new:
            # evict the stalest entry
            oldest = min(self.table.values(), key=lambda e: e.last_seen)
            del self.table[oldest.record.node_id]
        self.table[rec.node_id] = _Entry(record=rec)
        if is_new and self.on_peer is not None:
            self.on_peer(rec)
