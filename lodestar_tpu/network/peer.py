"""Peer bookkeeping: connection state, status handshake, RPC score store.

Reference: packages/beacon-node/src/network/peers/peerManager.ts:105
(status handshake on connect, ping/metadata upkeep, goodbye on prune) and
peers/score.ts (PeerRpcScoreStore: decaying score, action weights, the
Healthy/Disconnect/Ban thresholds that let the node shed byzantine peers).
"""

from __future__ import annotations

import asyncio
import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.logger import get_logger

logger = get_logger("peers")


class PeerAction(str, enum.Enum):
    """Score penalties (peers/score.ts PeerAction weights)."""

    FATAL = "fatal"                  # instant ban
    LOW_TOLERANCE = "low"            # -10: ~5 strikes to ban
    MID_TOLERANCE = "mid"            # -5: ~10 strikes to ban
    HIGH_TOLERANCE = "high"          # -1: ~50 strikes to ban


_ACTION_WEIGHT = {
    PeerAction.FATAL: -(10**6),
    PeerAction.LOW_TOLERANCE: -10.0,
    PeerAction.MID_TOLERANCE: -5.0,
    PeerAction.HIGH_TOLERANCE: -1.0,
}

MIN_SCORE = -100.0
MIN_SCORE_BEFORE_DISCONNECT = -20.0
MIN_SCORE_BEFORE_BAN = -50.0
SCORE_HALFLIFE_S = 600.0  # ten-minute half-life (score.ts halfLifeDecay)


class ScoreState(str, enum.Enum):
    HEALTHY = "healthy"
    DISCONNECT = "disconnect"
    BANNED = "banned"


class PeerRpcScoreStore:
    """Decaying per-peer score keyed by a stable peer identity (the remote
    address here — connection-scoped ids would reset the score on
    reconnect, defeating bans).  peers/score.ts reduced to its contract:
    apply_action accumulates weighted penalties, scores decay toward zero
    with a half-life, and the state thresholds gate disconnect/ban."""

    def __init__(self):
        self._scores: Dict[str, float] = {}
        self._last_update: Dict[str, float] = {}

    def _decay(self, key: str, now: float) -> None:
        last = self._last_update.get(key, now)
        dt = max(0.0, now - last)
        if dt > 0 and key in self._scores:
            self._scores[key] *= 0.5 ** (dt / SCORE_HALFLIFE_S)
        self._last_update[key] = now

    def apply_action(self, key: str, action: PeerAction, reason: str = "") -> None:
        now = time.monotonic()
        self._decay(key, now)
        score = self._scores.get(key, 0.0) + _ACTION_WEIGHT[action]
        self._scores[key] = max(MIN_SCORE, score)
        if action != PeerAction.HIGH_TOLERANCE:
            logger.debug("peer %s penalized (%s): %s -> %.1f", key, action.value, reason, self._scores[key])

    def score(self, key: str) -> float:
        self._decay(key, time.monotonic())
        return self._scores.get(key, 0.0)

    def state(self, key: str) -> ScoreState:
        s = self.score(key)
        if s <= MIN_SCORE_BEFORE_BAN:
            return ScoreState.BANNED
        if s <= MIN_SCORE_BEFORE_DISCONNECT:
            return ScoreState.DISCONNECT
        return ScoreState.HEALTHY


@dataclass
class Peer:
    peer_id: str
    reqresp: object  # ReqRespNode
    wire: object  # Wire
    status: Optional[object] = None  # last Status from the peer
    metadata: Optional[object] = None
    score: int = 0
    remote_key: str = ""  # stable identity for the score store (host:port)
    tasks: List[asyncio.Task] = field(default_factory=list)

    def penalize(self, points: int = 1) -> None:
        self.score -= points


class PeerManager:
    def __init__(self, max_peers: int = 55):
        self.max_peers = max_peers
        self.peers: Dict[str, Peer] = {}

    def add(self, peer: Peer) -> None:
        self.peers[peer.peer_id] = peer

    def remove(self, peer_id: str) -> Optional[Peer]:
        return self.peers.pop(peer_id, None)

    def get(self, peer_id: str) -> Optional[Peer]:
        return self.peers.get(peer_id)

    def connected(self) -> List[Peer]:
        return list(self.peers.values())

    def best_peer_for_sync(self) -> Optional[Peer]:
        """Peer with the highest advertised head slot (rangeSync picks its
        target chain from peer statuses — range.ts:76)."""
        best = None
        for p in self.peers.values():
            if p.status is None:
                continue
            if best is None or p.status.head_slot > best.status.head_slot:
                best = p
        return best

    async def handshake(self, peer: Peer, local_status) -> object:
        """Exchange Status on connect (peerManager onConnect flow); stores
        and returns the peer's status."""
        status = await peer.reqresp.status(local_status)
        peer.status = status
        try:
            peer.metadata = await peer.reqresp.metadata()
        except Exception:
            peer.metadata = None
        return status
