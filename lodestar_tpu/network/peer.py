"""Peer bookkeeping: connection state, status handshake, scoring stub.

Reference: packages/beacon-node/src/network/peers/peerManager.ts:105
(status handshake on connect, ping/metadata upkeep, goodbye on prune) and
peers/score.ts (kept minimal: a misbehavior counter that gates pruning).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.logger import get_logger

logger = get_logger("peers")


@dataclass
class Peer:
    peer_id: str
    reqresp: object  # ReqRespNode
    wire: object  # Wire
    status: Optional[object] = None  # last Status from the peer
    metadata: Optional[object] = None
    score: int = 0
    tasks: List[asyncio.Task] = field(default_factory=list)

    def penalize(self, points: int = 1) -> None:
        self.score -= points


class PeerManager:
    def __init__(self, max_peers: int = 55):
        self.max_peers = max_peers
        self.peers: Dict[str, Peer] = {}

    def add(self, peer: Peer) -> None:
        self.peers[peer.peer_id] = peer

    def remove(self, peer_id: str) -> Optional[Peer]:
        return self.peers.pop(peer_id, None)

    def get(self, peer_id: str) -> Optional[Peer]:
        return self.peers.get(peer_id)

    def connected(self) -> List[Peer]:
        return list(self.peers.values())

    def best_peer_for_sync(self) -> Optional[Peer]:
        """Peer with the highest advertised head slot (rangeSync picks its
        target chain from peer statuses — range.ts:76)."""
        best = None
        for p in self.peers.values():
            if p.status is None:
                continue
            if best is None or p.status.head_slot > best.status.head_slot:
                best = p
        return best

    async def handshake(self, peer: Peer, local_status) -> object:
        """Exchange Status on connect (peerManager onConnect flow); stores
        and returns the peer's status."""
        status = await peer.reqresp.status(local_status)
        peer.status = status
        try:
            peer.metadata = await peer.reqresp.metadata()
        except Exception:
            peer.metadata = None
        return status
