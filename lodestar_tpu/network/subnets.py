"""Attestation/sync-committee subnet services + metadata controller.

Reference: packages/beacon-node/src/network/subnets/attnetsService.ts:31
(long-lived random subnets with epoch-based rotation + short-lived
committee subscriptions for aggregation duties, ENR/metadata updates,
shouldProcess gate), subnets/syncnetsService.ts:18, network/metadata.ts
(seq-numbered metadata served over reqresp).

This stack floods gossip to all peers, so "subscription" here governs
what the node ADVERTISES (metadata/ENR bitfields) and which subnets'
messages it validates eagerly (should_process) — the same observable
surface the reference's mesh joins produce.
"""

from __future__ import annotations

import hashlib
import random
from typing import Dict, List, Optional, Set

from ..params import Preset
from ..params.presets import (
    ATTESTATION_SUBNET_COUNT,
    SYNC_COMMITTEE_SUBNET_COUNT,
)
from ..utils.logger import get_logger

logger = get_logger("subnets")

RANDOM_SUBNETS_PER_VALIDATOR = 1
EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION = 256


class MetadataController:
    """seq-numbered metadata (network/metadata.ts): every attnets/syncnets
    change bumps seq_number so peers know to re-fetch."""

    def __init__(self):
        self.seq_number = 0
        self.attnets = [False] * ATTESTATION_SUBNET_COUNT
        self.syncnets = [False] * SYNC_COMMITTEE_SUBNET_COUNT

    def update_attnets(self, bits: List[bool]) -> None:
        if bits != self.attnets:
            self.attnets = list(bits)
            self.seq_number += 1

    def update_syncnets(self, bits: List[bool]) -> None:
        if bits != self.syncnets:
            self.syncnets = list(bits)
            self.seq_number += 1


class AttnetsService:
    """Long-lived random subnets (one per tracked validator, rotated every
    ~256 epochs at a per-validator offset) + short-lived committee
    subscriptions from aggregation duties (attnetsService.ts:31,100-130)."""

    def __init__(self, preset: Preset, metadata: MetadataController, node_seed: bytes = b""):
        self.p = preset
        self.metadata = metadata
        self.node_seed = node_seed or bytes(8)
        self.tracked_validators: Set[int] = set()
        # subnet -> expiry slot for short-lived committee subscriptions
        self._committee_subs: Dict[int, int] = {}
        self._current_epoch = 0

    # -- inputs ---------------------------------------------------------------

    def add_validator(self, validator_index: int) -> None:
        self.tracked_validators.add(int(validator_index))
        self._refresh_metadata()

    def add_committee_subscription(self, subnet: int, until_slot: int) -> None:
        """Short-lived duty subscription (beacon_committee_subscriptions
        API route -> prepareBeaconCommitteeSubnet)."""
        cur = self._committee_subs.get(subnet, 0)
        self._committee_subs[subnet] = max(cur, until_slot)
        self._refresh_metadata()

    def on_slot(self, slot: int) -> None:
        epoch = slot // self.p.SLOTS_PER_EPOCH
        changed = epoch != self._current_epoch
        self._current_epoch = epoch
        expired = [s for s, until in self._committee_subs.items() if until < slot]
        for s in expired:
            del self._committee_subs[s]
        if changed or expired:
            self._refresh_metadata()

    # -- subnet math ----------------------------------------------------------

    def _random_subnet_for(self, validator_index: int, epoch: int) -> int:
        """Deterministic rotation: stable for EPOCHS_PER_RANDOM_SUBNET_
        SUBSCRIPTION epochs, phase-shifted per validator so the fleet's
        rotations spread out (the reference randomizes lifetimes; a seeded
        hash gives the same distribution reproducibly)."""
        period = EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION
        offset = validator_index % period
        window = (epoch + offset) // period
        digest = hashlib.sha256(
            self.node_seed + validator_index.to_bytes(8, "little") + window.to_bytes(8, "little")
        ).digest()
        return int.from_bytes(digest[:8], "little") % ATTESTATION_SUBNET_COUNT

    def active_subnets(self) -> Set[int]:
        out = {
            self._random_subnet_for(vi, self._current_epoch)
            for vi in self.tracked_validators
        }
        out.update(self._committee_subs.keys())
        return out

    def should_process(self, subnet: int) -> bool:
        """attnetsService.ts shouldProcess: eagerly validate only the
        subnets we serve (others still forward via the router dedup)."""
        return subnet in self.active_subnets()

    def _refresh_metadata(self) -> None:
        bits = [False] * ATTESTATION_SUBNET_COUNT
        for s in self.active_subnets():
            bits[s] = True
        self.metadata.update_attnets(bits)


class SyncnetsService:
    """Sync-committee subnets from duties (syncnetsService.ts:18)."""

    def __init__(self, preset: Preset, metadata: MetadataController):
        self.p = preset
        self.metadata = metadata
        self._subs: Dict[int, int] = {}  # subnet -> expiry slot

    def add_subscription(self, subnet: int, until_slot: int) -> None:
        cur = self._subs.get(subnet, 0)
        self._subs[subnet] = max(cur, until_slot)
        self._refresh()

    def on_slot(self, slot: int) -> None:
        expired = [s for s, until in self._subs.items() if until < slot]
        for s in expired:
            del self._subs[s]
        if expired:
            self._refresh()

    def active_subnets(self) -> Set[int]:
        return set(self._subs.keys())

    def _refresh(self) -> None:
        bits = [False] * SYNC_COMMITTEE_SUBNET_COUNT
        for s in self._subs:
            bits[s] = True
        self.metadata.update_syncnets(bits)
