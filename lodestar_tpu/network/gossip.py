"""Gossipsub-semantics router: scored mesh overlay with lazy IHAVE/IWANT.

Reference: packages/beacon-node/src/network/gossip/gossipsub.ts:84 (the
scored mesh), scoringParameters.ts:18-120 (D parameters, topic weights,
thresholds, behaviour penalty), and the gossipsub v1.1 spec semantics —
re-expressed on this stack's custom wire (network/wire.py KIND_GOSSIP /
KIND_GOSSIP_CTRL frames) rather than libp2p:

- MESH: per-topic overlay of degree D (D_LO..D_HI), maintained by a
  heartbeat: GRAFT under-filled meshes from known subscribers with
  non-negative score, PRUNE over-filled ones keeping the highest-scored.
  Publishes and forwards go to mesh members only — O(D) fanout per
  message instead of O(peers).
- LAZY GOSSIP: each heartbeat advertises the last few windows of message
  ids (IHAVE) to D_LAZY random non-mesh subscribers; peers request what
  they miss (IWANT) from the message cache.
- SCORING: per-peer, per-topic counters (time in mesh, first deliveries,
  invalid deliveries) with the reference's topic weights, plus a global
  behaviour penalty; decayed every heartbeat.  Scores gate GRAFT
  acceptance, order PRUNE victims, and drive eviction below the graylist
  threshold.

Subscriptions are exchanged on connect and on change (SUB/UNSUB control
entries), so meshes only ever contain peers that declared the topic.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple

from ..utils.logger import get_logger

logger = get_logger("gossip")

TOPIC_BLOCK = "beacon_block"
TOPIC_AGGREGATE = "beacon_aggregate_and_proof"
TOPIC_ATTESTATION = "beacon_attestation_{subnet}"
TOPIC_EXIT = "voluntary_exit"
TOPIC_PROPOSER_SLASHING = "proposer_slashing"
TOPIC_ATTESTER_SLASHING = "attester_slashing"
TOPIC_SYNC_CONTRIBUTION = "sync_committee_contribution_and_proof"
TOPIC_SYNC_COMMITTEE = "sync_committee_{subnet}"

from ..params.presets import (  # noqa: E402 - single source of truth
    ATTESTATION_SUBNET_COUNT,
    SYNC_COMMITTEE_SUBNET_COUNT,
)

# mesh degree parameters (scoringParameters.ts:18-20)
GOSSIP_D = 8
GOSSIP_D_LOW = 6
GOSSIP_D_HIGH = 12
GOSSIP_D_LAZY = 6
GOSSIP_FACTOR = 0.25
MCACHE_LEN = 5        # heartbeats of full messages kept
MCACHE_GOSSIP = 3     # windows advertised in IHAVE
HEARTBEAT_INTERVAL = 0.7
MAX_IHAVE_LEN = 5000

# peer score thresholds (scoringParameters.ts gossipScoreThresholds)
GOSSIP_THRESHOLD = -4000.0      # below: no gossip exchange (IHAVE/IWANT)
PUBLISH_THRESHOLD = -8000.0     # below: not eligible for publish fanout
GRAYLIST_THRESHOLD = -16000.0   # below: evict

MAX_IN_MESH_SCORE = 10.0
MAX_FIRST_MESSAGE_DELIVERIES_SCORE = 40.0


@dataclass
class TopicScoreParams:
    """Per-topic score weights (scoringParameters.ts TopicScoreParams,
    reduced to the counters this router tracks)."""

    topic_weight: float
    time_in_mesh_weight: float = 0.033
    time_in_mesh_cap: float = 300.0
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_cap: float = 40.0
    first_message_deliveries_decay: float = 0.95
    invalid_message_deliveries_weight: float = -140.0
    invalid_message_deliveries_decay: float = 0.99


# topic weights (scoringParameters.ts:24-31)
_TOPIC_WEIGHTS = {
    TOPIC_BLOCK: 0.5,
    TOPIC_AGGREGATE: 0.5,
    TOPIC_EXIT: 0.05,
    TOPIC_PROPOSER_SLASHING: 0.05,
    TOPIC_ATTESTER_SLASHING: 0.05,
    TOPIC_SYNC_CONTRIBUTION: 0.2,
}


def topic_score_params(topic: str) -> TopicScoreParams:
    name = parse_topic(topic) or topic
    if name.startswith("beacon_attestation"):
        return TopicScoreParams(topic_weight=1.0 / ATTESTATION_SUBNET_COUNT)
    if name.startswith("sync_committee_") and name != TOPIC_SYNC_CONTRIBUTION:
        return TopicScoreParams(topic_weight=1.0 / SYNC_COMMITTEE_SUBNET_COUNT)
    return TopicScoreParams(topic_weight=_TOPIC_WEIGHTS.get(name, 0.05))


def topic_string(fork_digest: bytes, name: str) -> str:
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


def parse_topic(topic: str) -> Optional[str]:
    parts = topic.split("/")
    if len(parts) == 5 and parts[1] == "eth2" and parts[4] == "ssz_snappy":
        return parts[3]
    return None


class SeenMessages:
    """Message-id LRU (gossipsub seenCache)."""

    def __init__(self, max_size: int = 8192):
        self.max_size = max_size
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()

    def check_and_add(self, msg_id: bytes) -> bool:
        """True if newly seen."""
        if msg_id in self._seen:
            return False
        self._seen[msg_id] = None
        while len(self._seen) > self.max_size:
            self._seen.popitem(last=False)
        return True

    def __contains__(self, msg_id: bytes) -> bool:
        return msg_id in self._seen


def message_id(topic: str, data: bytes) -> bytes:
    return hashlib.sha256(topic.encode() + b"\x00" + data).digest()[:20]


@dataclass
class _TopicCounters:
    time_in_mesh: float = 0.0            # heartbeats while in our mesh
    first_message_deliveries: float = 0.0
    invalid_message_deliveries: float = 0.0


@dataclass
class _PeerState:
    send_msg: Callable[[str, bytes], Awaitable[None]]
    send_ctrl: Callable[[dict], Awaitable[None]]
    topics: Set[str] = field(default_factory=set)
    counters: Dict[str, _TopicCounters] = field(default_factory=dict)
    behaviour_penalty: float = 0.0
    explicit_subs: bool = False  # has the peer sent any subscription info?

    def topic_counters(self, topic: str) -> _TopicCounters:
        if topic not in self.counters:
            self.counters[topic] = _TopicCounters()
        return self.counters[topic]

    def score(self) -> float:
        s = 0.0
        for topic, c in self.counters.items():
            p = topic_score_params(topic)
            s += p.topic_weight * (
                min(c.time_in_mesh * p.time_in_mesh_weight, MAX_IN_MESH_SCORE)
                + min(c.first_message_deliveries, p.first_message_deliveries_cap)
                * p.first_message_deliveries_weight
                + c.invalid_message_deliveries**2 * p.invalid_message_deliveries_weight
            )
        # behaviour penalty (P7): quadratic above the threshold
        excess = self.behaviour_penalty - 6.0
        if excess > 0:
            s -= excess * excess * 10.0
        return s


def sheddable_topic(name: str) -> bool:
    """Topics whose intake may slow under BLS-pool backpressure: the
    per-subnet storm traffic (unaggregated attestations, sync-committee
    messages).  Blocks, aggregates, contributions, and the rare op-pool
    topics always flow — under overload they are exactly what the node
    must keep validating."""
    return name.startswith("beacon_attestation_") or (
        name.startswith("sync_committee_") and name != TOPIC_SYNC_CONTRIBUTION
    )


class GossipRouter:
    """Scored-mesh pubsub over per-peer send callables.

    ``on_reject``: (peer_key, code) when a peer relays a REJECTed message
    (feeds the RPC score store).  ``on_evict``: (peer_key, score) when a
    peer's gossip score crosses the graylist threshold.

    ``backpressure``: zero-arg callable read per inbound message; while it
    returns True (the BLS pool is above its high-water mark) sheddable
    topics are dropped AT INTAKE — before validation, before the pool —
    so the verification queue stops growing instead of OOMing
    (docs/overload.md §Backpressure).  Dropped intake is counted in
    ``gossip_queue_dropped_total{topic}``; the message is not forwarded
    (it was never validated) and the sender is not penalized."""

    def __init__(
        self,
        on_reject: Optional[Callable[[str, str], None]] = None,
        on_evict: Optional[Callable[[str, float], None]] = None,
        heartbeat_interval: float = HEARTBEAT_INTERVAL,
        metrics=None,
        backpressure: Optional[Callable[[], bool]] = None,
    ):
        self.metrics = metrics
        self.subscriptions: Dict[str, Callable[[bytes], Awaitable[None]]] = {}
        self.seen = SeenMessages()
        self.peers: Dict[str, _PeerState] = {}
        self.mesh: Dict[str, Set[str]] = {}
        self.on_reject = on_reject
        self.on_evict = on_evict
        self.backpressure = backpressure
        self.backpressure_dropped = 0
        self.heartbeat_interval = heartbeat_interval
        self._mcache: Dict[bytes, Tuple[str, bytes]] = {}
        self._mcache_windows: deque = deque(maxlen=MCACHE_LEN)
        self._mcache_windows.append([])
        self._iwant_budget: Dict[str, int] = {}
        self._hb_task: Optional[asyncio.Task] = None
        self._rng = random.Random()
        # observability
        self.messages_sent = 0
        self.messages_received = 0
        self.ihave_sent = 0
        self.iwant_received = 0

    # -- peer lifecycle -----------------------------------------------------

    def add_peer(self, key: str, send_msg, send_ctrl) -> None:
        self.peers[key] = _PeerState(send_msg=send_msg, send_ctrl=send_ctrl)

    def remove_peer(self, key: str) -> None:
        self.peers.pop(key, None)
        for members in self.mesh.values():
            members.discard(key)

    async def announce_subscriptions(self, key: str) -> None:
        """Send our full subscription list to a (new) peer."""
        st = self.peers.get(key)
        if st is None:
            return
        try:
            await st.send_ctrl({"sub": sorted(self.subscriptions)})
        except Exception as e:  # noqa: BLE001
            logger.debug("subscription announce to %s failed: %s", key, e)

    # -- pubsub API ----------------------------------------------------------

    def subscribe(self, topic: str, handler: Callable[[bytes], Awaitable[None]]) -> None:
        self.subscriptions[topic] = handler
        self.mesh.setdefault(topic, set())

    def score(self, key: str) -> float:
        st = self.peers.get(key)
        return st.score() if st else 0.0

    def _eligible(self, topic: str, key: str, floor: float) -> bool:
        st = self.peers.get(key)
        if st is None:
            return False
        if st.explicit_subs and topic not in st.topics:
            return False
        return st.score() >= floor

    def _publish_targets(self, topic: str) -> List[str]:
        members = [
            k for k in self.mesh.get(topic, ()) if self._eligible(topic, k, PUBLISH_THRESHOLD)
        ]
        if members:
            return members
        # mesh not yet built (before the first heartbeat): fan out to up to
        # D subscribed-or-unknown peers so young networks still propagate
        cands = [
            k for k in self.peers if self._eligible(topic, k, PUBLISH_THRESHOLD)
        ]
        self._rng.shuffle(cands)
        return cands[:GOSSIP_D]

    async def publish(self, topic: str, ssz_bytes: bytes) -> int:
        mid = message_id(topic, ssz_bytes)
        self.seen.check_and_add(mid)
        self._mcache_put(mid, topic, ssz_bytes)
        n = 0
        for key in self._publish_targets(topic):
            try:
                await self.peers[key].send_msg(topic, ssz_bytes)
                n += 1
            except Exception as e:  # noqa: BLE001
                logger.warning("gossip publish to %s failed: %s", key, e)
        self.messages_sent += n
        return n

    async def on_message(
        self, topic: str, ssz_bytes: bytes, *, forward: bool = True,
        from_peer: Optional[str] = None,
    ) -> None:
        """Inbound message: dedup -> local handler -> forward to mesh.
        IGNORE drops silently; REJECT drops, counts an invalid delivery
        against the sender's topic score AND reports to the RPC store."""
        mid = message_id(topic, ssz_bytes)
        if not self.seen.check_and_add(mid):
            return
        self.messages_received += 1
        self._mcache_put(mid, topic, ssz_bytes)
        if from_peer is not None and from_peer in self.peers:
            self.peers[from_peer].topic_counters(topic).first_message_deliveries += 1
        handler = self.subscriptions.get(topic)
        if handler is None:
            return
        name = parse_topic(topic) or topic
        if (
            self.backpressure is not None
            and sheddable_topic(name)
            and self.backpressure()
        ):
            # overload: shed storm-lane intake before it reaches the
            # validation queue (the pool's high-water mark is the signal)
            self.backpressure_dropped += 1
            if self.metrics:
                self.metrics.gossip_queue_dropped_total.labels(topic=name).inc()
            return
        from ..chain.validation import GossipAction, GossipValidationError

        try:
            await handler(ssz_bytes)
        except GossipValidationError as e:
            logger.debug("gossip %s: %s", topic, e)
            if self.metrics:
                verdict = "reject" if e.action == GossipAction.REJECT else "ignore"
                self.metrics.gossip_validation_total.labels(
                    topic=parse_topic(topic) or topic, verdict=verdict
                ).inc()
            if e.action == GossipAction.REJECT and from_peer:
                if from_peer in self.peers:
                    self.peers[from_peer].topic_counters(topic).invalid_message_deliveries += 1
                    self._maybe_evict(from_peer)
                if self.on_reject:
                    self.on_reject(from_peer, e.code)
            return
        except Exception as e:  # noqa: BLE001
            # a local handler bug or transient state miss is OUR problem —
            # penalizing the relaying peer would let a local fault ban the
            # whole peer set; only REJECT downscores
            logger.warning("gossip handler error on %s: %s", topic, e)
            return
        if self.metrics:
            self.metrics.gossip_validation_total.labels(
                topic=parse_topic(topic) or topic, verdict="accept"
            ).inc()
        if forward:
            for key in self._publish_targets(topic):
                if key == from_peer:
                    continue
                try:
                    await self.peers[key].send_msg(topic, ssz_bytes)
                    self.messages_sent += 1
                except Exception:
                    pass

    # -- control plane -------------------------------------------------------

    async def on_control(self, from_peer: str, ctrl: dict) -> None:
        st = self.peers.get(from_peer)
        if st is None:
            return
        for topic in ctrl.get("sub", []):
            st.topics.add(topic)
            st.explicit_subs = True
        for topic in ctrl.get("unsub", []):
            st.topics.discard(topic)
            st.explicit_subs = True
            self.mesh.get(topic, set()).discard(from_peer)
        prunes = []
        for topic in ctrl.get("graft", []):
            if topic not in self.subscriptions or st.score() < 0:
                prunes.append(topic)
                # grafting while unsubscribed/negative is misbehavior
                st.behaviour_penalty += 0.1
                continue
            self.mesh.setdefault(topic, set()).add(from_peer)
        for topic in ctrl.get("prune", []):
            self.mesh.get(topic, set()).discard(from_peer)
        # IHAVE: ask for unseen ids (bounded per heartbeat), unless the
        # peer is below the gossip threshold
        if st.score() >= GOSSIP_THRESHOLD:
            want = []
            budget = self._iwant_budget.get(from_peer, MAX_IHAVE_LEN)
            for topic, ids in ctrl.get("ihave", []):
                if topic not in self.subscriptions:
                    continue
                for mid in ids:
                    if budget <= 0:
                        break
                    if mid not in self.seen:
                        want.append(mid)
                        budget -= 1
            self._iwant_budget[from_peer] = budget
            if want:
                try:
                    await st.send_ctrl({"iwant": want})
                except Exception:
                    pass
        # IWANT: serve from the message cache
        iwant = ctrl.get("iwant", [])
        if iwant:
            self.iwant_received += len(iwant)
            for mid in iwant[:MAX_IHAVE_LEN]:
                entry = self._mcache.get(bytes(mid))
                if entry is not None:
                    try:
                        await st.send_msg(entry[0], entry[1])
                    except Exception:
                        break
        if prunes:
            try:
                await st.send_ctrl({"prune": prunes})
            except Exception:
                pass

    # -- heartbeat -----------------------------------------------------------

    def start(self) -> None:
        if self._hb_task is None:
            self._hb_task = asyncio.create_task(self._hb_loop())

    def stop(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None

    async def _hb_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.heartbeat_interval)
                await self.heartbeat()
        except asyncio.CancelledError:
            pass

    async def heartbeat(self) -> None:
        """Mesh maintenance + lazy gossip + score decay (gossipsub v1.1
        heartbeat, gossipsub.ts mesh maintenance)."""
        grafts: Dict[str, List[str]] = {}
        prunes: Dict[str, List[str]] = {}
        for topic in self.subscriptions:
            members = self.mesh.setdefault(topic, set())
            # drop members that went away or turned negative
            for key in list(members):
                if key not in self.peers or self.peers[key].score() < 0:
                    members.discard(key)
                    prunes.setdefault(key, []).append(topic)
            if len(members) < GOSSIP_D_LOW:
                cands = [
                    k
                    for k, st in self.peers.items()
                    if k not in members
                    and topic in st.topics
                    and st.score() >= 0
                ]
                self._rng.shuffle(cands)
                for k in cands[: GOSSIP_D - len(members)]:
                    members.add(k)
                    grafts.setdefault(k, []).append(topic)
            elif len(members) > GOSSIP_D_HIGH:
                ranked = sorted(members, key=lambda k: self.peers[k].score(), reverse=True)
                for k in ranked[GOSSIP_D:]:
                    members.discard(k)
                    prunes.setdefault(k, []).append(topic)
            # time-in-mesh accrual
            for k in members:
                if k in self.peers:
                    self.peers[k].topic_counters(topic).time_in_mesh += 1
        for key, topics in grafts.items():
            try:
                await self.peers[key].send_ctrl({"graft": topics})
            except Exception:
                pass
        for key, topics in prunes.items():
            if key in self.peers:
                try:
                    await self.peers[key].send_ctrl({"prune": topics})
                except Exception:
                    pass
        if self.metrics:
            for topic, members in self.mesh.items():
                self.metrics.gossip_mesh_peers.labels(
                    topic=parse_topic(topic) or topic
                ).set(len(members))
            for st in self.peers.values():
                self.metrics.gossip_peer_score.observe(st.score())
            for key, topics in grafts.items():
                self.metrics.gossip_control_total.labels(kind="graft", dir="out").inc(
                    len(topics)
                )
            for key, topics in prunes.items():
                self.metrics.gossip_control_total.labels(kind="prune", dir="out").inc(
                    len(topics)
                )
        await self._emit_gossip()
        self._decay_scores()
        self._iwant_budget.clear()
        self._mcache_shift()

    async def _emit_gossip(self) -> None:
        """IHAVE advertisements to D_LAZY random non-mesh subscribers."""
        window_ids: Dict[str, List[bytes]] = {}
        for window in list(self._mcache_windows)[-MCACHE_GOSSIP:]:
            for mid in window:
                entry = self._mcache.get(mid)
                if entry is not None:
                    window_ids.setdefault(entry[0], []).append(mid)
        for topic, ids in window_ids.items():
            cands = [
                k
                for k, st in self.peers.items()
                if k not in self.mesh.get(topic, set())
                and topic in st.topics
                and st.score() >= GOSSIP_THRESHOLD
            ]
            self._rng.shuffle(cands)
            n = max(GOSSIP_D_LAZY, int(len(cands) * GOSSIP_FACTOR))
            for k in cands[:n]:
                try:
                    await self.peers[k].send_ctrl({"ihave": [(topic, ids)]})
                    self.ihave_sent += 1
                except Exception:
                    pass

    def _decay_scores(self) -> None:
        for key, st in list(self.peers.items()):
            for topic, c in st.counters.items():
                p = topic_score_params(topic)
                c.first_message_deliveries *= p.first_message_deliveries_decay
                c.invalid_message_deliveries *= p.invalid_message_deliveries_decay
            st.behaviour_penalty *= 0.99
            self._maybe_evict(key)

    def _maybe_evict(self, key: str) -> None:
        st = self.peers.get(key)
        if st is not None and self.on_evict is not None:
            s = st.score()
            if s < GRAYLIST_THRESHOLD:
                self.on_evict(key, s)

    # -- message cache ---------------------------------------------------------

    def _mcache_put(self, mid: bytes, topic: str, data: bytes) -> None:
        if mid not in self._mcache:
            self._mcache[mid] = (topic, data)
            self._mcache_windows[-1].append(mid)

    def _mcache_shift(self) -> None:
        if len(self._mcache_windows) == self._mcache_windows.maxlen:
            for mid in self._mcache_windows[0]:
                self._mcache.pop(mid, None)
        self._mcache_windows.append([])
