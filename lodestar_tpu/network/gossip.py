"""Gossip router: topic pub/sub with first-seen dedup and flood publish.

Reference: packages/beacon-node/src/network/gossip/ (gossipsub.ts:84 topic
handling, topic.ts encoding).  Topic strings follow the spec shape
``/eth2/<fork_digest_hex>/<name>/ssz_snappy``; message ids are
sha256(topic | data) — the gossipsub v1.1 message-id function reduced to
its dedup role.  Mesh management/scoring is not modeled; publish floods to
all connected peers, which is exact for the node counts the in-process
tests and LAN deployments here target.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Awaitable, Callable, Dict, List, Optional

from ..utils.logger import get_logger

logger = get_logger("gossip")

TOPIC_BLOCK = "beacon_block"
TOPIC_AGGREGATE = "beacon_aggregate_and_proof"
TOPIC_ATTESTATION = "beacon_attestation_{subnet}"
TOPIC_EXIT = "voluntary_exit"
TOPIC_PROPOSER_SLASHING = "proposer_slashing"
TOPIC_ATTESTER_SLASHING = "attester_slashing"
# altair sync-committee traffic (gossip/interface.ts, topic.ts)
TOPIC_SYNC_CONTRIBUTION = "sync_committee_contribution_and_proof"
TOPIC_SYNC_COMMITTEE = "sync_committee_{subnet}"

from ..params.presets import (  # noqa: E402 - single source of truth
    ATTESTATION_SUBNET_COUNT,
    SYNC_COMMITTEE_SUBNET_COUNT,
)


def topic_string(fork_digest: bytes, name: str) -> str:
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


def parse_topic(topic: str) -> Optional[str]:
    parts = topic.split("/")
    if len(parts) == 5 and parts[1] == "eth2" and parts[4] == "ssz_snappy":
        return parts[3]
    return None


class SeenMessages:
    """Message-id LRU (gossipsub seenCache)."""

    def __init__(self, max_size: int = 8192):
        self.max_size = max_size
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()

    def check_and_add(self, msg_id: bytes) -> bool:
        """True if newly seen."""
        if msg_id in self._seen:
            return False
        self._seen[msg_id] = None
        while len(self._seen) > self.max_size:
            self._seen.popitem(last=False)
        return True


def message_id(topic: str, data: bytes) -> bytes:
    return hashlib.sha256(topic.encode() + b"\x00" + data).digest()[:20]


class GossipRouter:
    """Binds topic subscriptions to handler coroutines and floods publishes
    to peers.  Transport-agnostic: `send_fns` are per-peer async callables
    (topic, ssz_bytes) -> None registered by the Network."""

    def __init__(self, on_reject: Optional[Callable[[str, str], None]] = None):
        self.subscriptions: Dict[str, Callable[[bytes], Awaitable[None]]] = {}
        self.seen = SeenMessages()
        self.send_fns: List[Callable[[str, bytes], Awaitable[None]]] = []
        # called as (peer_key, code) when a peer's message is REJECTed —
        # the hook the PeerRpcScoreStore hangs off (scoringParameters.ts
        # invalid-message penalties reduced to their effect)
        self.on_reject = on_reject

    def subscribe(self, topic: str, handler: Callable[[bytes], Awaitable[None]]) -> None:
        self.subscriptions[topic] = handler

    def add_peer_sender(self, fn: Callable[[str, bytes], Awaitable[None]]) -> None:
        self.send_fns.append(fn)

    def remove_peer_sender(self, fn) -> None:
        if fn in self.send_fns:
            self.send_fns.remove(fn)

    async def publish(self, topic: str, ssz_bytes: bytes) -> int:
        """Flood to peers (marks the message seen so the echo is dropped).
        Returns the number of peers sent to."""
        self.seen.check_and_add(message_id(topic, ssz_bytes))
        n = 0
        for fn in list(self.send_fns):
            try:
                await fn(topic, ssz_bytes)
                n += 1
            except Exception as e:  # noqa: BLE001
                logger.warning("gossip publish to peer failed: %s", e)
        return n

    async def on_message(
        self, topic: str, ssz_bytes: bytes, *, forward: bool = True,
        from_peer: Optional[str] = None,
    ) -> None:
        """Inbound message: dedup -> local handler -> re-flood.  IGNORE
        drops silently; REJECT drops AND reports the sending peer to the
        score store via on_reject (an invalid message is provable
        misbehavior; a merely-late one is not)."""
        if not self.seen.check_and_add(message_id(topic, ssz_bytes)):
            return
        handler = self.subscriptions.get(topic)
        if handler is None:
            return
        from ..chain.validation import GossipAction, GossipValidationError

        try:
            await handler(ssz_bytes)
        except GossipValidationError as e:
            logger.debug("gossip %s: %s", topic, e)
            if e.action == GossipAction.REJECT and from_peer and self.on_reject:
                self.on_reject(from_peer, e.code)
            return  # IGNORE and REJECT both stop propagation here
        except Exception as e:  # noqa: BLE001
            # a local handler bug or transient state miss is OUR problem —
            # penalizing the relaying peer for it would let a local fault
            # ban the entire peer set (review r4); only REJECT downscores
            logger.warning("gossip handler error on %s: %s", topic, e)
            return
        if forward:
            for fn in list(self.send_fns):
                try:
                    await fn(topic, ssz_bytes)
                except Exception:
                    pass
