"""Networking: req/resp + gossip over a TCP wire, peer management.

Reference surface: packages/beacon-node/src/network/ (network.ts:41,
reqresp/reqResp.ts:45, gossip/gossipsub.ts:84, peers/peerManager.ts:105).
The v1 transport is TCP loopback/LAN with ssz_snappy payload framing —
the protocol semantics (method set, status handshake, IGNORE/REJECT
gossip flow, range sync batching) match the reference; the libp2p
multistream/noise layers are out of scope for this milestone and isolated
behind the Wire class so a discv5/libp2p transport can slot in.
"""

from .network import Network  # noqa: F401
from .peer import PeerManager  # noqa: F401
