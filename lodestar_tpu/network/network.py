"""Network: TCP listener/dialer tying wire frames to req/resp + gossip.

Reference: packages/beacon-node/src/network/network.ts:41 — the object a
node owns: transport lifecycle, peer manager, req/resp endpoint per peer,
gossip router bound to the chain's gossip handlers.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..params import Preset
from ..types import get_types
from ..utils.logger import get_logger
from .gossip import (
    ATTESTATION_SUBNET_COUNT,
    SYNC_COMMITTEE_SUBNET_COUNT,
    TOPIC_AGGREGATE,
    TOPIC_ATTESTATION,
    TOPIC_ATTESTER_SLASHING,
    TOPIC_BLOCK,
    TOPIC_EXIT,
    TOPIC_PROPOSER_SLASHING,
    TOPIC_SYNC_COMMITTEE,
    TOPIC_SYNC_CONTRIBUTION,
    GossipRouter,
    parse_topic,
    topic_string,
)
from .peer import (
    Peer,
    PeerAction,
    PeerManager,
    PeerRpcScoreStore,
    ScoreState,
)
from .reqresp import ReqRespNode
from .wire import (
    KIND_GOSSIP,
    KIND_GOSSIP_CTRL,
    KIND_REQUEST,
    KIND_RESPONSE_CHUNK,
    KIND_RESPONSE_END,
    Wire,
)

logger = get_logger("network")


class Network:
    def __init__(self, preset: Preset, chain, gossip_handlers=None, host: str = "127.0.0.1", metrics=None):
        self.p = preset
        self.chain = chain
        self.handlers = gossip_handlers
        self.metrics = metrics
        self.host = host
        self.port: Optional[int] = None
        self.peer_manager = PeerManager()
        self.score_store = PeerRpcScoreStore()
        self.router = GossipRouter(
            on_reject=self._on_gossip_reject, on_evict=self._on_gossip_evict,
            metrics=metrics,
            # storm-topic intake slows while the BLS pool sits above its
            # high-water mark (docs/overload.md §Backpressure)
            backpressure=lambda: getattr(
                getattr(chain, "bls", None), "overloaded", False
            ),
        )
        # subnet services + seq-numbered metadata (SURVEY §2.5 attnets/
        # syncnets; served to peers over reqresp METADATA)
        from .subnets import AttnetsService, MetadataController, SyncnetsService

        self.metadata = MetadataController()
        self.attnets = AttnetsService(preset, self.metadata)
        self.syncnets = SyncnetsService(preset, self.metadata)
        # chain progress ticks the subnet services (rotation + expiry);
        # committee/sync subscriptions arrive via the REST routes
        from ..chain.emitter import ChainEvent

        chain.emitter.on(
            ChainEvent.BLOCK,
            lambda sb, _root: (
                self.attnets.on_slot(sb.message.slot),
                self.syncnets.on_slot(sb.message.slot),
            ),
        )
        self._server: Optional[asyncio.AbstractServer] = None
        self._peer_seq = 0
        self.discovery = None
        self.t = get_types(preset).phase0
        if gossip_handlers is not None:
            self._subscribe_core_topics()

    # -- discovery (peers/discover.ts role) ------------------------------------

    async def enable_discovery(
        self, identity, udp_port: int = 0, bootstrap=()
    ) -> int:
        """Start the UDP discovery service; newly discovered records are
        dialed while the peer count is below max_peers."""
        from .discovery import DiscoveryService

        def on_peer(rec) -> None:
            if len(self.peer_manager.peers) >= self.peer_manager.max_peers:
                return
            if self.score_store.state(str(rec.ip)) == ScoreState.BANNED:
                return
            logger.info("discovered peer %s:%d; dialing", rec.ip, rec.tcp_port)
            asyncio.ensure_future(self._dial_discovered(rec))

        self.discovery = DiscoveryService(
            identity, tcp_port=self.port or 0, host=self.host, on_peer=on_peer
        )
        port = await self.discovery.listen(udp_port)
        for host, bport in bootstrap:
            self.discovery.add_bootstrap(host, bport)
        self.discovery.start_lookups()
        return port

    async def _dial_discovered(self, rec) -> None:
        try:
            await self.connect(rec.ip, rec.tcp_port)
        except Exception as e:  # noqa: BLE001
            logger.debug("dial of discovered peer failed: %s", e)

    # -- lifecycle -------------------------------------------------------------

    async def listen(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_inbound, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.router.start()
        logger.info("listening on %s:%d", self.host, self.port)
        return self.port

    async def connect(self, host: str, port: int) -> Peer:
        reader, writer = await asyncio.open_connection(host, port)
        return await self._setup_peer(reader, writer, initiator=True)

    async def close(self) -> None:
        self.router.stop()
        if self.discovery is not None:
            await self.discovery.close()
        for peer in self.peer_manager.connected():
            await self._drop_peer(peer, goodbye=True)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- connection plumbing ---------------------------------------------------

    async def _on_inbound(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            await self._setup_peer(reader, writer, initiator=False)
        except ConnectionRefusedError as e:
            logger.debug("inbound connection refused: %s", e)

    async def _setup_peer(self, reader, writer, *, initiator: bool) -> Peer:
        self._peer_seq += 1
        peer_id = f"peer-{id(self) & 0xFFFF:x}-{self._peer_seq}"
        try:
            # score identity = remote HOST for both directions: inbound
            # source ports are ephemeral, and a split host:port/host keying
            # would let a banned outbound peer re-enter inbound (review r4).
            # IP-granular banning (with its NAT collateral) matches the
            # reference's IP ban list.
            remote_key = str(writer.get_extra_info("peername")[0])
        except Exception:
            remote_key = peer_id
        # banned identities are refused at the door (peers/score.ts ban)
        if self.score_store.state(remote_key) == ScoreState.BANNED:
            writer.close()
            raise ConnectionRefusedError(f"peer {remote_key} is banned")
        wire = Wire(reader, writer)
        reqresp = ReqRespNode(self.p, self.chain, wire, metadata=self.metadata, metrics=self.metrics)
        peer = Peer(peer_id=peer_id, reqresp=reqresp, wire=wire, remote_key=remote_key)

        async def gossip_send(topic: str, ssz_bytes: bytes) -> None:
            await wire.send_frame(KIND_GOSSIP, Wire.encode_gossip(topic, ssz_bytes))

        async def gossip_ctrl(ctrl: dict) -> None:
            await wire.send_frame(KIND_GOSSIP_CTRL, Wire.encode_gossip_ctrl(ctrl))

        # mesh identity is the CONNECTION (peer_id): score identity stays
        # the remote host, but distinct peers on one host must hold
        # distinct mesh slots
        self.router.add_peer(peer.peer_id, gossip_send, gossip_ctrl)
        self.peer_manager.add(peer)
        if self.metrics:
            self.metrics.peers.set(len(self.peer_manager.peers))
        task = asyncio.create_task(self._read_loop(peer))
        peer.tasks.append(task)
        await self.router.announce_subscriptions(peer.peer_id)
        if initiator:
            await self.peer_manager.handshake(peer, reqresp.local_status())
        return peer

    async def _read_loop(self, peer: Peer) -> None:
        try:
            while True:
                kind, payload = await peer.wire.recv_frame()
                if kind == KIND_REQUEST:
                    asyncio.ensure_future(peer.reqresp.on_request_frame(payload))
                elif kind in (KIND_RESPONSE_CHUNK, KIND_RESPONSE_END):
                    peer.reqresp.on_response_frame(kind, payload)
                elif kind == KIND_GOSSIP:
                    topic, data = Wire.decode_gossip(payload)
                    if self.metrics:
                        self.metrics.gossip_messages_total.labels(dir="rx").inc()
                    await self.router.on_message(topic, data, from_peer=peer.peer_id)
                    await self._enforce_score(peer)
                elif kind == KIND_GOSSIP_CTRL:
                    ctrl = Wire.decode_gossip_ctrl(payload)
                    await self.router.on_control(peer.peer_id, ctrl)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as e:  # noqa: BLE001
            logger.warning("peer %s read loop error: %s", peer.peer_id, e)
        finally:
            await self._drop_peer(peer, goodbye=False)

    async def _drop_peer(self, peer: Peer, *, goodbye: bool) -> None:
        if self.peer_manager.get(peer.peer_id) is None:
            return
        if goodbye:
            await peer.reqresp.goodbye()
        self.peer_manager.remove(peer.peer_id)
        if self.metrics:
            self.metrics.peers.set(len(self.peer_manager.peers))
        self.router.remove_peer(peer.peer_id)
        peer.wire.close()
        for t in peer.tasks:
            if t is not asyncio.current_task():
                t.cancel()

    # -- peer scoring (peers/score.ts enforcement) -----------------------------

    def _on_gossip_reject(self, peer_key: str, code: str) -> None:
        """Router callback: an invalid (REJECT) gossip message is provable
        misbehavior — downscore the sender (router keys are connection ids;
        the score store keys on the remote host)."""
        peer = self.peer_manager.get(peer_key)
        key = peer.remote_key if peer is not None else peer_key
        self.score_store.apply_action(key, PeerAction.LOW_TOLERANCE, f"gossip:{code}")

    def _on_gossip_evict(self, peer_key: str, score: float) -> None:
        """Router callback: gossip score fell below the graylist
        threshold (scoringParameters.ts gossipScoreThresholds) — drop the
        peer."""
        peer = self.peer_manager.get(peer_key)
        if peer is not None:
            logger.info("evicting peer %s (gossip score %.0f)", peer_key, score)
            asyncio.ensure_future(self._drop_peer(peer, goodbye=True))

    async def report_peer(self, peer: Peer, action: PeerAction, reason: str = "") -> None:
        """Apply a score action and enforce the resulting state (the
        reqresp/sync entry point: bad blocks, garbage responses...)."""
        self.score_store.apply_action(peer.remote_key, action, reason)
        await self._enforce_score(peer)

    async def _enforce_score(self, peer: Peer) -> None:
        state = self.score_store.state(peer.remote_key)
        if state != ScoreState.HEALTHY and self.peer_manager.get(peer.peer_id) is not None:
            logger.info("dropping peer %s (%s)", peer.peer_id, state.value)
            await self._drop_peer(peer, goodbye=True)

    # -- gossip binding --------------------------------------------------------

    def _fork_digest(self) -> bytes:
        from ..state_transition import compute_fork_digest

        state = self.chain.head_state()
        return compute_fork_digest(
            self.p, bytes(state.fork.current_version), bytes(state.genesis_validators_root)
        )

    def _subscribe_core_topics(self) -> None:
        """Bind the spec topics to the chain's gossip handlers with SSZ
        decode at the boundary (gossipsub.ts topic handler table).  Topics
        are registered under EVERY fork digest in the schedule — the
        in-process analog of forks.ts's subscribe-2-epochs-ahead: messages
        for a past or future fork's digest resolve to the same handlers."""
        for digest in self._all_fork_digests():
            self._subscribe_topics_for_digest(digest)

    def _all_fork_digests(self):
        from ..state_transition import compute_fork_digest

        state = self.chain.head_state()
        gvr = bytes(state.genesis_validators_root)
        digests = []
        for info in self.chain.fork_config.forks_ascending:
            d = compute_fork_digest(self.p, info.version, gvr)
            if d not in digests:
                digests.append(d)
        return digests

    def _subscribe_topics_for_digest(self, digest: bytes) -> None:
        h = self.handlers
        t = self.t

        async def on_block(data: bytes) -> None:
            from ..db.beacon import _FORK_ORDER

            all_t = get_types(self.p)
            ft = getattr(all_t, _FORK_ORDER[data[0]])
            await h.on_block(ft.SignedBeaconBlock.deserialize(data[1:]))

        async def on_aggregate(data: bytes) -> None:
            await h.on_aggregate_and_proof(t.SignedAggregateAndProof.deserialize(data))

        async def on_exit(data: bytes) -> None:
            await h.on_voluntary_exit(t.SignedVoluntaryExit.deserialize(data))

        async def on_prop_slashing(data: bytes) -> None:
            await h.on_proposer_slashing(t.ProposerSlashing.deserialize(data))

        async def on_att_slashing(data: bytes) -> None:
            await h.on_attester_slashing(t.AttesterSlashing.deserialize(data))

        self.router.subscribe(topic_string(digest, TOPIC_BLOCK), on_block)
        self.router.subscribe(topic_string(digest, TOPIC_AGGREGATE), on_aggregate)
        self.router.subscribe(topic_string(digest, TOPIC_EXIT), on_exit)
        self.router.subscribe(topic_string(digest, TOPIC_PROPOSER_SLASHING), on_prop_slashing)
        self.router.subscribe(topic_string(digest, TOPIC_ATTESTER_SLASHING), on_att_slashing)
        for subnet in range(ATTESTATION_SUBNET_COUNT):  # all 64 (topic.ts)
            topic = topic_string(digest, TOPIC_ATTESTATION.format(subnet=subnet))

            async def on_att(data: bytes, _subnet=subnet) -> None:
                await h.on_attestation(t.Attestation.deserialize(data), subnet=_subnet)

            self.router.subscribe(topic, on_att)

        # altair sync-committee topics (gossip/interface.ts): the
        # contribution topic plus the 4 per-subnet message topics
        alt = get_types(self.p).altair

        async def on_contribution(data: bytes) -> None:
            await h.on_sync_contribution(alt.SignedContributionAndProof.deserialize(data))

        self.router.subscribe(topic_string(digest, TOPIC_SYNC_CONTRIBUTION), on_contribution)
        for subnet in range(SYNC_COMMITTEE_SUBNET_COUNT):
            topic = topic_string(digest, TOPIC_SYNC_COMMITTEE.format(subnet=subnet))

            async def on_sync_msg(data: bytes, _subnet=subnet) -> None:
                await h.on_sync_committee_message(
                    alt.SyncCommitteeMessage.deserialize(data), subnet=_subnet
                )

            self.router.subscribe(topic, on_sync_msg)

    # -- publish helpers (network.ts publishBeaconBlock etc.) ------------------

    async def publish_block(self, signed_block) -> int:
        from ..db.beacon import _FORK_ORDER
        from ..state_transition.upgrade import block_fork_name

        fork = block_fork_name(signed_block.message).value
        all_t = get_types(self.p)
        data = bytes([_FORK_ORDER.index(fork)]) + getattr(all_t, fork).SignedBeaconBlock.serialize(
            signed_block
        )
        return await self.router.publish(topic_string(self._fork_digest(), TOPIC_BLOCK), data)

    async def publish_attestation(self, attestation, subnet: int = 0) -> int:
        data = self.t.Attestation.serialize(attestation)
        return await self.router.publish(
            topic_string(self._fork_digest(), TOPIC_ATTESTATION.format(subnet=subnet)), data
        )

    async def publish_voluntary_exit(self, signed_exit) -> int:
        data = self.t.SignedVoluntaryExit.serialize(signed_exit)
        return await self.router.publish(topic_string(self._fork_digest(), TOPIC_EXIT), data)

    async def publish_sync_committee_message(self, message, subnet: int) -> int:
        data = get_types(self.p).altair.SyncCommitteeMessage.serialize(message)
        return await self.router.publish(
            topic_string(self._fork_digest(), TOPIC_SYNC_COMMITTEE.format(subnet=subnet)),
            data,
        )

    async def publish_sync_contribution(self, signed_contribution) -> int:
        data = get_types(self.p).altair.SignedContributionAndProof.serialize(signed_contribution)
        return await self.router.publish(
            topic_string(self._fork_digest(), TOPIC_SYNC_CONTRIBUTION), data
        )
