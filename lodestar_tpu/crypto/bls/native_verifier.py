"""FastBlsVerifier — the native-C CPU verifier behind IBlsVerifier.

The blst-class CPU path (reference: @chainsafe/blst behind the worker pool,
SURVEY.md section 2.9): portable C with 64-bit Montgomery limbs
(csrc/fastbls.c), ~30x the pure-Python oracle per core.  Roles:

- the node's default small-batch / gossip-single verifier (a TPU dispatch
  costs hundreds of ms of serial scan latency; one C verify costs ~10 ms —
  the same latency split the reference makes with blsVerifyOnMainThread,
  network/gossip/handlers/index.ts:114-118),
- the honest vs_baseline denominator in bench.py,
- the oracle-checked fallback when no TPU is present.

Falls back to PyBlsVerifier transparently when the C toolchain is missing.
"""

from __future__ import annotations

import secrets
from typing import Sequence

from ...native import fastbls
from .verifier import (
    AggregatedSignatureSet,
    PyBlsVerifier,
    SignatureSet,
    SingleSignatureSet,
)


class FastBlsVerifier:
    """IBlsVerifier implementation over csrc/fastbls.c."""

    def __init__(self) -> None:
        self._fallback = PyBlsVerifier() if not fastbls.have_native() else None
        self.batch_retries = 0
        self.sets_verified = 0

    @property
    def native(self) -> bool:
        return self._fallback is None

    def verify_signature_sets(self, sets: Sequence[SignatureSet]) -> bool:
        if not sets:
            # contract parity across the IBlsVerifier boundary (TpuBlsVerifier,
            # PyBlsVerifier, BlsBatchPool all raise; the reference throws)
            raise ValueError("verify_signature_sets: empty batch of signature sets")
        if self._fallback is not None:
            return self._fallback.verify_signature_sets(sets)
        packed = []
        for s in sets:
            if isinstance(s, SingleSignatureSet):
                pks = [s.pubkey.to_bytes()]
            elif isinstance(s, AggregatedSignatureSet):
                if not s.pubkeys:
                    return False
                pks = [pk.to_bytes() for pk in s.pubkeys]
            else:  # pragma: no cover - defensive
                return False
            if len(s.signing_root) != 32 or len(s.signature) != 96:
                return False
            packed.append((pks, s.signing_root, s.signature))
        coeffs = [secrets.randbits(64) | 1 for _ in packed]
        out = fastbls.batch_verify(packed, coeffs)
        if out is None:  # native lib vanished mid-run; degrade gracefully
            self._fallback = PyBlsVerifier()
            return self._fallback.verify_signature_sets(sets)
        if out:
            self.sets_verified += len(packed)
        else:
            self.batch_retries += 1
        return bool(out)

    def close(self) -> None:
        return None
