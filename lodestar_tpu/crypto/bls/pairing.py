"""Optimal ate pairing on BLS12-381.

e(P, Q) for P in G1 (over Fq), Q in G2 (on the twist, over Fq2):
Miller loop f_{|z|,Q}(P) with affine line evaluations, conjugated for z < 0,
then final exponentiation (p^12 - 1)/r.

Line evaluations use the sparse embedding derived from the twist
(x, y) -> (x/v, y/(v*w)): a doubling/addition line through T evaluated at
P = (xP, yP), scaled by the subfield factor v*w (free modulo final exp), is

    l = (lam * xT - yT)  +  (-lam * xP) * v  +  yP * v*w

with lam the slope in Fq2 — i.e. Fq12 element (c0 + c1*v, c2*v).

The final exponentiation hard part is computed with a plain bigint exponent
(p^4 - p^2 + 1)/r: slower than the cyclotomic addition chains, but this module
is the correctness oracle — the optimized chain lives in the JAX kernels and is
differential-tested against this.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from .curve import B2, G1_GEN, Point
from .fields import BLS_X, Fq, Fq2, Fq6, Fq12, P, R

_ABS_X_BITS = bin(abs(BLS_X))[2:]  # MSB first


def _line(lam: Fq2, xT: Fq2, yT: Fq2, xP: Fq, yP: Fq) -> Fq12:
    """Sparse Fq12 line value (see module docstring)."""
    c0 = lam * xT - yT
    c1 = -(lam.mul_scalar(xP.n))
    c2 = Fq2(yP.n, 0)
    return Fq12(Fq6(c0, c1, Fq2.zero()), Fq6(Fq2.zero(), c2, Fq2.zero()))


def miller_loop(p_aff: Tuple[Fq, Fq], q_aff: Tuple[Fq2, Fq2]) -> Fq12:
    """f_{|z|, Q}(P), conjugated for the negative BLS parameter."""
    xP, yP = p_aff
    xQ, yQ = q_aff
    f = Fq12.one()
    xT, yT = xQ, yQ
    for bit in _ABS_X_BITS[1:]:
        # doubling step: slope of the tangent at T
        lam = xT.square().mul_scalar(3) * (yT.mul_scalar(2)).inv()
        f = f.square() * _line(lam, xT, yT, xP, yP)
        # T = 2T (affine)
        x2 = lam.square() - xT.mul_scalar(2)
        yT = lam * (xT - x2) - yT
        xT = x2
        if bit == "1":
            # addition step: line through T and Q
            if xT == xQ:
                if yT == yQ:
                    lam = xT.square().mul_scalar(3) * (yT.mul_scalar(2)).inv()
                else:
                    # T + Q = O mid-loop: only possible for Q of tiny order,
                    # which subgroup-checked inputs never are.
                    raise ZeroDivisionError("degenerate Miller loop input (Q of tiny order)")
            else:
                lam = (yT - yQ) * (xT - xQ).inv()
            f = f * _line(lam, xT, yT, xP, yP)
            x3 = lam.square() - xT - xQ
            yT = lam * (xT - x3) - yT
            xT = x3
    # z < 0: f_{z} = conj(f_{|z|}) modulo final exponentiation
    return f.conjugate()


_HARD_EXP = (P**4 - P**2 + 1) // R


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p^12-1)/r) = [(f^(p^6-1))^(p^2+1)]^((p^4-p^2+1)/r)."""
    # easy part
    f = f.conjugate() * f.inv()  # f^(p^6 - 1)
    f = f.frobenius_n(2) * f  # ^(p^2 + 1)
    # hard part (plain exponent — correctness oracle)
    return f.pow(_HARD_EXP)


def pairing(p: Point[Fq], q: Point[Fq2]) -> Fq12:
    """e(P, Q); returns 1 for either input at infinity."""
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()
    return final_exponentiation(miller_loop(p.to_affine(), q.to_affine()))


def multi_pairing(pairs: Sequence[Tuple[Point[Fq], Point[Fq2]]]) -> Fq12:
    """Product of pairings with a single shared final exponentiation — the
    structure the batched verifier exploits (one final exp per batch)."""
    f = Fq12.one()
    for p, q in pairs:
        if p.is_infinity() or q.is_infinity():
            continue
        f = f * miller_loop(p.to_affine(), q.to_affine())
    return final_exponentiation(f)
