"""Hash-to-curve for G2: BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380 §8.8.2).

Pipeline: expand_message_xmd(sha256) -> hash_to_field(Fq2, count=2) ->
simplified-SWU onto the 3-isogenous curve E' -> 3-isogeny to E2 ->
clear cofactor (psi-endomorphism method, curve.g2_clear_cofactor).

E': y^2 = x^3 + A'x + B' with A' = 240*u, B' = 1012*(1+u), Z = -(2+u).

The 3-isogeny coefficients are validated at import time: ~16 random points of
E' are mapped and checked to land on E2. A degree-3 rational map taking E' to
E2 and infinity to infinity is automatically an isogeny (a morphism of
elliptic curves fixing O), so curve-preservation over random points pins the
constants to negligible error probability.
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

from .curve import B2, Point, g2_clear_cofactor
from .fields import Fq2, P

# Ethereum consensus signature DST (proof-of-possession scheme)
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

L = 64  # bytes per field element draw: ceil((381 + 128) / 8)

ISO_A = Fq2(0, 240)
ISO_B = Fq2(1012, 1012)
SSWU_Z = Fq2(-2, -1)  # -(2 + u)

# ---------------------------------------------------------------------------
# 3-isogeny E' -> E2 coefficients (RFC 9380 Appendix E.3), validated below.
# x = x_num(x') / x_den(x'); y = y' * y_num(x') / y_den(x') — coeffs ascending.
# ---------------------------------------------------------------------------

_K1 = [  # x_num, degree 3
    Fq2(
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    Fq2(
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    Fq2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    Fq2(
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
]

_K2 = [  # x_den, degree 2 (monic)
    Fq2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    Fq2(
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
    Fq2(1, 0),
]

_K3 = [  # y_num, degree 3
    Fq2(
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    Fq2(
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    Fq2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    Fq2(
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
]

_K4 = [  # y_den, degree 3 (monic)
    Fq2(
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    Fq2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    Fq2(
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
    Fq2(1, 0),
]


def _eval_poly(coeffs: List[Fq2], x: Fq2) -> Fq2:
    acc = Fq2.zero()
    for c in reversed(coeffs):
        acc = acc * x + c
    return acc


def _iso_map(x: Fq2, y: Fq2) -> Tuple[Fq2, Fq2]:
    x_num = _eval_poly(_K1, x)
    x_den = _eval_poly(_K2, x)
    y_num = _eval_poly(_K3, x)
    y_den = _eval_poly(_K4, x)
    return x_num * x_den.inv(), y * y_num * y_den.inv()


def _gprime(x: Fq2) -> Fq2:
    """g'(x) = x^3 + A'x + B' on the isogenous curve."""
    return x.square() * x + ISO_A * x + ISO_B


def _verify_iso_constants() -> None:
    """Map random E' points through the isogeny; all must land on E2."""
    import random

    rng = random.Random(0xB15C0)
    checked = 0
    while checked < 16:
        x = Fq2(rng.randrange(P), rng.randrange(P))
        y = _gprime(x).sqrt()
        if y is None:
            continue
        xm, ym = _iso_map(x, y)
        if ym.square() != xm.square() * xm + B2:
            raise AssertionError(
                "3-isogeny constants failed curve-preservation check "
                "(hash_to_curve iso table is wrong)"
            )
        checked += 1


_verify_iso_constants()


# ---------------------------------------------------------------------------
# expand_message_xmd / hash_to_field (RFC 9380 §5)
# ---------------------------------------------------------------------------


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        raise ValueError("DST too long")
    ell = (len_in_bytes + 31) // 32
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * 64  # sha256 block size
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = bytearray(bi)
    for i in range(2, ell + 1):
        tmp = bytes(a ^ b for a, b in zip(b0, bi))
        bi = hashlib.sha256(tmp + bytes([i]) + dst_prime).digest()
        out += bi
    return bytes(out[:len_in_bytes])


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2) -> List[Fq2]:
    len_in_bytes = count * 2 * L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off : off + L], "big") % P)
        out.append(Fq2(coords[0], coords[1]))
    return out


# ---------------------------------------------------------------------------
# Simplified SWU (RFC 9380 §6.6.2, non-uniform branches are fine off-TPU)
# ---------------------------------------------------------------------------


def map_to_curve_sswu(u: Fq2) -> Tuple[Fq2, Fq2]:
    tv1 = SSWU_Z.square() * u.pow(4) + SSWU_Z * u.square()
    if tv1.is_zero():
        x1 = ISO_B * (SSWU_Z * ISO_A).inv()
    else:
        x1 = (-ISO_B) * ISO_A.inv() * (Fq2.one() + tv1.inv())
    gx1 = _gprime(x1)
    if gx1.is_square():
        x, y = x1, gx1.sqrt()
    else:
        x2 = SSWU_Z * u.square() * x1
        gx2 = _gprime(x2)
        y = gx2.sqrt()
        if y is None:
            raise AssertionError("SSWU: neither gx1 nor gx2 is square (impossible)")
        x = x2
    assert y is not None
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


def map_to_curve_g2(u: Fq2) -> Point[Fq2]:
    x, y = map_to_curve_sswu(u)
    xm, ym = _iso_map(x, y)
    return Point.from_affine(xm, ym, B2)


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> Point[Fq2]:
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = map_to_curve_g2(u0)
    q1 = map_to_curve_g2(u1)
    return g2_clear_cofactor(q0 + q1)
