"""BLS12-381 field towers over Python bigints — the ground-truth implementation.

This is the correctness oracle for the JAX/TPU limb-arithmetic kernels in
``lodestar_tpu.ops`` (differential-tested against this module) and the host
fallback for tiny batches (the role blst-native plays for the reference's
``BlsSingleThreadVerifier``, packages/beacon-node/src/chain/bls/singleThread.ts).

Tower construction (standard for BLS12-381):
    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - xi),  xi = 1 + u
    Fq12 = Fq6[w] / (w^2 - v)

All code here is written from the mathematical definitions; nothing is
translated from the reference (whose BLS is a C dependency, supranational/blst).
"""

from __future__ import annotations

from typing import List, Tuple

# Field modulus and curve parameters
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter z (negative): p = ((z-1)^2/3) * r + z,  r = z^4 - z^2 + 1
BLS_X = -0xD201000000010000

assert (BLS_X**4 - BLS_X**2 + 1) == R
assert ((BLS_X - 1) ** 2 // 3) * R + BLS_X == P

# G1 cofactor h1 = (z-1)^2 / 3
H1 = (BLS_X - 1) ** 2 // 3


def fq_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fq_sqrt(a: int) -> int | None:
    """Square root in Fq (p % 4 == 3, so a^((p+1)/4))."""
    root = pow(a, (P + 1) // 4, P)
    return root if root * root % P == a % P else None


class Fq2:
    """a = c0 + c1*u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    @staticmethod
    def zero() -> "Fq2":
        return Fq2(0, 0)

    @staticmethod
    def one() -> "Fq2":
        return Fq2(1, 0)

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Fq2) and self.c0 == other.c0 and self.c1 == other.c1

    def __hash__(self) -> int:
        return hash((self.c0, self.c1))

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        # (a0 + a1 u)(b0 + b1 u) = (a0b0 - a1b1) + (a0b1 + a1b0) u
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        return Fq2(a0 * b0 - a1 * b1, a0 * b1 + a1 * b0)

    def mul_scalar(self, k: int) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def square(self) -> "Fq2":
        a0, a1 = self.c0, self.c1
        return Fq2((a0 + a1) * (a0 - a1), 2 * a0 * a1)

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def inv(self) -> "Fq2":
        # 1/(a0 + a1 u) = (a0 - a1 u) / (a0^2 + a1^2)
        norm = (self.c0 * self.c0 + self.c1 * self.c1) % P
        ninv = fq_inv(norm)
        return Fq2(self.c0 * ninv, -self.c1 * ninv)

    def pow(self, e: int) -> "Fq2":
        if e < 0:
            return self.inv().pow(-e)
        result, base = Fq2.one(), self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for m=2: sign of c0, or of c1 if c0 == 0."""
        sign_0 = self.c0 % 2
        zero_0 = self.c0 == 0
        sign_1 = self.c1 % 2
        return sign_0 or (zero_0 and sign_1)

    def is_square(self) -> bool:
        # Legendre in Fq2: a^((q^2-1)/2) == 1; equivalently norm is a QR in Fq.
        if self.is_zero():
            return True
        norm = (self.c0 * self.c0 + self.c1 * self.c1) % P
        return pow(norm, (P - 1) // 2, P) == 1

    def sqrt(self) -> "Fq2 | None":
        """Square root in Fq2 for p % 4 == 3 (complex-extension method)."""
        if self.is_zero():
            return Fq2.zero()
        # candidate = a^((q+1)/4) with q = p^2; (p^2+7)/16 etc. avoided by
        # the two-step method: a1 = a^((p-3)/4); alpha = a1^2 * a = a^((p-1)/2)
        a1 = self.pow((P - 3) // 4)
        alpha = a1.square() * self
        x0 = a1 * self
        if alpha == Fq2(P - 1, 0):  # alpha == -1
            cand = Fq2(-x0.c1, x0.c0)  # i * x0
        else:
            b = (alpha + Fq2.one()).pow((P - 1) // 2)
            cand = b * x0
        return cand if cand.square() == self else None

    def frobenius(self) -> "Fq2":
        """x -> x^p (conjugation, since u^p = -u for p % 4 == 3)."""
        return self.conjugate()

    def __repr__(self) -> str:  # pragma: no cover
        return f"Fq2({hex(self.c0)}, {hex(self.c1)})"


class Fq:
    """Fq element with the same operator protocol as Fq2 (lets the curve ops
    in curve.py be generic over the base field of G1 vs G2)."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    @staticmethod
    def zero() -> "Fq":
        return Fq(0)

    @staticmethod
    def one() -> "Fq":
        return Fq(1)

    def is_zero(self) -> bool:
        return self.n == 0

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Fq) and self.n == other.n

    def __hash__(self) -> int:
        return hash(("Fq", self.n))

    def __add__(self, o: "Fq") -> "Fq":
        return Fq(self.n + o.n)

    def __sub__(self, o: "Fq") -> "Fq":
        return Fq(self.n - o.n)

    def __neg__(self) -> "Fq":
        return Fq(-self.n)

    def __mul__(self, o: "Fq") -> "Fq":
        return Fq(self.n * o.n)

    def mul_scalar(self, k: int) -> "Fq":
        return Fq(self.n * k)

    def square(self) -> "Fq":
        return Fq(self.n * self.n)

    def inv(self) -> "Fq":
        return Fq(fq_inv(self.n))

    def pow(self, e: int) -> "Fq":
        return Fq(pow(self.n, e, P)) if e >= 0 else self.inv().pow(-e)

    def sgn0(self) -> int:
        return self.n % 2

    def is_square(self) -> bool:
        return self.n == 0 or pow(self.n, (P - 1) // 2, P) == 1

    def sqrt(self) -> "Fq | None":
        root = fq_sqrt(self.n)
        return Fq(root) if root is not None else None

    def __repr__(self) -> str:  # pragma: no cover
        return f"Fq({hex(self.n)})"


XI = Fq2(1, 1)  # the Fq6 non-residue xi = 1 + u

# Frobenius coefficients, computed (not transcribed):
#   Fq6:  v^p  = xi^((p-1)/3) * v,   v^(2p) coefficient for v^2 term
#   Fq12: w^p  = xi^((p-1)/6) * w
FROB_C1_V = XI.pow((P - 1) // 3)  # gamma for v
FROB_C1_V2 = XI.pow(2 * (P - 1) // 3)  # gamma for v^2
FROB_C1_W = XI.pow((P - 1) // 6)  # gamma for w


class Fq6:
    """a = c0 + c1*v + c2*v^2 with v^3 = xi."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    @staticmethod
    def zero() -> "Fq6":
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one() -> "Fq6":
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Fq6)
            and self.c0 == other.c0
            and self.c1 == other.c1
            and self.c2 == other.c2
        )

    def __hash__(self) -> int:
        return hash(("Fq6", self.c0, self.c1, self.c2))

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        # Karatsuba-style (Toom) interpolation
        c0 = t0 + XI * ((a1 + a2) * (b1 + b2) - t1 - t2)
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + XI * t2
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def mul_by_fq2(self, k: Fq2) -> "Fq6":
        return Fq6(self.c0 * k, self.c1 * k, self.c2 * k)

    def mul_by_v(self) -> "Fq6":
        """Multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1)."""
        return Fq6(XI * self.c2, self.c0, self.c1)

    def square(self) -> "Fq6":
        return self * self

    def inv(self) -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - XI * (a1 * a2)
        t1 = XI * a2.square() - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = a0 * t0 + XI * (a2 * t1 + a1 * t2)
        dinv = denom.inv()
        return Fq6(t0 * dinv, t1 * dinv, t2 * dinv)

    def frobenius(self) -> "Fq6":
        return Fq6(
            self.c0.frobenius(),
            self.c1.frobenius() * FROB_C1_V,
            self.c2.frobenius() * FROB_C1_V2,
        )

    def __repr__(self) -> str:  # pragma: no cover
        return f"Fq6({self.c0!r}, {self.c1!r}, {self.c2!r})"


class Fq12:
    """a = c0 + c1*w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    @staticmethod
    def one() -> "Fq12":
        return Fq12(Fq6.one(), Fq6.zero())

    def is_one(self) -> bool:
        return self == Fq12.one()

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Fq12) and self.c0 == other.c0 and self.c1 == other.c1

    def __hash__(self) -> int:
        return hash(("Fq12", self.c0, self.c1))

    def __mul__(self, o: "Fq12") -> "Fq12":
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        return self * self

    def conjugate(self) -> "Fq12":
        """c0 - c1 w == x^(p^6); on the cyclotomic subgroup this is x^-1."""
        return Fq12(self.c0, -self.c1)

    def inv(self) -> "Fq12":
        # 1/(a0 + a1 w) = (a0 - a1 w)/(a0^2 - a1^2 v)
        denom = self.c0.square() - self.c1.square().mul_by_v()
        dinv = denom.inv()
        return Fq12(self.c0 * dinv, -(self.c1 * dinv))

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inv().pow(-e)
        result, base = Fq12.one(), self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def frobenius(self) -> "Fq12":
        c0 = self.c0.frobenius()
        c1f = self.c1.frobenius()
        return Fq12(c0, Fq6(c1f.c0 * FROB_C1_W, c1f.c1 * FROB_C1_W, c1f.c2 * FROB_C1_W))

    def frobenius_n(self, n: int) -> "Fq12":
        out = self
        for _ in range(n):
            out = out.frobenius()
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"Fq12({self.c0!r}, {self.c1!r})"
