"""BLS signature API (ETH2 / proof-of-possession ciphersuite).

Mirrors the capability surface of the reference's @chainsafe/bls facade
(SecretKey/PublicKey/Signature, aggregate, verifyMultipleSignatures —
SURVEY.md §2.9) over the ground-truth pairing in this package.

Batch verification follows the random-linear-combination scheme of blst's
``verifyMultipleSignatures`` (reference call site:
packages/beacon-node/src/chain/bls/maybeBatch.ts:17-27): with random 64-bit
nonzero coefficients c_i,

    e(-g1, sum c_i s_i) * prod e(c_i pk_i, H(m_i)) == 1

soundness: a forged set passes with probability ~2^-64 per attempt.
"""

from __future__ import annotations

import hashlib
import secrets
from typing import List, Optional, Sequence, Tuple

from .curve import (
    B1,
    B2,
    G1_GEN,
    Point,
    g1_from_bytes,
    g1_subgroup_check,
    g1_to_bytes,
    g2_from_bytes,
    g2_subgroup_check,
    g2_to_bytes,
)
from .fields import Fq, Fq2, R
from .hash_to_curve import DST_G2, hash_to_g2
from .pairing import multi_pairing
from ...native import fastbls as _native


class SecretKey:
    __slots__ = ("value",)

    def __init__(self, value: int):
        if not 0 < value < R:
            raise ValueError("secret key out of range")
        self.value = value

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != 32:
            raise ValueError("secret key must be 32 bytes")
        return cls(int.from_bytes(data, "big"))

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(32, "big")

    def to_public_key(self) -> "PublicKey":
        raw = _native.sk_to_pk(self.to_bytes())
        if raw is not None:
            return PublicKey(raw=raw)
        return PublicKey(G1_GEN * self.value)

    def sign(self, msg: bytes, variable_time: bool = False) -> "Signature":
        """sk * H(msg), native-first (identical compressed bytes to the
        bigint ladder, ~3 orders of magnitude faster; differential tests
        pin byte equality AND fb_selftest pins ct == variable-time).

        Default is the CONSTANT-TIME-SAFE native ladder (fb_sign_ct:
        fixed-length double-and-always-add, uniform operation sequence) —
        the variable-time sliding ladder (fb_sign) leaks the secret
        scalar through its branch pattern and is opt-in for dev/interop
        fixtures where the keys are the published interop secrets
        (``variable_time=True``; ValidatorStore gates this via
        ``dev_signing``).  The pure-Python fallback (no native lib) is a
        plain double-and-add bigint ladder: correct, slow, and NOT
        constant-time — acceptable only because it is the no-toolchain
        degradation path."""
        sk = self.to_bytes()
        raw = _native.sign(sk, msg) if variable_time else _native.sign_ct(sk, msg)
        if raw is not None:
            return Signature(raw=raw)
        return Signature(hash_to_g2(msg) * self.value)


class PublicKey:
    """Lazily materialised: freshly-derived keys carry only their canonical
    compressed bytes (the native fb_sk_to_pk output) and decompress on first
    curve use, so serialize-only flows never pay Python field math."""

    __slots__ = ("_point", "_raw")

    def __init__(self, point: Optional[Point[Fq]] = None, raw: Optional[bytes] = None):
        if point is None and raw is None:
            raise ValueError("PublicKey needs a point or raw bytes")
        self._point = point
        self._raw = raw

    @property
    def point(self) -> Point[Fq]:
        if self._point is None:
            # self-produced canonical bytes: skip the subgroup check
            self._point = g1_from_bytes(self._raw, subgroup_check=False)
        return self._point

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "PublicKey":
        pk = cls(g1_from_bytes(data, subgroup_check=validate))
        pk._raw = bytes(data)
        return pk

    def to_bytes(self) -> bytes:
        if self._raw is None:
            self._raw = g1_to_bytes(self._point)
        return self._raw

    def is_infinity(self) -> bool:
        if self._point is not None:
            return self._point.is_infinity()
        return self._raw[0] == 0xC0 and not any(self._raw[1:])

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PublicKey) and self.to_bytes() == other.to_bytes()

    def __hash__(self) -> int:
        return hash(("PublicKey", self.to_bytes()))


class Signature:
    """Lazily materialised like PublicKey: native-signed signatures carry
    compressed bytes only until a pairing needs the actual point."""

    __slots__ = ("_point", "_raw")

    def __init__(self, point: Optional[Point[Fq2]] = None, raw: Optional[bytes] = None):
        if point is None and raw is None:
            raise ValueError("Signature needs a point or raw bytes")
        self._point = point
        self._raw = raw

    @property
    def point(self) -> Point[Fq2]:
        if self._point is None:
            self._point = g2_from_bytes(self._raw, subgroup_check=False)
        return self._point

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "Signature":
        sig = cls(g2_from_bytes(data, subgroup_check=validate))
        sig._raw = bytes(data)
        return sig

    def to_bytes(self) -> bytes:
        if self._raw is None:
            self._raw = g2_to_bytes(self._point)
        return self._raw

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Signature) and self.to_bytes() == other.to_bytes()

    def __hash__(self) -> int:
        return hash(("Signature", self.to_bytes()))


def sign_aggregate(sks: Sequence[SecretKey], msg: bytes) -> "Signature":
    """Aggregate signature of the same message by many keys — one hash +
    one scalar mult on the native path (fb_sign_aggregate); per-key sign +
    aggregate otherwise.  The whole-committee signing shape of DEV CHAINS
    and sim fixtures only (interop keys): the underlying scalar mult is
    the variable-time ladder, which is fine exactly because these keys
    are public test vectors — production per-validator signing goes
    through ValidatorStore (constant-time path)."""
    raw = _native.sign_aggregate([sk.to_bytes() for sk in sks], msg)
    if raw is not None:
        return Signature(raw=raw)
    return aggregate_signatures([sk.sign(msg, variable_time=True) for sk in sks])


def aggregate_pubkeys(pubkeys: Sequence[PublicKey]) -> PublicKey:
    """Sum in jacobian coords (reference: getAggregatedPubkey,
    chain/bls/utils.ts:5, ~3x faster than affine per interface.ts:31-33).
    All-raw inputs aggregate natively (fb_aggregate_pubkeys_c)."""
    if pubkeys and all(pk._raw is not None and pk._point is None for pk in pubkeys):
        out = _native.aggregate_pks([pk._raw for pk in pubkeys])
        if out is not None:
            return PublicKey(raw=out)
    acc: Point[Fq] = Point.infinity(B1)
    for pk in pubkeys:
        acc = acc + pk.point
    return PublicKey(acc)


def aggregate_signatures(sigs: Sequence[Signature]) -> Signature:
    if sigs and all(s._raw is not None and s._point is None for s in sigs):
        out = _native.aggregate_sigs([s._raw for s in sigs])
        if out is not None:
            return Signature(raw=out)
    acc: Point[Fq2] = Point.infinity(B2)
    for s in sigs:
        acc = acc + s.point
    return Signature(acc)


def verify(pk: PublicKey, msg: bytes, sig: Signature) -> bool:
    """Core verify (PoP scheme): e(g1, sig) == e(pk, H(msg))."""
    if pk.point.is_infinity() or sig.point.is_infinity():
        return False
    return multi_pairing([(-G1_GEN, sig.point), (pk.point, hash_to_g2(msg))]).is_one()


def fast_aggregate_verify(pks: Sequence[PublicKey], msg: bytes, sig: Signature) -> bool:
    """Same message, many signers (sync-committee / aggregate attestations)."""
    if not pks:
        return False
    return verify(aggregate_pubkeys(pks), msg, sig)


def aggregate_verify(
    pks: Sequence[PublicKey], msgs: Sequence[bytes], sig: Signature
) -> bool:
    """Distinct messages, one aggregate signature."""
    if not pks or len(pks) != len(msgs):
        return False
    if any(pk.point.is_infinity() for pk in pks) or sig.point.is_infinity():
        return False
    pairs: List[Tuple[Point[Fq], Point[Fq2]]] = [(-G1_GEN, sig.point)]
    pairs += [(pk.point, hash_to_g2(m)) for pk, m in zip(pks, msgs)]
    return multi_pairing(pairs).is_one()


def verify_multiple_signatures(
    sets: Sequence[Tuple[PublicKey, bytes, Signature]],
    rand_bits: int = 64,
) -> bool:
    """Batch verify with random linear combination (see module docstring)."""
    if not sets:
        return False
    if any(pk.point.is_infinity() or s.point.is_infinity() for pk, _, s in sets):
        return False
    coeffs = [secrets.randbits(rand_bits) | 1 for _ in sets]
    sig_acc: Point[Fq2] = Point.infinity(B2)
    pairs: List[Tuple[Point[Fq], Point[Fq2]]] = []
    for (pk, msg, sig), c in zip(sets, coeffs):
        sig_acc = sig_acc + sig.point * c
        pairs.append((pk.point * c, hash_to_g2(msg)))
    pairs.append((-G1_GEN, sig_acc))
    return multi_pairing(pairs).is_one()


# ---------------------------------------------------------------------------
# Interop (deterministic test keys)
# ---------------------------------------------------------------------------


def interop_secret_key(index: int) -> SecretKey:
    """sk_i = int(LE(sha256(LE64(i) padded to 32)))) mod r.

    Reference: packages/state-transition/src/util/interop.ts:20-24 (eth2
    interop key derivation; validated against
    packages/state-transition/test-cache/interop-pubkeys.json).
    """
    digest = hashlib.sha256(index.to_bytes(32, "little")).digest()
    return SecretKey(int.from_bytes(digest, "little") % R)


def interop_pubkeys(count: int) -> List[bytes]:
    return [interop_secret_key(i).to_public_key().to_bytes() for i in range(count)]
