"""BLS signature API (ETH2 / proof-of-possession ciphersuite).

Mirrors the capability surface of the reference's @chainsafe/bls facade
(SecretKey/PublicKey/Signature, aggregate, verifyMultipleSignatures —
SURVEY.md §2.9) over the ground-truth pairing in this package.

Batch verification follows the random-linear-combination scheme of blst's
``verifyMultipleSignatures`` (reference call site:
packages/beacon-node/src/chain/bls/maybeBatch.ts:17-27): with random 64-bit
nonzero coefficients c_i,

    e(-g1, sum c_i s_i) * prod e(c_i pk_i, H(m_i)) == 1

soundness: a forged set passes with probability ~2^-64 per attempt.
"""

from __future__ import annotations

import hashlib
import secrets
from typing import List, Optional, Sequence, Tuple

from .curve import (
    B1,
    B2,
    G1_GEN,
    Point,
    g1_from_bytes,
    g1_subgroup_check,
    g1_to_bytes,
    g2_from_bytes,
    g2_subgroup_check,
    g2_to_bytes,
)
from .fields import Fq, Fq2, R
from .hash_to_curve import DST_G2, hash_to_g2
from .pairing import multi_pairing


class SecretKey:
    __slots__ = ("value",)

    def __init__(self, value: int):
        if not 0 < value < R:
            raise ValueError("secret key out of range")
        self.value = value

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != 32:
            raise ValueError("secret key must be 32 bytes")
        return cls(int.from_bytes(data, "big"))

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(32, "big")

    def to_public_key(self) -> "PublicKey":
        return PublicKey(G1_GEN * self.value)

    def sign(self, msg: bytes) -> "Signature":
        return Signature(hash_to_g2(msg) * self.value)


class PublicKey:
    __slots__ = ("point",)

    def __init__(self, point: Point[Fq]):
        self.point = point

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "PublicKey":
        return cls(g1_from_bytes(data, subgroup_check=validate))

    def to_bytes(self) -> bytes:
        return g1_to_bytes(self.point)

    def is_infinity(self) -> bool:
        return self.point.is_infinity()

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PublicKey) and self.point == other.point

    def __hash__(self) -> int:
        return hash(("PublicKey", self.point))


class Signature:
    __slots__ = ("point",)

    def __init__(self, point: Point[Fq2]):
        self.point = point

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "Signature":
        return cls(g2_from_bytes(data, subgroup_check=validate))

    def to_bytes(self) -> bytes:
        return g2_to_bytes(self.point)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Signature) and self.point == other.point

    def __hash__(self) -> int:
        return hash(("Signature", self.point))


def aggregate_pubkeys(pubkeys: Sequence[PublicKey]) -> PublicKey:
    """Sum in jacobian coords (reference: getAggregatedPubkey,
    chain/bls/utils.ts:5, ~3x faster than affine per interface.ts:31-33)."""
    acc: Point[Fq] = Point.infinity(B1)
    for pk in pubkeys:
        acc = acc + pk.point
    return PublicKey(acc)


def aggregate_signatures(sigs: Sequence[Signature]) -> Signature:
    acc: Point[Fq2] = Point.infinity(B2)
    for s in sigs:
        acc = acc + s.point
    return Signature(acc)


def verify(pk: PublicKey, msg: bytes, sig: Signature) -> bool:
    """Core verify (PoP scheme): e(g1, sig) == e(pk, H(msg))."""
    if pk.point.is_infinity() or sig.point.is_infinity():
        return False
    return multi_pairing([(-G1_GEN, sig.point), (pk.point, hash_to_g2(msg))]).is_one()


def fast_aggregate_verify(pks: Sequence[PublicKey], msg: bytes, sig: Signature) -> bool:
    """Same message, many signers (sync-committee / aggregate attestations)."""
    if not pks:
        return False
    return verify(aggregate_pubkeys(pks), msg, sig)


def aggregate_verify(
    pks: Sequence[PublicKey], msgs: Sequence[bytes], sig: Signature
) -> bool:
    """Distinct messages, one aggregate signature."""
    if not pks or len(pks) != len(msgs):
        return False
    if any(pk.point.is_infinity() for pk in pks) or sig.point.is_infinity():
        return False
    pairs: List[Tuple[Point[Fq], Point[Fq2]]] = [(-G1_GEN, sig.point)]
    pairs += [(pk.point, hash_to_g2(m)) for pk, m in zip(pks, msgs)]
    return multi_pairing(pairs).is_one()


def verify_multiple_signatures(
    sets: Sequence[Tuple[PublicKey, bytes, Signature]],
    rand_bits: int = 64,
) -> bool:
    """Batch verify with random linear combination (see module docstring)."""
    if not sets:
        return False
    if any(pk.point.is_infinity() or s.point.is_infinity() for pk, _, s in sets):
        return False
    coeffs = [secrets.randbits(rand_bits) | 1 for _ in sets]
    sig_acc: Point[Fq2] = Point.infinity(B2)
    pairs: List[Tuple[Point[Fq], Point[Fq2]]] = []
    for (pk, msg, sig), c in zip(sets, coeffs):
        sig_acc = sig_acc + sig.point * c
        pairs.append((pk.point * c, hash_to_g2(msg)))
    pairs.append((-G1_GEN, sig_acc))
    return multi_pairing(pairs).is_one()


# ---------------------------------------------------------------------------
# Interop (deterministic test keys)
# ---------------------------------------------------------------------------


def interop_secret_key(index: int) -> SecretKey:
    """sk_i = int(LE(sha256(LE64(i) padded to 32)))) mod r.

    Reference: packages/state-transition/src/util/interop.ts:20-24 (eth2
    interop key derivation; validated against
    packages/state-transition/test-cache/interop-pubkeys.json).
    """
    digest = hashlib.sha256(index.to_bytes(32, "little")).digest()
    return SecretKey(int.from_bytes(digest, "little") % R)


def interop_pubkeys(count: int) -> List[bytes]:
    return [interop_secret_key(i).to_public_key().to_bytes() for i in range(count)]
