"""BLS12-381 G1/G2 group operations (jacobian coordinates) and ZCash-format
point serialization.

G1: y^2 = x^3 + 4        over Fq
G2: y^2 = x^3 + 4(1+u)   over Fq2 (the sextic twist)

Jacobian coordinates mirror the reference's choice of storing deserialized
pubkeys in jacobian form for fast aggregation
(packages/state-transition/src/cache/pubkeyCache.ts:75).

Serialization is the ZCash BLS12-381 compressed format used by the consensus
spec: 48-byte G1 / 96-byte G2, flag bits in the top 3 bits of byte 0
(compression, infinity, y-sign).
"""

from __future__ import annotations

from typing import Generic, Optional, TypeVar

from .fields import BLS_X, Fq, Fq2, P, R

F = TypeVar("F", Fq, Fq2)

B1 = Fq(4)
B2 = Fq2(4, 4)

# psi (untwist-Frobenius-twist) endomorphism constants, computed not transcribed:
#   psi(x, y) = (conj(x) / xi^((p-1)/3), conj(y) / xi^((p-1)/2))
from .fields import XI  # noqa: E402

PSI_CX = XI.pow((P - 1) // 3).inv()
PSI_CY = XI.pow((P - 1) // 2).inv()


class Point(Generic[F]):
    """Jacobian point (X, Y, Z): affine (X/Z^2, Y/Z^3); Z=0 is infinity."""

    __slots__ = ("x", "y", "z", "b")

    def __init__(self, x: F, y: F, z: F, b: F):
        self.x, self.y, self.z, self.b = x, y, z, b

    # -- constructors -------------------------------------------------------

    @staticmethod
    def infinity(b: F) -> "Point[F]":
        return Point(b.__class__.one(), b.__class__.one(), b.__class__.zero(), b)

    @staticmethod
    def from_affine(x: F, y: F, b: F) -> "Point[F]":
        return Point(x, y, b.__class__.one(), b)

    # -- predicates ---------------------------------------------------------

    def is_infinity(self) -> bool:
        return self.z.is_zero()

    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        # Y^2 = X^3 + b Z^6
        z2 = self.z.square()
        z6 = z2.square() * z2
        return self.y.square() == self.x.square() * self.x + self.b * z6

    def to_affine(self) -> Optional[tuple]:
        if self.is_infinity():
            return None
        zinv = self.z.inv()
        zinv2 = zinv.square()
        return (self.x * zinv2, self.y * zinv2 * zinv)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Point):
            return NotImplemented
        if self.is_infinity() or other.is_infinity():
            return self.is_infinity() and other.is_infinity()
        # X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3
        z12, z2sq = self.z.square(), other.z.square()
        if self.x * z2sq != other.x * z12:
            return False
        return self.y * z2sq * other.z == other.y * z12 * self.z

    def __hash__(self) -> int:
        aff = self.to_affine()
        return hash(("Point", None)) if aff is None else hash(("Point", aff[0], aff[1]))

    # -- group law ----------------------------------------------------------

    def double(self) -> "Point[F]":
        if self.is_infinity() or self.y.is_zero():
            return Point.infinity(self.b)
        x, y, z = self.x, self.y, self.z
        a = x.square()
        bb = y.square()
        c = bb.square()
        d = ((x + bb).square() - a - c).mul_scalar(2)
        e = a.mul_scalar(3)
        f = e.square()
        x3 = f - d.mul_scalar(2)
        y3 = e * (d - x3) - c.mul_scalar(8)
        z3 = (y * z).mul_scalar(2)
        return Point(x3, y3, z3, self.b)

    def __add__(self, other: "Point[F]") -> "Point[F]":
        if self.is_infinity():
            return other
        if other.is_infinity():
            return self
        z1z1 = self.z.square()
        z2z2 = other.z.square()
        u1 = self.x * z2z2
        u2 = other.x * z1z1
        s1 = self.y * z2z2 * other.z
        s2 = other.y * z1z1 * self.z
        if u1 == u2:
            if s1 == s2:
                return self.double()
            return Point.infinity(self.b)
        h = u2 - u1
        i = h.mul_scalar(2).square()
        j = h * i
        r = (s2 - s1).mul_scalar(2)
        v = u1 * i
        x3 = r.square() - j - v.mul_scalar(2)
        y3 = r * (v - x3) - (s1 * j).mul_scalar(2)
        z3 = ((self.z + other.z).square() - z1z1 - z2z2) * h
        return Point(x3, y3, z3, self.b)

    def __neg__(self) -> "Point[F]":
        return Point(self.x, -self.y, self.z, self.b)

    def __sub__(self, other: "Point[F]") -> "Point[F]":
        return self + (-other)

    def __mul__(self, k: int) -> "Point[F]":
        if k < 0:
            return (-self) * (-k)
        result = Point.infinity(self.b)
        addend = self
        while k:
            if k & 1:
                result = result + addend
            addend = addend.double()
            k >>= 1
        return result

    __rmul__ = __mul__

    def __repr__(self) -> str:  # pragma: no cover
        aff = self.to_affine()
        return f"Point(infinity)" if aff is None else f"Point({aff[0]!r}, {aff[1]!r})"


def batch_inverse(elems: list) -> list:
    """Montgomery batch inversion: n field inverses for ONE actual
    inversion plus 3(n-1) multiplications.  Works over any field element
    type with ``*`` and ``.inv()`` (Fq and Fq2 here); all elements must be
    nonzero and of the same type.

    This is what makes the pack stage's per-set ``to_affine()`` affordable
    at batch size: the bigint ``pow(a, p-2, p)`` is ~100x a multiplication,
    so amortizing it across the batch collapses the Amdahl serial stage
    (the analog of blst's blst_fp_inverse batching in Lodestar's pack
    path)."""
    if not elems:
        return []
    prefix = [elems[0]]
    for e in elems[1:]:
        prefix.append(prefix[-1] * e)
    acc = prefix[-1].inv()
    out: list = [None] * len(elems)
    for i in range(len(elems) - 1, 0, -1):
        out[i] = acc * prefix[i - 1]
        acc = acc * elems[i]
    out[0] = acc
    return out


def to_affine_batch(points: list) -> list:
    """Affine (x, y) for many jacobian points with one field inversion via
    ``batch_inverse`` over the Z coordinates.  Infinity points map to None
    (callers reject them before packing).  All points must share a field
    type — G1 and G2 batches are inverted separately."""
    live = [(i, p) for i, p in enumerate(points) if not p.is_infinity()]
    zinvs = batch_inverse([p.z for _, p in live])
    out: list = [None] * len(points)
    for (i, p), zi in zip(live, zinvs):
        zi2 = zi.square()
        out[i] = (p.x * zi2, p.y * zi2 * zi)
    return out


# -- generators (standard BLS12-381 generator points) -----------------------

G1_GEN = Point.from_affine(
    Fq(0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB),
    Fq(0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1),
    B1,
)

G2_GEN = Point.from_affine(
    Fq2(
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    Fq2(
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
    B2,
)


# -- endomorphisms & subgroup checks ---------------------------------------


def psi(pt: Point[Fq2]) -> Point[Fq2]:
    """Untwist-Frobenius-twist endomorphism on E2. On G2, psi(P) = [z]P
    (the Frobenius eigenvalue p is congruent to the BLS parameter z mod r)."""
    if pt.is_infinity():
        return pt
    x, y = pt.to_affine()
    return Point.from_affine(x.conjugate() * PSI_CX, y.conjugate() * PSI_CY, B2)


def g2_subgroup_check(pt: Point[Fq2]) -> bool:
    """P in G2 iff psi(P) == [z]P (z = BLS_X < 0)."""
    if pt.is_infinity():
        return True
    if not pt.is_on_curve():
        return False
    return psi(pt) == pt * BLS_X


def g2_clear_cofactor(pt: Point[Fq2]) -> Point[Fq2]:
    """Fast cofactor clearing (Budroni-Pintore):
    h_eff * P = [z^2 - z - 1]P + [z - 1]psi(P) + psi^2([2]P)."""
    z = BLS_X
    t1 = pt * (z * z - z - 1)
    t2 = psi(pt) * (z - 1)
    t3 = psi(psi(pt.double()))
    return t1 + t2 + t3


# G1 endomorphism: sigma(x, y) = (beta*x, y) with beta a primitive cube root
# of unity; on G1, sigma(P) = [z^2 - 1]P (lambda^2 + lambda + 1 = 0 mod r).
def _find_beta() -> int:
    # beta = c^((p-1)/3) for any c with a nontrivial cube character.
    c = 2
    while True:
        beta = pow(c, (P - 1) // 3, P)
        if beta != 1:
            # pick the root matching eigenvalue z^2 - 1 on the generator
            cand = Point.from_affine(G1_GEN.x * Fq(beta), G1_GEN.y, B1)
            if cand == G1_GEN * (BLS_X * BLS_X - 1):
                return beta
            beta2 = beta * beta % P
            cand = Point.from_affine(G1_GEN.x * Fq(beta2), G1_GEN.y, B1)
            if cand == G1_GEN * (BLS_X * BLS_X - 1):
                return beta2
            raise AssertionError("no cube root of unity matches the G1 eigenvalue")
        c += 1


BETA = _find_beta()


def g1_subgroup_check(pt: Point[Fq]) -> bool:
    """P in G1 iff sigma(P) == [z^2 - 1]P."""
    if pt.is_infinity():
        return True
    if not pt.is_on_curve():
        return False
    x, y = pt.to_affine()
    sigma = Point.from_affine(x * Fq(BETA), y, B1)
    return sigma == pt * (BLS_X * BLS_X - 1)


# -- serialization (ZCash compressed format) --------------------------------

_COMPRESSED_FLAG = 0x80
_INFINITY_FLAG = 0x40
_SIGN_FLAG = 0x20


def g1_to_bytes(pt: Point[Fq]) -> bytes:
    if pt.is_infinity():
        return bytes([_COMPRESSED_FLAG | _INFINITY_FLAG]) + b"\x00" * 47
    x, y = pt.to_affine()
    flags = _COMPRESSED_FLAG | (_SIGN_FLAG if y.n > (P - 1) // 2 else 0)
    out = bytearray(x.n.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_from_bytes(data: bytes, subgroup_check: bool = True) -> Point[Fq]:
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMPRESSED_FLAG:
        raise ValueError("uncompressed G1 input not supported")
    if flags & _INFINITY_FLAG:
        if any(data[1:]) or flags & ~(_COMPRESSED_FLAG | _INFINITY_FLAG) or data[0] != 0xC0:
            raise ValueError("malformed G1 infinity encoding")
        return Point.infinity(B1)
    xn = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if xn >= P:
        raise ValueError("G1 x coordinate out of range")
    x = Fq(xn)
    y2 = x.square() * x + B1
    y = y2.sqrt()
    if y is None:
        raise ValueError("G1 x not on curve")
    if (y.n > (P - 1) // 2) != bool(flags & _SIGN_FLAG):
        y = -y
    pt = Point.from_affine(x, y, B1)
    if subgroup_check and not g1_subgroup_check(pt):
        raise ValueError("G1 point not in subgroup")
    return pt


def g2_to_bytes(pt: Point[Fq2]) -> bytes:
    if pt.is_infinity():
        return bytes([_COMPRESSED_FLAG | _INFINITY_FLAG]) + b"\x00" * 95
    x, y = pt.to_affine()
    # sign: lexicographic on (c1, c0)
    greater = y.c1 > (P - 1) // 2 or (y.c1 == 0 and y.c0 > (P - 1) // 2)
    flags = _COMPRESSED_FLAG | (_SIGN_FLAG if greater else 0)
    out = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_from_bytes(data: bytes, subgroup_check: bool = True) -> Point[Fq2]:
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMPRESSED_FLAG:
        raise ValueError("uncompressed G2 input not supported")
    if flags & _INFINITY_FLAG:
        if any(data[1:]) or data[0] != 0xC0:
            raise ValueError("malformed G2 infinity encoding")
        return Point.infinity(B2)
    c1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    c0 = int.from_bytes(data[48:], "big")
    if c0 >= P or c1 >= P:
        raise ValueError("G2 x coordinate out of range")
    x = Fq2(c0, c1)
    y2 = x.square() * x + B2
    y = y2.sqrt()
    if y is None:
        raise ValueError("G2 x not on curve")
    greater = y.c1 > (P - 1) // 2 or (y.c1 == 0 and y.c0 > (P - 1) // 2)
    if greater != bool(flags & _SIGN_FLAG):
        y = -y
    pt = Point.from_affine(x, y, B2)
    if subgroup_check and not g2_subgroup_check(pt):
        raise ValueError("G2 point not in subgroup")
    return pt
