"""BLS12-381 for the ETH2 proof-of-possession ciphersuite.

Ground-truth Python implementation (fields/curve/pairing/hash_to_curve/api)
plus the IBlsVerifier plugin boundary (verifier). The TPU-backed verifier
lives in lodestar_tpu.ops and is differential-tested against this package.
"""

from .api import (
    PublicKey,
    SecretKey,
    Signature,
    aggregate_pubkeys,
    aggregate_signatures,
    aggregate_verify,
    fast_aggregate_verify,
    interop_pubkeys,
    interop_secret_key,
    verify,
    verify_multiple_signatures,
)
from .verifier import (
    AggregatedSignatureSet,
    IBlsVerifier,
    PyBlsVerifier,
    SignatureSet,
    SingleSignatureSet,
    get_aggregated_pubkey,
)

__all__ = [
    "PublicKey",
    "SecretKey",
    "Signature",
    "aggregate_pubkeys",
    "aggregate_signatures",
    "aggregate_verify",
    "fast_aggregate_verify",
    "interop_pubkeys",
    "interop_secret_key",
    "verify",
    "verify_multiple_signatures",
    "AggregatedSignatureSet",
    "IBlsVerifier",
    "PyBlsVerifier",
    "SignatureSet",
    "SingleSignatureSet",
    "get_aggregated_pubkey",
]
