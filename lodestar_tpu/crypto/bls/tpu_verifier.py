"""TpuBlsVerifier — the IBlsVerifier implementation backed by the batched
JAX kernel (lodestar_tpu.ops.batch_verify).

This is the replacement for the reference's BlsMultiThreadWorkerPool
(packages/beacon-node/src/chain/bls/multithread/index.ts:98): instead of
shipping serialized {pubkey, message, signature} triples to N worker
threads, the host packs the whole batch into fixed-shape limb arrays and
issues ONE device dispatch.  Shape-bucketing replaces the reference's
chunkify-at-128 policy (multithread/index.ts:39): batches are padded up to
the next bucket size so XLA compiles a handful of programs, once.

Host responsibilities (cheap, byte-oriented):
- aggregate pubkeys per set (jacobian sum, mirroring chain/bls/utils.ts:5),
- decompress signature bytes (sqrt via bigint pow — microseconds each;
  subgroup checks stay ON DEVICE where they are batched),
- sha256 expand_message / hash_to_field draws,
- sample fresh odd 64-bit RLC coefficients per dispatch.

Device responsibilities: everything algebraic (see batch_verify.py).

Round-6 pipeline split: ``verify_signature_sets`` is now sugar over three
explicit stages —

    packed  = verifier.pack(sets)          # host, numpy-vectorized
    pending = verifier.dispatch(packed)    # device enqueue, NO sync
    ok      = pending.result()             # readback + host final exp

``jax.jit`` dispatch is asynchronous, so ``dispatch`` returns before the
device finishes; a scheduling layer (chain/bls_pool.BlsBatchPool) keeps
2-3 batches in flight, packing batch N+1 and finishing batch N-1's host
final exponentiation while batch N computes.  AOT warmup and the
persistent-compilation-cache wiring live HERE (``warmup`` /
``configure_persistent_cache``) so a node's first block import doesn't
eat a cold Mosaic/XLA compile — bench.py and cli.py both call in.
"""

from __future__ import annotations

import os
import secrets
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from ...forensics.journal import JOURNAL, install_jax_monitoring
from ...forensics.watchdog import INFLIGHT
from ...observatory.compile_ledger import COMPILE_LEDGER
from ...ops import batch_verify as bv
from ...ops import htc
from ...ops import limbs as fl
from ...tracing import TRACER, current_batch_id
from ...utils.logger import get_logger
from .curve import g2_from_bytes, to_affine_batch
from .verifier import (
    PointCache,
    SignatureSet,
    SingleSignatureSet,
    get_aggregated_pubkey,
)

logger = get_logger("tpu-verifier")


def _fused_default() -> bool:
    """The fused Pallas dispatch is the production path on real TPUs; the
    XLA-graph kernels remain the portable path (CPU tests, sharded dryrun).
    LODESTAR_TPU_FUSED=0/1 overrides."""
    env = os.environ.get("LODESTAR_TPU_FUSED")
    if env is not None:
        return env not in ("0", "false", "no")
    import jax

    return jax.default_backend() == "tpu"


_CACHE_CONFIGURED = False


def configure_persistent_cache(
    cache_dir: Optional[str] = None, min_compile_secs: float = 1.0
) -> str:
    """Wire the persistent XLA compilation cache (idempotent).

    The batched-verify programs cost minutes of TPU compile cold; the
    cache brings a process restart down to seconds.  Lived in bench.py
    until round 6 — but the node pays the same cold compile on its first
    block import, so the wiring belongs to the verifier.  Resolution:
    explicit arg > LODESTAR_TPU_JAX_CACHE env > repo-local .jax_cache.
    """
    global _CACHE_CONFIGURED
    if cache_dir is None:
        cache_dir = os.environ.get("LODESTAR_TPU_JAX_CACHE")
    if cache_dir is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        )
        cache_dir = os.path.join(repo, ".jax_cache")
    if not _CACHE_CONFIGURED:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile_secs)
        # flight recorder: compile/cache-load durations land in the
        # always-on journal, so a wedged/cold compile is visible in any
        # diagnostic bundle (the evidence BENCH_r05 died without)
        install_jax_monitoring(JOURNAL)
        # performance observatory: the same monitoring feed also keeps
        # the persistent compile ledger (cold/warm_load/hit per entry ×
        # bucket × device), stored next to the executables it describes
        COMPILE_LEDGER.configure(cache_dir=cache_dir).install()
        _CACHE_CONFIGURED = True
    return cache_dir


# Padding buckets: smallest program that fits the batch gets used.  128
# mirrors MAX_SIGNATURE_SETS_PER_JOB (multithread/index.ts:39); larger
# buckets let sync batches amortize the dispatch.
DEFAULT_BUCKETS = (4, 16, 64, 128, 256)


def _entry_name(key) -> str:
    """Compile-ledger entry label for a (n, host_final_exp, fused)
    program key: which of the 4 public kernels this program is."""
    _n, host_final_exp, fused = key
    if fused:
        return "fused_split" if host_final_exp else "fused_full"
    return "xla_split" if host_final_exp else "xla_full"


#: Process-level program memo: (program key, device identity) -> compiled
#: callable.  The compile ledger surfaced the cost this kills: every
#: fresh ``TpuBlsVerifier`` built fresh ``jax.jit`` wrappers, so a
#: re-instantiated verifier (fallback-tier rebuilds, tests, a node
#: restarting its pool) re-paid trace + lower + a ~25s persistent-cache
#: LOAD per program — for bytes-identical executables already live in
#: this process.  The memo shares the wrapper (and any AOT executable
#: warmup() built) across instances; per-executor ``compiled`` dicts
#: still take precedence, so tests that inject stub programs are
#: unaffected, and ``close()`` keeps its per-instance semantics.
_PROGRAM_MEMO: dict = {}
_PROGRAM_MEMO_LOCK = threading.Lock()


class PendingVerdict:
    """A dispatched batch whose verdict has not been read back.

    Construction never blocks: the device work is already enqueued (jax
    dispatch is async) and ``result()`` performs the only synchronization
    — the device readback plus, on the split path, the host C final
    exponentiation.  ``result()`` is idempotent (the verdict is cached).

    ``release`` is the scheduler's in-flight slot return: called exactly
    once when the first ``result()`` completes, so the least-loaded
    placement sees the device free again."""

    __slots__ = ("_verifier", "_f", "_ok", "_out", "_value", "_parts", "_release",
                 "device", "deadline")

    def __init__(self, verifier=None, f=None, ok=None, out=None, value=None,
                 parts=None, release=None, device=None, deadline=None):
        self._verifier = verifier
        self._f = f
        self._ok = ok
        self._out = out
        self._value = value
        self._parts = parts
        self._release = release
        self.device = device  # executor name the batch landed on (None for chunked)
        self.deadline = deadline  # tightest job deadline riding this batch

    def done_hint(self) -> bool:
        """True once the verdict is cached (no sync performed)."""
        return self._value is not None

    def result(self) -> bool:
        if self._value is None:
            try:
                if self._parts is not None:
                    results = [p.result() for p in self._parts]
                    self._value = all(results)
                elif self._f is not None:
                    self._value = self._verifier._host_final_exp_verdict(self._f, self._ok)
                else:
                    # fused on-device verdict: the bool() read is the sync; the
                    # span plays the final_exp role on this path's timeline
                    t0_ns = TRACER.now()
                    self._value = bool(self._out)
                    if TRACER.enabled:
                        TRACER.add_span(
                            "bls.final_exp", "bls", t0_ns,
                            cid=current_batch_id(), on_device=True,
                        )
            finally:
                release, self._release = self._release, None
                if release is not None:
                    release()
        return self._value


class DeviceExecutor:
    """One chip's slice of the verifier: its own compiled programs (keyed
    like the old single-device cache) plus an in-flight batch counter the
    scheduler reads for least-loaded placement.

    Each executor's programs are plain single-device ``jax.jit(...,
    device=d)`` compilations — the fused Pallas kernels stay single-chip
    programs (no Mosaic cross-chip lowering risk), and any bucket size
    runs on any device count because batches are never sharded, only
    placed."""

    __slots__ = ("device", "index", "name", "inflight", "compiled")

    def __init__(self, device=None, index: int = 0):
        self.device = device  # None = default backend device (unpinned jit)
        self.index = index
        self.name = (
            f"{device.platform}:{device.id}" if device is not None else "default"
        )
        self.inflight = 0
        self.compiled = {}


class TpuBlsVerifier:
    """Batched device verifier behind the IBlsVerifier boundary.

    ``platform=None`` uses the default JAX backend (TPU when present);
    tests pin ``platform='cpu'``.

    Round-4 split dispatch (``host_final_exp=True``, the default): the
    device runs only the batch-parallel stages and returns the Miller
    product; the host finishes with the native C final exponentiation
    (csrc/fastbls.c — ~2 ms vs ~145 ms of serial device scan latency;
    see ops/batch_verify.miller_product_kernel).  The pure-Python oracle
    is the automatic fallback when the C toolchain is absent, and
    ``host_final_exp=False`` restores the single fused device program.

    Multi-chip scale-out (``devices=[...]``, round-8): a ``DeviceExecutor``
    per chip, each holding its own AOT-compiled programs, and a throughput
    scheduler in ``dispatch()`` that places each whole packed batch on the
    least-loaded device (round-robin tie-break).  This replaces the old
    mesh-sharding-one-batch design: kernels stay single-chip programs, any
    bucket works on any device count, and the pipeline depth multiplies by
    ``n_devices`` (chain/bls_pool keeps ``pipeline_depth`` batches in
    flight PER DEVICE).  Oversized batches chunk at ``buckets[-1]`` and
    fan out across the pool (verify_signature_sets_async).

    Pack-side caches (the Amdahl serial-stage attack): ``point_cache_size``
    bounds an LRU of decompressed/affine points keyed by compressed bytes
    (signatures, single pubkeys, and committee aggregates keyed by their
    member bytes), and the remaining jacobian->affine conversions batch
    through one Montgomery inversion per pack (curve.to_affine_batch)
    instead of one bigint inversion per set.

    ``metrics``: optional Metrics registry; per-stage histograms
    (bls_pool_pack_seconds / bls_pool_dispatch_seconds is pool-side /
    bls_pool_final_exp_seconds) are observed when present.  The plain
    ``stage_seconds`` dict accumulates the same figures unconditionally.
    """

    def __init__(
        self,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        platform: Optional[str] = None,
        devices: Optional[Sequence] = None,
        host_final_exp: bool = True,
        fused: Optional[bool] = None,
        metrics=None,
        point_cache_size: int = 8192,
    ):
        self.buckets = tuple(sorted(buckets))
        self.platform = platform
        self.devices = list(devices) if devices else None
        self.host_final_exp = host_final_exp
        # round-5: the fused Pallas kernel path (ops/fused_verify) — the
        # production dispatch on TPU; resolved lazily so constructing a
        # verifier never touches a JAX backend.
        self.fused = fused
        self.metrics = metrics
        # one executor per device; a single default executor otherwise
        # (its device is resolved lazily at first jit so constructing a
        # verifier still never touches a JAX backend)
        if self.devices:
            self._executors = [
                DeviceExecutor(d, i) for i, d in enumerate(self.devices)
            ]
        else:
            self._executors = [DeviceExecutor(None, 0)]
        self._sched_lock = threading.Lock()
        self._rr = 0  # round-robin tie-break cursor
        self.point_cache = PointCache(point_cache_size)
        # stats lock: the counters below are mutated from asyncio.to_thread
        # pack/result workers AND the warmup daemon thread concurrently
        # (the PR-3 race surface the lock audit pins) — every write goes
        # through this leaf lock (never held across another lock or any
        # device work)
        self._stats_lock = threading.Lock()
        # pool-style counters (metrics parity with blsThreadPool.*,
        # metrics/metrics/lodestar.ts:385)
        self.dispatches = 0
        self.sets_verified = 0
        self.padding_wasted = 0
        self.host_final_exps = 0
        self.fused_fallbacks = 0
        self.pack_rejected = 0
        self.pack_cache_hits = 0
        self.pack_cache_misses = 0
        self.stage_seconds = {"pack": 0.0, "dispatch": 0.0, "final_exp": 0.0, "warmup": 0.0}

    @property
    def n_devices(self) -> int:
        return len(self._executors)

    @property
    def _compiled(self):
        """Primary executor's program cache — kept under the historical
        name for callers/tests that inspect it."""
        return self._executors[0].compiled

    def device_inflight(self):
        """Snapshot of per-device in-flight batch counts (debug API)."""
        return {ex.name: ex.inflight for ex in self._executors}

    # -- compilation cache ---------------------------------------------------

    def _resolve_fused(self) -> bool:
        if self.fused is None:
            self.fused = _fused_default()
        return self.fused

    def _kernel(self, key):
        """Python kernel callable for a (n, host_final_exp, fused) key."""
        n, host_final_exp, fused = key
        if fused:
            from ...ops import fused_verify as fv

            if host_final_exp:
                def kernel(*args):
                    f, ok = fv.miller_product_fused(*args, interpret=False)
                    return f.a, ok
            else:
                def kernel(*args):
                    return fv.verify_signature_sets_fused(*args, interpret=False)
            return kernel
        return (
            bv.miller_product_kernel if host_final_exp
            else bv.verify_signature_sets_kernel
        )

    def _jit(self, key, executor: DeviceExecutor):
        import jax

        kernel = self._kernel(key)
        device = executor.device
        if device is None and self.platform is not None:
            device = jax.devices(self.platform)[0]
        if device is not None:
            return jax.jit(kernel, device=device)
        return jax.jit(kernel)

    def _memo_key(self, key, executor: DeviceExecutor):
        """Device identity for the process-level memo: a pinned executor
        keys by (platform, ordinal); an unpinned one by the verifier's
        platform request (its device resolves deterministically)."""
        d = executor.device
        dev = (d.platform, d.id) if d is not None else ("platform", self.platform)
        return (key, dev)

    def _fn(self, n: int, fused: Optional[bool] = None,
            executor: Optional[DeviceExecutor] = None):
        key = (n, self.host_final_exp, self._resolve_fused() if fused is None else fused)
        ex = executor if executor is not None else self._executors[0]
        if key not in ex.compiled:
            mk = self._memo_key(key, ex)
            with _PROGRAM_MEMO_LOCK:
                fn = _PROGRAM_MEMO.get(mk)
            if fn is None:
                fn = self._jit(key, ex)
                with _PROGRAM_MEMO_LOCK:
                    fn = _PROGRAM_MEMO.setdefault(mk, fn)
            ex.compiled[key] = fn
        return ex.compiled[key]

    # -- scheduling -----------------------------------------------------------

    def _acquire_executor(self) -> DeviceExecutor:
        """Least-loaded placement with a rotating round-robin tie-break, so
        equal-load devices are fed in rotation rather than always device 0.
        The in-flight increment happens under the same lock as the pick —
        concurrent dispatch threads can't double-book a device."""
        with self._sched_lock:
            k = len(self._executors)
            if k == 1:
                ex = self._executors[0]
            else:
                start = self._rr
                self._rr = (self._rr + 1) % k
                ex = min(
                    (self._executors[(start + i) % k] for i in range(k)),
                    key=lambda e: e.inflight,
                )
            ex.inflight += 1
            inflight = ex.inflight
        if self.metrics:
            self.metrics.bls_device_inflight.labels(device=ex.name).set(inflight)
        return ex

    def _release_executor(self, ex: DeviceExecutor) -> None:
        with self._sched_lock:
            ex.inflight -= 1
            inflight = ex.inflight
        if self.metrics:
            self.metrics.bls_device_inflight.labels(device=ex.name).set(inflight)

    def _abstract_args(self, n: int):
        """ShapeDtypeStructs matching pack() output — AOT lowering inputs."""
        import jax
        import jax.numpy as jnp

        S = jax.ShapeDtypeStruct
        f32 = jnp.float32
        return (
            S((n, fl.NLIMBS), f32),
            S((n, fl.NLIMBS), f32),
            S((n, 2, fl.NLIMBS), f32),
            S((n, 2, fl.NLIMBS), f32),
            S((n, 2, 2, fl.NLIMBS), f32),
            S((n, 64), f32),
            S((n,), jnp.bool_),
        )

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> float:
        """AOT-compile the dispatch program for every bucket of the active
        path (``jit(...).lower(...).compile()``) on EVERY device executor,
        populating both the in-process executable caches and the persistent
        compilation cache.

        Returns the wall seconds spent.  A bucket whose compile FAILS
        (e.g. a Mosaic lowering bug in the fused path) degrades that
        verifier to the XLA-graph kernels instead of raising — the node
        must come up either way."""
        t0 = time.perf_counter()
        for b in tuple(buckets if buckets is not None else self.buckets):
            key = (b, self.host_final_exp, self._resolve_fused())
            for ex in self._executors:
                if key in ex.compiled and not hasattr(ex.compiled[key], "lower"):
                    continue  # already an AOT executable
                mk = self._memo_key(key, ex)
                with _PROGRAM_MEMO_LOCK:
                    memo_fn = _PROGRAM_MEMO.get(mk)
                if memo_fn is not None and not hasattr(memo_fn, "lower"):
                    # another verifier instance already AOT-compiled this
                    # exact program for this device in this process
                    ex.compiled[key] = memo_fn
                    continue
                try:
                    # ledger attribution: the monitoring events this
                    # compile fires land on (entry, bucket, device) and
                    # classify as cold vs persistent-cache warm load
                    with COMPILE_LEDGER.attribute(
                        _entry_name(key), bucket=b, device=ex.name
                    ):
                        ex.compiled[key] = self._jit(key, ex).lower(
                            *self._abstract_args(b)
                        ).compile()
                    with _PROGRAM_MEMO_LOCK:
                        _PROGRAM_MEMO[mk] = ex.compiled[key]
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "warmup compile failed for bucket %d on %s: %s",
                        b, ex.name, e,
                    )
                    if self.fused:
                        logger.warning("degrading to XLA-graph kernels (fused=False)")
                        JOURNAL.record(
                            "bls.degrade", level="WARNING", where="warmup",
                            bucket=b, device=ex.name, error=str(e)[:300],
                        )
                        self.fused = False
                        with self._stats_lock:
                            self.fused_fallbacks += 1
                        for e2 in self._executors:
                            e2.compiled.pop(key, None)
                            with _PROGRAM_MEMO_LOCK:
                                _PROGRAM_MEMO.pop(self._memo_key(key, e2), None)
                        return self.warmup(buckets) + (time.perf_counter() - t0)
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self.stage_seconds["warmup"] += dt
        if TRACER.enabled:
            TRACER.instant("bls.warmup_done", cat="bls", seconds=round(dt, 3),
                           devices=self.n_devices)
        JOURNAL.record("bls.warmup", seconds=round(dt, 3),
                       devices=self.n_devices, fused=self.fused)
        return dt

    def warmup_async(self, buckets: Optional[Sequence[int]] = None) -> threading.Thread:
        """warmup() on a daemon thread — lets a node serve imports through
        the (slow but correct) cold path while programs compile."""
        t = threading.Thread(target=self.warmup, args=(buckets,), daemon=True,
                             name="tpu-bls-warmup")
        t.start()
        return t

    def _host_final_exp_verdict(self, f_digits, ok) -> bool:
        """Reduce the device Miller product to canonical bytes and run the
        final exponentiation + is-one check on the host (native C first,
        bigint oracle as fallback).  The ``bool(ok)`` read is the device
        sync point, so this stage's timing covers readback + final exp."""
        t0 = time.perf_counter()
        t0_ns = TRACER.now()
        try:
            if not bool(ok):
                return False
            with self._stats_lock:
                self.host_final_exps += 1
            f = np.asarray(f_digits, dtype=np.float64)  # (6, 2, 50)
            comps = []
            for i in range(6):
                for j in range(2):
                    comps.append(fl.limbs_to_int(f[i, j]) % fl.P_INT)
            blob = b"".join(c.to_bytes(48, "big") for c in comps)
            from ...native import fastbls

            out = fastbls.final_exp_is_one(blob)
            if out is not None:
                return bool(out)
            # oracle fallback: same verdict via bigint final exponentiation
            from .fields import Fq2, Fq6, Fq12
            from .pairing import final_exponentiation

            fq12 = Fq12(
                Fq6(Fq2(*comps[0:2]), Fq2(*comps[2:4]), Fq2(*comps[4:6])),
                Fq6(Fq2(*comps[6:8]), Fq2(*comps[8:10]), Fq2(*comps[10:12])),
            )
            return final_exponentiation(fq12).is_one()
        finally:
            dt = time.perf_counter() - t0
            with self._stats_lock:
                self.stage_seconds["final_exp"] += dt
            if self.metrics:
                self.metrics.bls_pool_final_exp_seconds.observe(dt)
                self.metrics.bls_verifier_stage_duration_seconds.labels(
                    stage="final_exp"
                ).observe(dt)
            if TRACER.enabled:
                TRACER.add_span("bls.final_exp", "bls", t0_ns,
                                cid=current_batch_id())

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # -- IBlsVerifier --------------------------------------------------------

    def verify_signature_sets(self, sets: Sequence[SignatureSet]) -> bool:
        return self.verify_signature_sets_async(sets).result()

    def verify_signature_sets_async(
        self, sets: Sequence[SignatureSet], deadline: Optional[float] = None
    ) -> PendingVerdict:
        """Pack + enqueue without waiting for the device: the returned
        handle's ``result()`` is the only sync.  Oversized batches chunk
        at the largest bucket with every chunk enqueued back-to-back —
        chunk N+1's pack overlaps chunk N's device time even on the
        single-caller path, and on a multi-device pool the scheduler fans
        the chunks out round-robin across the executors.

        ``deadline`` (absolute ``time.monotonic()``, optional) is the
        tightest job deadline riding the batch — the scheduling layer
        (chain/bls_pool) sheds expired jobs before packing, so by the
        time a deadline reaches here it is informational: dispatch
        records it in the journal and the in-flight table so a stalled
        batch's bundle can say whether its work was already worthless.

        An empty batch is a caller bug, not a verification failure — the
        reference throws (multithread/index.ts verifySignatureSets), and a
        silent False verdict here would poison retry-individually logic
        upstream."""
        if not sets:
            raise ValueError("verify_signature_sets_async: empty batch of signature sets")
        largest = self.buckets[-1]
        if len(sets) > largest:
            # split oversized batches (chunkify analog, multithread/utils.ts:4)
            parts = [
                self.verify_signature_sets_async(sets[i : i + largest], deadline)
                for i in range(0, len(sets), largest)
            ]
            return PendingVerdict(parts=parts, deadline=deadline)
        packed = self.pack(sets)
        if packed is None:
            return PendingVerdict(value=False)  # malformed bytes / infinity
        return self.dispatch(packed, deadline=deadline)

    def dispatch(self, packed, deadline: Optional[float] = None) -> PendingVerdict:
        """Place one packed batch on the least-loaded device executor and
        enqueue it — returns immediately (the jax dispatch is
        asynchronous; compile, if cold, is not).  The executor's in-flight
        slot is held until the verdict's first ``result()`` completes, so
        back-to-back dispatches (chunked range-sync batches, pipelined
        pool flushes) spread across the device pool.

        A compile failure on the fused path (Mosaic lowering) degrades
        this verifier to the XLA-graph kernels and retries once — a bad
        kernel must not take block import down with it."""
        live = int(np.sum(np.asarray(packed[6])))
        with self._stats_lock:
            self.dispatches += 1
            self.sets_verified += live
        n = packed[0].shape[0]
        t0_ns = TRACER.now()
        # snapshot the path THIS call uses: a concurrent warmup_async thread
        # may degrade self.fused mid-flight, and the except arm must judge
        # the path that actually raised, not the flag's latest value
        used_fused = self._resolve_fused()
        ex = self._acquire_executor()
        t_disp = time.perf_counter()
        try:
            try:
                # ledger attribution: a first-call compile classifies as
                # cold/warm_load; an already-live program records an
                # in-process hit — the three-way split the cold-start
                # baseline (ROADMAP item 4) is measured against
                with COMPILE_LEDGER.attribute(
                    _entry_name((n, self.host_final_exp, used_fused)),
                    bucket=n, device=ex.name,
                ):
                    out = self._fn(n, fused=used_fused, executor=ex)(*packed)
            except Exception as e:  # noqa: BLE001
                if not used_fused:
                    raise
                logger.warning("fused dispatch failed (%s); degrading to XLA kernels", e)
                JOURNAL.record(
                    "bls.degrade", level="WARNING", where="dispatch",
                    bucket=n, device=ex.name, error=str(e)[:300],
                )
                self.fused = False
                with self._stats_lock:
                    self.fused_fallbacks += 1
                # drop the broken fused program from the process memo so
                # a later verifier retries it fresh (status-quo per-
                # instance behavior) instead of inheriting the failure
                with _PROGRAM_MEMO_LOCK:
                    _PROGRAM_MEMO.pop(
                        self._memo_key((n, self.host_final_exp, True), ex), None
                    )
                with COMPILE_LEDGER.attribute(
                    _entry_name((n, self.host_final_exp, False)),
                    bucket=n, device=ex.name,
                ):
                    out = self._fn(n, fused=False, executor=ex)(*packed)
        except Exception:
            self._release_executor(ex)
            raise
        dt_disp = time.perf_counter() - t_disp
        with self._stats_lock:
            self.stage_seconds["dispatch"] += dt_disp
        if self.metrics:
            self.metrics.bls_verifier_stage_duration_seconds.labels(
                stage="dispatch"
            ).observe(dt_disp)
        cid = current_batch_id()
        if TRACER.enabled:
            # covers the async enqueue only (plus compile when cold); the
            # device compute itself surfaces as the gap before final_exp.
            # device/devices_total let tools/check_trace.py assert a
            # multi-device dump actually spread across the pool
            TRACER.add_span("bls.dispatch", "bls", t0_ns,
                            cid=cid, bucket=n, fused=used_fused,
                            device=ex.name, devices_total=self.n_devices)
        # flight recorder: placement decision into the black box, the
        # batch into the in-flight table the watchdog scans — resolved by
        # the same exactly-once path that returns the executor slot, so a
        # verdict that never syncs leaves a stall-shaped entry behind.
        # The remaining deadline headroom (seconds, negative = already
        # expired) rides both records: a stall bundle can then say whether
        # the wedged work was still worth anything.
        headroom = None
        if deadline is not None:
            headroom = round(deadline - time.monotonic(), 3)
        if JOURNAL.enabled:
            JOURNAL.record("bls.dispatch", cid=cid, device=ex.name, bucket=n,
                           sets=live, fused=used_fused,
                           inflight=ex.inflight, devices_total=self.n_devices,
                           deadline_headroom_s=headroom)
        token = INFLIGHT.register(cid=cid, device=ex.name, bucket=n, sets=live,
                                  deadline_s=headroom)

        def release():
            INFLIGHT.resolve(token)
            self._release_executor(ex)

        if self.host_final_exp:
            f, ok = out
            return PendingVerdict(verifier=self, f=f, ok=ok, release=release,
                                  device=ex.name, deadline=deadline)
        return PendingVerdict(verifier=self, out=out, release=release,
                              device=ex.name, deadline=deadline)

    def close(self) -> None:
        for ex in self._executors:
            ex.compiled.clear()

    # -- packing -------------------------------------------------------------

    def _pack_reject(self):
        """Accounting for a rejected batch (malformed bytes / infinity):
        only the rejection counter moves — padding and the pack histogram
        count successful packs exclusively (a rejected batch never
        dispatches, so its padding was never 'wasted' on a device)."""
        with self._stats_lock:
            self.pack_rejected += 1
        if self.metrics:
            self.metrics.bls_pack_rejected_total.inc()
        return None

    def pack(self, sets: Sequence[SignatureSet]):
        """Host packing stage, numpy-vectorized: ONE bulk byte->limb
        conversion per coordinate family (ops/limbs.ints_to_limbs) and a
        vectorized RLC bit expansion instead of per-element/per-bit Python
        loops.  Returns the 7-tuple of device-ready arrays, or None when
        any set is malformed (infinity pubkey/signature, bad bytes).

        Round-8 serial-stage attack: affine coordinates come from the
        ``point_cache`` LRU (keyed by compressed signature bytes, single
        pubkey bytes, or an aggregate's concatenated member bytes) and the
        misses convert jacobian->affine through ONE Montgomery batch
        inversion per family (curve.to_affine_batch) instead of one bigint
        inversion per set."""
        t0 = time.perf_counter()
        t0_ns = TRACER.now()
        hits = misses = 0
        try:
            n = len(sets)
            b = self._bucket(n)
            cache = self.point_cache
            pk_vals: List[Optional[tuple]] = [None] * n
            sig_vals: List[Optional[tuple]] = [None] * n
            pk_miss: List[tuple] = []   # (index, jacobian point, cache key | None)
            sig_miss: List[tuple] = []
            msgs: List[bytes] = []
            for i, s in enumerate(sets):
                # -- pubkey: single keys cache by their compressed bytes,
                #    aggregates by the concatenation of member bytes (the
                #    same committee re-aggregates every epoch) -------------
                if isinstance(s, SingleSignatureSet):
                    pk_key = s.pubkey._raw
                    if pk_key is not None:
                        pk_key = b"P" + pk_key
                elif cache.enabled:
                    pk_key = b"A" + b"".join(m.to_bytes() for m in s.pubkeys)
                else:
                    pk_key = None
                hit = cache.get(pk_key) if pk_key is not None else None
                if hit is not None:
                    pk_vals[i] = hit
                    hits += 1
                else:
                    misses += 1
                    pk = get_aggregated_pubkey(s)
                    if pk.is_infinity():
                        return self._pack_reject()
                    pk_miss.append((i, pk.point, pk_key))
                # -- signature --------------------------------------------
                raw = s.signature
                hit = cache.get(b"S" + raw) if cache.enabled else None
                if hit is not None:
                    sig_vals[i] = hit
                    hits += 1
                else:
                    misses += 1
                    try:
                        # on-curve guaranteed by sqrt decompression; subgroup
                        # check happens on device (batched)
                        sig_pt = g2_from_bytes(raw, subgroup_check=False)
                    except ValueError:
                        return self._pack_reject()
                    if sig_pt.is_infinity():
                        return self._pack_reject()
                    sig_miss.append((i, sig_pt, b"S" + raw))
                msgs.append(s.signing_root)
            # one Montgomery batch inversion per coordinate family
            for aff, missed in (
                (to_affine_batch([pt for _, pt, _ in pk_miss]), pk_miss),
                (to_affine_batch([pt for _, pt, _ in sig_miss]), sig_miss),
            ):
                for (i, _pt, key), xy in zip(missed, aff):
                    x, y = xy
                    if hasattr(x, "n"):  # Fq (G1 pubkey)
                        val = (x.n, y.n)
                        pk_vals[i] = val
                    else:  # Fq2 (G2 signature)
                        val = (x.c0, x.c1, y.c0, y.c1)
                        sig_vals[i] = val
                    if key is not None:
                        cache.put(key, val)
            pk_ints: List[int] = [c for v in pk_vals for c in v]
            sig_ints: List[int] = [c for v in sig_vals for c in v]
            # one batched byte->limb conversion per family
            pk_limbs = fl.ints_to_limbs(pk_ints).reshape(n, 2, fl.NLIMBS)
            sig_limbs = fl.ints_to_limbs(sig_ints).reshape(n, 2, 2, fl.NLIMBS)
            pk_x = np.zeros((b, fl.NLIMBS), dtype=fl.NP_DTYPE)
            pk_y = np.zeros((b, fl.NLIMBS), dtype=fl.NP_DTYPE)
            sig_x = np.zeros((b, 2, fl.NLIMBS), dtype=fl.NP_DTYPE)
            sig_y = np.zeros((b, 2, fl.NLIMBS), dtype=fl.NP_DTYPE)
            pk_x[:n], pk_y[:n] = pk_limbs[:, 0], pk_limbs[:, 1]
            sig_x[:n], sig_y[:n] = sig_limbs[:, 0], sig_limbs[:, 1]
            # padding lanes: copy lane 0 (valid coords keep the algebra
            # non-degenerate; the mask keeps them out of the verdict)
            if b > n:
                pk_x[n:], pk_y[n:] = pk_x[0], pk_y[0]
                sig_x[n:], sig_y[n:] = sig_x[0], sig_y[0]
                msgs += [b""] * (b - n)
            msg_u = htc.hash_to_field_limbs(msgs)
            # fresh odd 64-bit RLC coefficients, expanded to bit planes in
            # one vectorized shift instead of a per-(coeff, bit) Python loop
            coeffs = np.frombuffer(secrets.token_bytes(8 * b), dtype=np.uint64)
            coeffs = coeffs | np.uint64(1)
            bits = (
                (coeffs[:, None] >> np.arange(64, dtype=np.uint64)[None, :])
                & np.uint64(1)
            ).astype(fl.NP_DTYPE)
            mask = np.zeros(b, dtype=bool)
            mask[:n] = True
            # padding counts only for batches that will actually dispatch
            with self._stats_lock:
                self.padding_wasted += b - n
            if self.metrics:
                self.metrics.bls_pool_pack_seconds.observe(time.perf_counter() - t0)
            return (pk_x, pk_y, sig_x, sig_y, msg_u, bits, mask)
        finally:
            dt = time.perf_counter() - t0
            with self._stats_lock:
                self.stage_seconds["pack"] += dt
                self.pack_cache_hits += hits
                self.pack_cache_misses += misses
            if self.metrics:
                self.metrics.bls_verifier_stage_duration_seconds.labels(
                    stage="pack"
                ).observe(dt)
                if hits:
                    self.metrics.bls_pack_cache_hits_total.inc(hits)
                if misses:
                    self.metrics.bls_pack_cache_misses_total.inc(misses)
            if TRACER.enabled:
                TRACER.add_span("bls.pack", "bls", t0_ns,
                                cid=current_batch_id(), sets=len(sets),
                                cache_hits=hits)

    # kept for callers/tests that used the private name
    _pack = pack
