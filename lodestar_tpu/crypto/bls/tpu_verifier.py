"""TpuBlsVerifier — the IBlsVerifier implementation backed by the batched
JAX kernel (lodestar_tpu.ops.batch_verify).

This is the replacement for the reference's BlsMultiThreadWorkerPool
(packages/beacon-node/src/chain/bls/multithread/index.ts:98): instead of
shipping serialized {pubkey, message, signature} triples to N worker
threads, the host packs the whole batch into fixed-shape limb arrays and
issues ONE device dispatch.  Shape-bucketing replaces the reference's
chunkify-at-128 policy (multithread/index.ts:39): batches are padded up to
the next bucket size so XLA compiles a handful of programs, once.

Host responsibilities (cheap, byte-oriented):
- aggregate pubkeys per set (jacobian sum, mirroring chain/bls/utils.ts:5),
- decompress signature bytes (sqrt via bigint pow — microseconds each;
  subgroup checks stay ON DEVICE where they are batched),
- sha256 expand_message / hash_to_field draws,
- sample fresh odd 64-bit RLC coefficients per dispatch.

Device responsibilities: everything algebraic (see batch_verify.py).
"""

from __future__ import annotations

import os
import secrets
from typing import Optional, Sequence

import numpy as np

from ...ops import batch_verify as bv
from ...ops import htc
from ...ops import limbs as fl
from ...ops import tower as tw
from .curve import g2_from_bytes
from .verifier import SignatureSet, get_aggregated_pubkey


def _fused_default() -> bool:
    """The fused Pallas dispatch is the production path on real TPUs; the
    XLA-graph kernels remain the portable path (CPU tests, sharded dryrun).
    LODESTAR_TPU_FUSED=0/1 overrides."""
    env = os.environ.get("LODESTAR_TPU_FUSED")
    if env is not None:
        return env not in ("0", "false", "no")
    import jax

    return jax.default_backend() == "tpu"

# Padding buckets: smallest program that fits the batch gets used.  128
# mirrors MAX_SIGNATURE_SETS_PER_JOB (multithread/index.ts:39); larger
# buckets let sync batches amortize the dispatch.
DEFAULT_BUCKETS = (4, 16, 64, 128, 256)


class TpuBlsVerifier:
    """Batched device verifier behind the IBlsVerifier boundary.

    ``platform=None`` uses the default JAX backend (TPU when present);
    tests pin ``platform='cpu'``.

    Round-4 split dispatch (``host_final_exp=True``, the default): the
    device runs only the batch-parallel stages and returns the Miller
    product; the host finishes with the native C final exponentiation
    (csrc/fastbls.c — ~2 ms vs ~145 ms of serial device scan latency;
    see ops/batch_verify.miller_product_kernel).  The pure-Python oracle
    is the automatic fallback when the C toolchain is absent, and
    ``host_final_exp=False`` restores the single fused device program.

    Multi-device scale-out (``devices=[...]``): the batch axis is sharded
    over a 1-D jax.sharding.Mesh, the ICI data-parallel story of SURVEY
    §2.10 item 1 — production dispatch, not just the dryrun demo.  Buckets
    that don't divide evenly fall back to single-device dispatch.
    """

    def __init__(
        self,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        platform: Optional[str] = None,
        devices: Optional[Sequence] = None,
        host_final_exp: bool = True,
        fused: Optional[bool] = None,
    ):
        self.buckets = tuple(sorted(buckets))
        self.platform = platform
        self.devices = list(devices) if devices else None
        self.host_final_exp = host_final_exp
        # round-5: the fused Pallas kernel path (ops/fused_verify) — the
        # production dispatch on TPU; resolved lazily so constructing a
        # verifier never touches a JAX backend.
        self.fused = fused
        self._compiled = {}
        # pool-style counters (metrics parity with blsThreadPool.*,
        # metrics/metrics/lodestar.ts:385)
        self.dispatches = 0
        self.sets_verified = 0
        self.padding_wasted = 0
        self.host_final_exps = 0

    # -- compilation cache ---------------------------------------------------

    def _fn(self, n: int):
        if self.fused is None:
            self.fused = _fused_default()
        key = (n, self.host_final_exp, self.fused)
        if key not in self._compiled:
            import jax

            if self.fused:
                from ...ops import fused_verify as fv

                if self.host_final_exp:
                    def kernel(*args):
                        f, ok = fv.miller_product_fused(*args, interpret=False)
                        return f.a, ok
                else:
                    def kernel(*args):
                        return fv.verify_signature_sets_fused(*args, interpret=False)
            else:
                kernel = (
                    bv.miller_product_kernel if self.host_final_exp
                    else bv.verify_signature_sets_kernel
                )
            if self.devices and len(self.devices) > 1 and n % len(self.devices) == 0:
                from jax.sharding import Mesh, NamedSharding, PartitionSpec

                # the multi-device dispatch stays on the XLA-graph kernels:
                # the batch axis shards cleanly there, while the fused
                # path's merged ladders are single-chip programs
                kernel = (
                    bv.miller_product_kernel if self.host_final_exp
                    else bv.verify_signature_sets_kernel
                )
                mesh = Mesh(np.array(self.devices), ("sets",))
                batch = NamedSharding(mesh, PartitionSpec("sets"))
                fn = jax.jit(kernel, in_shardings=(batch,) * 7)
            elif self.platform is not None:
                device = jax.devices(self.platform)[0]
                fn = jax.jit(kernel, device=device)
            else:
                fn = jax.jit(kernel)
            self._compiled[key] = fn
        return self._compiled[key]

    def _host_final_exp_verdict(self, f_digits, ok) -> bool:
        """Reduce the device Miller product to canonical bytes and run the
        final exponentiation + is-one check on the host (native C first,
        bigint oracle as fallback)."""
        if not bool(ok):
            return False
        self.host_final_exps += 1
        f = np.asarray(f_digits, dtype=np.float64)  # (6, 2, 50)
        comps = []
        for i in range(6):
            for j in range(2):
                comps.append(fl.limbs_to_int(f[i, j]) % fl.P_INT)
        blob = b"".join(c.to_bytes(48, "big") for c in comps)
        from ...native import fastbls

        out = fastbls.final_exp_is_one(blob)
        if out is not None:
            return bool(out)
        # oracle fallback: same verdict via bigint final exponentiation
        from .fields import Fq2, Fq6, Fq12
        from .pairing import final_exponentiation

        fq12 = Fq12(
            Fq6(Fq2(*comps[0:2]), Fq2(*comps[2:4]), Fq2(*comps[4:6])),
            Fq6(Fq2(*comps[6:8]), Fq2(*comps[8:10]), Fq2(*comps[10:12])),
        )
        return final_exponentiation(fq12).is_one()

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # -- IBlsVerifier --------------------------------------------------------

    def verify_signature_sets(self, sets: Sequence[SignatureSet]) -> bool:
        if not sets:
            return False
        largest = self.buckets[-1]
        # split oversized batches (chunkify analog, multithread/utils.ts:4)
        if len(sets) > largest:
            return all(
                self.verify_signature_sets(sets[i : i + largest])
                for i in range(0, len(sets), largest)
            )
        packed = self._pack(sets)
        if packed is None:
            return False  # malformed bytes / infinity inputs
        self.dispatches += 1
        self.sets_verified += len(sets)
        if self.host_final_exp:
            f, ok = self._fn(packed[0].shape[0])(*packed)
            return self._host_final_exp_verdict(f, ok)
        out = self._fn(packed[0].shape[0])(*packed)
        return bool(out)

    def close(self) -> None:
        self._compiled.clear()

    # -- packing -------------------------------------------------------------

    def _pack(self, sets: Sequence[SignatureSet]):
        n = len(sets)
        b = self._bucket(n)
        self.padding_wasted += b - n
        pk_x = np.zeros((b, fl.NLIMBS), dtype=fl.NP_DTYPE)
        pk_y = np.zeros((b, fl.NLIMBS), dtype=fl.NP_DTYPE)
        sig_x = np.zeros((b, 2, fl.NLIMBS), dtype=fl.NP_DTYPE)
        sig_y = np.zeros((b, 2, fl.NLIMBS), dtype=fl.NP_DTYPE)
        msgs = []
        for i, s in enumerate(sets):
            pk = get_aggregated_pubkey(s)
            if pk.is_infinity():
                return None
            try:
                # on-curve guaranteed by sqrt decompression; subgroup check
                # happens on device (batched)
                sig_pt = g2_from_bytes(s.signature, subgroup_check=False)
            except ValueError:
                return None
            if sig_pt.is_infinity():
                return None
            pk_aff = pk.point.to_affine()
            sig_aff = sig_pt.to_affine()
            pk_x[i] = fl.int_to_limbs(pk_aff[0].n)
            pk_y[i] = fl.int_to_limbs(pk_aff[1].n)
            sig_x[i] = tw.fq2_const(sig_aff[0])
            sig_y[i] = tw.fq2_const(sig_aff[1])
            msgs.append(s.signing_root)
        # padding lanes: copy lane 0 (valid coords keep the algebra
        # non-degenerate; the mask keeps them out of the verdict)
        for i in range(n, b):
            pk_x[i], pk_y[i] = pk_x[0], pk_y[0]
            sig_x[i], sig_y[i] = sig_x[0], sig_y[0]
            msgs.append(b"")
        msg_u = htc.hash_to_field_limbs(msgs)
        coeffs = [secrets.randbits(64) | 1 for _ in range(b)]
        bits = np.array(
            [[(c >> j) & 1 for j in range(64)] for c in coeffs], dtype=fl.NP_DTYPE
        )
        mask = np.zeros(b, dtype=bool)
        mask[:n] = True
        return (pk_x, pk_y, sig_x, sig_y, msg_u, bits, mask)
