"""TpuBlsVerifier — the IBlsVerifier implementation backed by the batched
JAX kernel (lodestar_tpu.ops.batch_verify).

This is the replacement for the reference's BlsMultiThreadWorkerPool
(packages/beacon-node/src/chain/bls/multithread/index.ts:98): instead of
shipping serialized {pubkey, message, signature} triples to N worker
threads, the host packs the whole batch into fixed-shape limb arrays and
issues ONE device dispatch.  Shape-bucketing replaces the reference's
chunkify-at-128 policy (multithread/index.ts:39): batches are padded up to
the next bucket size so XLA compiles a handful of programs, once.

Host responsibilities (cheap, byte-oriented):
- aggregate pubkeys per set (jacobian sum, mirroring chain/bls/utils.ts:5),
- decompress signature bytes (sqrt via bigint pow — microseconds each;
  subgroup checks stay ON DEVICE where they are batched),
- sha256 expand_message / hash_to_field draws,
- sample fresh odd 64-bit RLC coefficients per dispatch.

Device responsibilities: everything algebraic (see batch_verify.py).

Round-6 pipeline split: ``verify_signature_sets`` is now sugar over three
explicit stages —

    packed  = verifier.pack(sets)          # host, numpy-vectorized
    pending = verifier.dispatch(packed)    # device enqueue, NO sync
    ok      = pending.result()             # readback + host final exp

``jax.jit`` dispatch is asynchronous, so ``dispatch`` returns before the
device finishes; a scheduling layer (chain/bls_pool.BlsBatchPool) keeps
2-3 batches in flight, packing batch N+1 and finishing batch N-1's host
final exponentiation while batch N computes.  AOT warmup and the
persistent-compilation-cache wiring live HERE (``warmup`` /
``configure_persistent_cache``) so a node's first block import doesn't
eat a cold Mosaic/XLA compile — bench.py and cli.py both call in.
"""

from __future__ import annotations

import os
import secrets
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from ...ops import batch_verify as bv
from ...ops import htc
from ...ops import limbs as fl
from ...tracing import TRACER, current_batch_id
from ...utils.logger import get_logger
from .curve import g2_from_bytes
from .verifier import SignatureSet, get_aggregated_pubkey

logger = get_logger("tpu-verifier")


def _fused_default() -> bool:
    """The fused Pallas dispatch is the production path on real TPUs; the
    XLA-graph kernels remain the portable path (CPU tests, sharded dryrun).
    LODESTAR_TPU_FUSED=0/1 overrides."""
    env = os.environ.get("LODESTAR_TPU_FUSED")
    if env is not None:
        return env not in ("0", "false", "no")
    import jax

    return jax.default_backend() == "tpu"


_CACHE_CONFIGURED = False


def configure_persistent_cache(
    cache_dir: Optional[str] = None, min_compile_secs: float = 1.0
) -> str:
    """Wire the persistent XLA compilation cache (idempotent).

    The batched-verify programs cost minutes of TPU compile cold; the
    cache brings a process restart down to seconds.  Lived in bench.py
    until round 6 — but the node pays the same cold compile on its first
    block import, so the wiring belongs to the verifier.  Resolution:
    explicit arg > LODESTAR_TPU_JAX_CACHE env > repo-local .jax_cache.
    """
    global _CACHE_CONFIGURED
    if cache_dir is None:
        cache_dir = os.environ.get("LODESTAR_TPU_JAX_CACHE")
    if cache_dir is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        )
        cache_dir = os.path.join(repo, ".jax_cache")
    if not _CACHE_CONFIGURED:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile_secs)
        _CACHE_CONFIGURED = True
    return cache_dir


# Padding buckets: smallest program that fits the batch gets used.  128
# mirrors MAX_SIGNATURE_SETS_PER_JOB (multithread/index.ts:39); larger
# buckets let sync batches amortize the dispatch.
DEFAULT_BUCKETS = (4, 16, 64, 128, 256)


class PendingVerdict:
    """A dispatched batch whose verdict has not been read back.

    Construction never blocks: the device work is already enqueued (jax
    dispatch is async) and ``result()`` performs the only synchronization
    — the device readback plus, on the split path, the host C final
    exponentiation.  ``result()`` is idempotent (the verdict is cached).
    """

    __slots__ = ("_verifier", "_f", "_ok", "_out", "_value", "_parts")

    def __init__(self, verifier=None, f=None, ok=None, out=None, value=None, parts=None):
        self._verifier = verifier
        self._f = f
        self._ok = ok
        self._out = out
        self._value = value
        self._parts = parts

    def done_hint(self) -> bool:
        """True once the verdict is cached (no sync performed)."""
        return self._value is not None

    def result(self) -> bool:
        if self._value is None:
            if self._parts is not None:
                results = [p.result() for p in self._parts]
                self._value = all(results)
            elif self._f is not None:
                self._value = self._verifier._host_final_exp_verdict(self._f, self._ok)
            else:
                # fused on-device verdict: the bool() read is the sync; the
                # span plays the final_exp role on this path's timeline
                t0_ns = TRACER.now()
                self._value = bool(self._out)
                if TRACER.enabled:
                    TRACER.add_span(
                        "bls.final_exp", "bls", t0_ns,
                        cid=current_batch_id(), on_device=True,
                    )
        return self._value


class TpuBlsVerifier:
    """Batched device verifier behind the IBlsVerifier boundary.

    ``platform=None`` uses the default JAX backend (TPU when present);
    tests pin ``platform='cpu'``.

    Round-4 split dispatch (``host_final_exp=True``, the default): the
    device runs only the batch-parallel stages and returns the Miller
    product; the host finishes with the native C final exponentiation
    (csrc/fastbls.c — ~2 ms vs ~145 ms of serial device scan latency;
    see ops/batch_verify.miller_product_kernel).  The pure-Python oracle
    is the automatic fallback when the C toolchain is absent, and
    ``host_final_exp=False`` restores the single fused device program.

    Multi-device scale-out (``devices=[...]``): the batch axis is sharded
    over a 1-D jax.sharding.Mesh, the ICI data-parallel story of SURVEY
    §2.10 item 1 — production dispatch, not just the dryrun demo.  Buckets
    that don't divide evenly fall back to single-device dispatch.

    ``metrics``: optional Metrics registry; per-stage histograms
    (bls_pool_pack_seconds / bls_pool_dispatch_seconds is pool-side /
    bls_pool_final_exp_seconds) are observed when present.  The plain
    ``stage_seconds`` dict accumulates the same figures unconditionally.
    """

    def __init__(
        self,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        platform: Optional[str] = None,
        devices: Optional[Sequence] = None,
        host_final_exp: bool = True,
        fused: Optional[bool] = None,
        metrics=None,
    ):
        self.buckets = tuple(sorted(buckets))
        self.platform = platform
        self.devices = list(devices) if devices else None
        self.host_final_exp = host_final_exp
        # round-5: the fused Pallas kernel path (ops/fused_verify) — the
        # production dispatch on TPU; resolved lazily so constructing a
        # verifier never touches a JAX backend.
        self.fused = fused
        self.metrics = metrics
        self._compiled = {}
        # pool-style counters (metrics parity with blsThreadPool.*,
        # metrics/metrics/lodestar.ts:385)
        self.dispatches = 0
        self.sets_verified = 0
        self.padding_wasted = 0
        self.host_final_exps = 0
        self.fused_fallbacks = 0
        self.stage_seconds = {"pack": 0.0, "dispatch": 0.0, "final_exp": 0.0, "warmup": 0.0}

    # -- compilation cache ---------------------------------------------------

    def _resolve_fused(self) -> bool:
        if self.fused is None:
            self.fused = _fused_default()
        return self.fused

    def _kernel(self, key):
        """Python kernel callable for a (n, host_final_exp, fused) key."""
        n, host_final_exp, fused = key
        if fused:
            from ...ops import fused_verify as fv

            if host_final_exp:
                def kernel(*args):
                    f, ok = fv.miller_product_fused(*args, interpret=False)
                    return f.a, ok
            else:
                def kernel(*args):
                    return fv.verify_signature_sets_fused(*args, interpret=False)
            return kernel
        return (
            bv.miller_product_kernel if host_final_exp
            else bv.verify_signature_sets_kernel
        )

    def _jit(self, key):
        import jax

        n = key[0]
        kernel = self._kernel(key)
        if self.devices and len(self.devices) > 1 and n % len(self.devices) == 0:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            # the multi-device dispatch stays on the XLA-graph kernels:
            # the batch axis shards cleanly there, while the fused
            # path's merged ladders are single-chip programs
            kernel = self._kernel((n, key[1], False))
            mesh = Mesh(np.array(self.devices), ("sets",))
            batch = NamedSharding(mesh, PartitionSpec("sets"))
            return jax.jit(kernel, in_shardings=(batch,) * 7)
        if self.platform is not None:
            device = jax.devices(self.platform)[0]
            return jax.jit(kernel, device=device)
        return jax.jit(kernel)

    def _fn(self, n: int, fused: Optional[bool] = None):
        key = (n, self.host_final_exp, self._resolve_fused() if fused is None else fused)
        if key not in self._compiled:
            self._compiled[key] = self._jit(key)
        return self._compiled[key]

    def _abstract_args(self, n: int):
        """ShapeDtypeStructs matching pack() output — AOT lowering inputs."""
        import jax
        import jax.numpy as jnp

        S = jax.ShapeDtypeStruct
        f32 = jnp.float32
        return (
            S((n, fl.NLIMBS), f32),
            S((n, fl.NLIMBS), f32),
            S((n, 2, fl.NLIMBS), f32),
            S((n, 2, fl.NLIMBS), f32),
            S((n, 2, 2, fl.NLIMBS), f32),
            S((n, 64), f32),
            S((n,), jnp.bool_),
        )

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> float:
        """AOT-compile the dispatch program for every bucket of the active
        path (``jit(...).lower(...).compile()``), populating both the
        in-process executable cache and the persistent compilation cache.

        Returns the wall seconds spent.  A bucket whose compile FAILS
        (e.g. a Mosaic lowering bug in the fused path) degrades that
        verifier to the XLA-graph kernels instead of raising — the node
        must come up either way."""
        t0 = time.perf_counter()
        for b in tuple(buckets if buckets is not None else self.buckets):
            key = (b, self.host_final_exp, self._resolve_fused())
            if key in self._compiled and not hasattr(self._compiled[key], "lower"):
                continue  # already an AOT executable
            try:
                self._compiled[key] = self._jit(key).lower(
                    *self._abstract_args(b)
                ).compile()
            except Exception as e:  # noqa: BLE001
                logger.warning("warmup compile failed for bucket %d: %s", b, e)
                if self.fused:
                    logger.warning("degrading to XLA-graph kernels (fused=False)")
                    self.fused = False
                    self.fused_fallbacks += 1
                    self._compiled.pop(key, None)
                    return self.warmup(buckets) + (time.perf_counter() - t0)
        dt = time.perf_counter() - t0
        self.stage_seconds["warmup"] += dt
        if TRACER.enabled:
            TRACER.instant("bls.warmup_done", cat="bls", seconds=round(dt, 3))
        return dt

    def warmup_async(self, buckets: Optional[Sequence[int]] = None) -> threading.Thread:
        """warmup() on a daemon thread — lets a node serve imports through
        the (slow but correct) cold path while programs compile."""
        t = threading.Thread(target=self.warmup, args=(buckets,), daemon=True,
                             name="tpu-bls-warmup")
        t.start()
        return t

    def _host_final_exp_verdict(self, f_digits, ok) -> bool:
        """Reduce the device Miller product to canonical bytes and run the
        final exponentiation + is-one check on the host (native C first,
        bigint oracle as fallback).  The ``bool(ok)`` read is the device
        sync point, so this stage's timing covers readback + final exp."""
        t0 = time.perf_counter()
        t0_ns = TRACER.now()
        try:
            if not bool(ok):
                return False
            self.host_final_exps += 1
            f = np.asarray(f_digits, dtype=np.float64)  # (6, 2, 50)
            comps = []
            for i in range(6):
                for j in range(2):
                    comps.append(fl.limbs_to_int(f[i, j]) % fl.P_INT)
            blob = b"".join(c.to_bytes(48, "big") for c in comps)
            from ...native import fastbls

            out = fastbls.final_exp_is_one(blob)
            if out is not None:
                return bool(out)
            # oracle fallback: same verdict via bigint final exponentiation
            from .fields import Fq2, Fq6, Fq12
            from .pairing import final_exponentiation

            fq12 = Fq12(
                Fq6(Fq2(*comps[0:2]), Fq2(*comps[2:4]), Fq2(*comps[4:6])),
                Fq6(Fq2(*comps[6:8]), Fq2(*comps[8:10]), Fq2(*comps[10:12])),
            )
            return final_exponentiation(fq12).is_one()
        finally:
            dt = time.perf_counter() - t0
            self.stage_seconds["final_exp"] += dt
            if self.metrics:
                self.metrics.bls_pool_final_exp_seconds.observe(dt)
            if TRACER.enabled:
                TRACER.add_span("bls.final_exp", "bls", t0_ns,
                                cid=current_batch_id())

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # -- IBlsVerifier --------------------------------------------------------

    def verify_signature_sets(self, sets: Sequence[SignatureSet]) -> bool:
        return self.verify_signature_sets_async(sets).result()

    def verify_signature_sets_async(
        self, sets: Sequence[SignatureSet]
    ) -> PendingVerdict:
        """Pack + enqueue without waiting for the device: the returned
        handle's ``result()`` is the only sync.  Oversized batches chunk
        at the largest bucket with every chunk enqueued back-to-back, so
        chunk N+1's pack overlaps chunk N's device time even on the
        single-caller path."""
        if not sets:
            return PendingVerdict(value=False)
        largest = self.buckets[-1]
        if len(sets) > largest:
            # split oversized batches (chunkify analog, multithread/utils.ts:4)
            parts = [
                self.verify_signature_sets_async(sets[i : i + largest])
                for i in range(0, len(sets), largest)
            ]
            return PendingVerdict(parts=parts)
        packed = self.pack(sets)
        if packed is None:
            return PendingVerdict(value=False)  # malformed bytes / infinity
        return self.dispatch(packed)

    def dispatch(self, packed) -> PendingVerdict:
        """Enqueue one packed batch on the device — returns immediately
        (the jax dispatch is asynchronous; compile, if cold, is not).

        A compile failure on the fused path (Mosaic lowering) degrades
        this verifier to the XLA-graph kernels and retries once — a bad
        kernel must not take block import down with it."""
        self.dispatches += 1
        self.sets_verified += int(np.sum(np.asarray(packed[6])))
        n = packed[0].shape[0]
        t0_ns = TRACER.now()
        # snapshot the path THIS call uses: a concurrent warmup_async thread
        # may degrade self.fused mid-flight, and the except arm must judge
        # the path that actually raised, not the flag's latest value
        used_fused = self._resolve_fused()
        try:
            out = self._fn(n, fused=used_fused)(*packed)
        except Exception as e:  # noqa: BLE001
            if not used_fused:
                raise
            logger.warning("fused dispatch failed (%s); degrading to XLA kernels", e)
            self.fused = False
            self.fused_fallbacks += 1
            out = self._fn(n, fused=False)(*packed)
        if TRACER.enabled:
            # covers the async enqueue only (plus compile when cold); the
            # device compute itself surfaces as the gap before final_exp
            TRACER.add_span("bls.dispatch", "bls", t0_ns,
                            cid=current_batch_id(), bucket=n, fused=used_fused)
        if self.host_final_exp:
            f, ok = out
            return PendingVerdict(verifier=self, f=f, ok=ok)
        return PendingVerdict(verifier=self, out=out)

    def close(self) -> None:
        self._compiled.clear()

    # -- packing -------------------------------------------------------------

    def pack(self, sets: Sequence[SignatureSet]):
        """Host packing stage, numpy-vectorized: ONE bulk byte->limb
        conversion per coordinate family (ops/limbs.ints_to_limbs) and a
        vectorized RLC bit expansion instead of per-element/per-bit Python
        loops.  Returns the 7-tuple of device-ready arrays, or None when
        any set is malformed (infinity pubkey/signature, bad bytes)."""
        t0 = time.perf_counter()
        t0_ns = TRACER.now()
        try:
            n = len(sets)
            b = self._bucket(n)
            self.padding_wasted += b - n
            pk_ints: List[int] = []
            sig_ints: List[int] = []
            msgs: List[bytes] = []
            for s in sets:
                pk = get_aggregated_pubkey(s)
                if pk.is_infinity():
                    return None
                try:
                    # on-curve guaranteed by sqrt decompression; subgroup
                    # check happens on device (batched)
                    sig_pt = g2_from_bytes(s.signature, subgroup_check=False)
                except ValueError:
                    return None
                if sig_pt.is_infinity():
                    return None
                pk_aff = pk.point.to_affine()
                sig_aff = sig_pt.to_affine()
                pk_ints += [pk_aff[0].n, pk_aff[1].n]
                sig_ints += [
                    sig_aff[0].c0, sig_aff[0].c1, sig_aff[1].c0, sig_aff[1].c1
                ]
                msgs.append(s.signing_root)
            # one batched byte->limb conversion per family
            pk_limbs = fl.ints_to_limbs(pk_ints).reshape(n, 2, fl.NLIMBS)
            sig_limbs = fl.ints_to_limbs(sig_ints).reshape(n, 2, 2, fl.NLIMBS)
            pk_x = np.zeros((b, fl.NLIMBS), dtype=fl.NP_DTYPE)
            pk_y = np.zeros((b, fl.NLIMBS), dtype=fl.NP_DTYPE)
            sig_x = np.zeros((b, 2, fl.NLIMBS), dtype=fl.NP_DTYPE)
            sig_y = np.zeros((b, 2, fl.NLIMBS), dtype=fl.NP_DTYPE)
            pk_x[:n], pk_y[:n] = pk_limbs[:, 0], pk_limbs[:, 1]
            sig_x[:n], sig_y[:n] = sig_limbs[:, 0], sig_limbs[:, 1]
            # padding lanes: copy lane 0 (valid coords keep the algebra
            # non-degenerate; the mask keeps them out of the verdict)
            if b > n:
                pk_x[n:], pk_y[n:] = pk_x[0], pk_y[0]
                sig_x[n:], sig_y[n:] = sig_x[0], sig_y[0]
                msgs += [b""] * (b - n)
            msg_u = htc.hash_to_field_limbs(msgs)
            # fresh odd 64-bit RLC coefficients, expanded to bit planes in
            # one vectorized shift instead of a per-(coeff, bit) Python loop
            coeffs = np.frombuffer(secrets.token_bytes(8 * b), dtype=np.uint64)
            coeffs = coeffs | np.uint64(1)
            bits = (
                (coeffs[:, None] >> np.arange(64, dtype=np.uint64)[None, :])
                & np.uint64(1)
            ).astype(fl.NP_DTYPE)
            mask = np.zeros(b, dtype=bool)
            mask[:n] = True
            return (pk_x, pk_y, sig_x, sig_y, msg_u, bits, mask)
        finally:
            dt = time.perf_counter() - t0
            self.stage_seconds["pack"] += dt
            if self.metrics:
                self.metrics.bls_pool_pack_seconds.observe(dt)
            if TRACER.enabled:
                TRACER.add_span("bls.pack", "bls", t0_ns,
                                cid=current_batch_id(), sets=len(sets))

    # kept for callers/tests that used the private name
    _pack = pack
